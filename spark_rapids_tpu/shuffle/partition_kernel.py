"""Fused partition-reorder kernel: the accelerated map side of the device
shuffle (GpuPartitioning.scala:44-75 contiguousSplit + Table.partition role).

Round 3 reordered batches with a global variadic sort (3.8 GB/s on this
chip). This module does it in ONE streaming HBM pass with a Pallas kernel:

  pack     columns -> one (rows, L) byte matrix (XLA; u32 bitcasts fuse into
           the concatenate — f64 uses upload-time bit siblings or an exact
           three-float32 expansion, see below)
  kernel   per 512-row window: partition ranks from a constant triangular
           int8 matrix batched across the group in one wide MXU dot, then a
           stacked one-hot int8 dot spreads the window's rows into
           per-partition segments appended to quota-padded per-(group,
           partition) staging blocks (25+ GB/s measured on chip)
  pieces   per (group, partition) quota block + live-count sidecars;
           `consolidate` block-gathers each partition's full 8-row blocks
           plus a tiny row-gather of the per-group remainders into one
           ordinary DeviceBatch (shuffles do not promise intra-partition
           row order)

Backend constraints discovered by probing (experiments/pallas_probe.py):
cumsum/sort/gather do not lower in Mosaic TC kernels; the X64 rewriter
cannot lower any 64-bit-element bitcast (f64->u64, i64->u32, signbit,
frexp); f64 ARITHMETIC is ~49-bit sloppy while f64 STORAGE is true 64-bit;
u64->f64 bitcast (the decode direction) works; unaligned uint8 dynamic
stores crash Mosaic (int32 ones do not). The design routes around each:
integers split to u32 by exact shifts, doubles ride as upload-time u64 bit
siblings (decode is the working bitcast direction) or as an exact hi/mid/lo
float32 expansion validated by an in-program flag, and segment appends use
32-aligned stores with a blended boundary tile.

Fallback: any overflow (quota or per-window) or f64-expansion inexactness
flags the batch back to the sort path — correctness never depends on the
fast path applying.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.dtypes import DType, Schema, bucket_capacity

W = 512                    #: window rows (one spread dot per window)
GROUP_WINDOWS = 64         #: windows per group (one output piece set each)
BLOCK = 8                  #: consolidation block rows
MAX_PARTS = 32             #: wider fan-outs fall back to the sort path


# ------------------------------------------------------------------ pack spec
@dataclass(frozen=True)
class _ColPlan:
    dtype: DType
    kind: str          # u32x1 | u32x2 | f64bits | f64split3 | u8 | string
    lane: int          # first byte lane of the data bytes
    nbytes: int        # data byte lanes
    smax: int = 0      # string byte width


@dataclass(frozen=True)
class PackSpec:
    """Byte-matrix layout for one batch schema: per-column data lanes, then
    one validity byte lane per column (order: all data, then validities)."""
    plans: Tuple[_ColPlan, ...]
    lanes: int

    @staticmethod
    def for_batch(batch: DeviceBatch) -> Optional["PackSpec"]:
        plans: List[_ColPlan] = []
        lane = 0
        for f, c in zip(batch.schema, batch.columns):
            dt = f.dtype
            if dt is DType.STRING:
                smax = c.data.shape[1]
                plans.append(_ColPlan(dt, "string", lane, smax + 4, smax))
                lane += smax + 4
            elif dt is DType.DOUBLE:
                if getattr(c, "bits", None) is not None:
                    plans.append(_ColPlan(dt, "f64bits", lane, 8))
                    lane += 8
                else:
                    plans.append(_ColPlan(dt, "f64split3", lane, 12))
                    lane += 12
            elif dt in (DType.LONG, DType.TIMESTAMP):
                plans.append(_ColPlan(dt, "u32x2", lane, 8))
                lane += 8
            elif dt in (DType.INT, DType.DATE, DType.FLOAT):
                plans.append(_ColPlan(dt, "u32x1", lane, 4))
                lane += 4
            elif dt in (DType.BOOLEAN, DType.BYTE):
                plans.append(_ColPlan(dt, "u8", lane, 1))
                lane += 1
            elif dt is DType.SHORT:
                plans.append(_ColPlan(dt, "u32x1", lane, 4))
                lane += 4
            else:
                return None                       # NULL etc: sort path
        return PackSpec(tuple(plans), lane + len(plans))


def _u32_bytes(a) -> "jax.Array":
    return jax.lax.bitcast_convert_type(a.astype(jnp.uint32), jnp.uint8)


def _split3(x):
    """Exact three-float32 expansion of device f64 (device arithmetic holds
    ~48 significand bits, so hi+mid+lo is exact for every device-COMPUTED
    value; `ok` is False for full-precision host-uploaded doubles, which
    carry bit siblings instead)."""
    hi = x.astype(jnp.float32)
    r1 = x - hi.astype(jnp.float64)
    mid = r1.astype(jnp.float32)
    lo = (r1 - mid.astype(jnp.float64)).astype(jnp.float32)
    rec = (hi.astype(jnp.float64) + mid.astype(jnp.float64)) \
        + lo.astype(jnp.float64)
    ok = jnp.all(jnp.where(jnp.isnan(x), jnp.isnan(rec), rec == x))
    return hi, mid, lo, ok


def pack_matrix(spec: PackSpec, batch_cols: Sequence, validities: Sequence):
    """Columns -> ((rows, L) u8 matrix, exactness_ok scalar). Runs inside
    the caller's jit; every bitcast/shift fuses into the one concatenate."""
    pieces = []
    ok = jnp.bool_(True)
    for plan, c in zip(spec.plans, batch_cols):
        if plan.kind == "string":
            pieces.append(c.data)
            pieces.append(_u32_bytes(c.lengths))
        elif plan.kind == "f64bits":
            bits = c.bits
            pieces.append(_u32_bytes(bits & np.uint64(0xFFFFFFFF)))
            pieces.append(_u32_bytes(bits >> np.uint64(32)))
        elif plan.kind == "f64split3":
            hi, mid, lo, good = _split3(c.data)
            ok = jnp.logical_and(ok, good)
            for part in (hi, mid, lo):
                pieces.append(_u32_bytes(
                    jax.lax.bitcast_convert_type(part, jnp.uint32)))
        elif plan.kind == "u32x2":
            x = c.data.astype(jnp.int64)
            pieces.append(_u32_bytes(x & np.int64(0xFFFFFFFF)))
            pieces.append(_u32_bytes(jnp.right_shift(x, np.int64(32))))
        elif plan.kind == "u32x1":
            if c.data.dtype == jnp.float32:
                pieces.append(_u32_bytes(
                    jax.lax.bitcast_convert_type(c.data, jnp.uint32)))
            else:
                pieces.append(_u32_bytes(c.data.astype(jnp.int64)
                                         & np.int64(0xFFFFFFFF)))
        elif plan.kind == "u8":
            pieces.append(c.data.astype(jnp.uint8)[:, None])
        else:
            raise AssertionError(plan.kind)
    for v in validities:
        pieces.append(v.astype(jnp.uint8)[:, None])
    return jnp.concatenate(pieces, axis=1), ok


def unpack_columns(spec: PackSpec, schema: Schema, mat) -> List[DeviceColumn]:
    """(rows, L) u8 matrix -> DeviceColumns (decode side; u64->f64 bitcast
    is the direction this backend supports)."""
    def u32(lane):
        # arithmetic byte assembly, NOT bitcast_convert_type: bitcasting a
        # lane SLICE of a u8 matrix silently zeroes low nibbles on this
        # backend (pack's u32->u8 direction is fine and stays a bitcast)
        b = [mat[:, lane + k].astype(jnp.uint32) for k in range(4)]
        return (b[0] | (b[1] << np.uint32(8)) | (b[2] << np.uint32(16))
                | (b[3] << np.uint32(24)))

    def u64(lane):
        lo = u32(lane).astype(jnp.uint64)
        hi = u32(lane + 4).astype(jnp.uint64)
        return lo | (hi << np.uint64(32))

    nvals = len(spec.plans)
    cols: List[DeviceColumn] = []
    for i, (plan, f) in enumerate(zip(spec.plans, schema)):
        validity = mat[:, spec.lanes - nvals + i] != 0
        if plan.kind == "string":
            data = mat[:, plan.lane:plan.lane + plan.smax]
            lengths = u32(plan.lane + plan.smax).astype(jnp.int32)
            cols.append(DeviceColumn(f.dtype, data, validity, lengths))
            continue
        if plan.kind == "f64bits":
            data = jax.lax.bitcast_convert_type(u64(plan.lane), jnp.float64)
        elif plan.kind == "f64split3":
            hi = jax.lax.bitcast_convert_type(u32(plan.lane), jnp.float32)
            mid = jax.lax.bitcast_convert_type(u32(plan.lane + 4),
                                               jnp.float32)
            lo = jax.lax.bitcast_convert_type(u32(plan.lane + 8),
                                              jnp.float32)
            data = (hi.astype(jnp.float64) + mid.astype(jnp.float64)) \
                + lo.astype(jnp.float64)
        elif plan.kind == "u32x2":
            data = u64(plan.lane).astype(jnp.int64)
            if f.dtype is DType.TIMESTAMP:
                data = data.astype(jnp.int64)
        elif plan.kind == "u32x1":
            raw = u32(plan.lane)
            if f.dtype is DType.FLOAT:
                data = jax.lax.bitcast_convert_type(raw, jnp.float32)
            else:
                data = raw.astype(jnp.int32)
        elif plan.kind == "u8":
            raw = mat[:, plan.lane]
            data = (raw != 0) if f.dtype is DType.BOOLEAN \
                else raw.astype(jnp.int8)
        else:
            raise AssertionError(plan.kind)
        if plan.kind == "f64bits":
            col = DeviceColumn(f.dtype, data, validity)
            object.__setattr__(col, "bits", u64(plan.lane))
            cols.append(col)
        else:
            cols.append(DeviceColumn(f.dtype, data, validity))
    return cols


# ------------------------------------------------------------------ geometry
@dataclass(frozen=True)
class KernelGeom:
    cap: int          # padded row count = groups * G * W
    groups: int
    G: int
    n: int
    q_w: int          # per-window per-partition segment bound
    quota: int        # per-(group, partition) piece rows
    L: int

    @staticmethod
    def plan(rows: int, n: int, L: int) -> "KernelGeom":
        G = min(GROUP_WINDOWS, max(1, math.ceil(rows / W)))
        gw = G * W
        groups = max(1, math.ceil(rows / gw))
        cap = groups * gw
        q_w = min(W, max(64, 2 * math.ceil(W / n)))
        q_w = (q_w + 7) // 8 * 8
        seg = q_w + 32
        quota = max(seg + 32,
                    math.ceil(1.25 * gw / n))
        quota = (quota + 511) // 512 * 512
        return KernelGeom(cap, groups, G, n, q_w, quota, L)


def padded_lanes(L: int) -> int:
    """Staging-buffer lane width: 128-multiple so the DMA consolidation can
    copy pieces whole (Mosaic lane tiling) without a separate pad pass."""
    return -(-L // 128) * 128


def _make_kernel(geom: KernelGeom):
    G, n, q_w, quota, L = (geom.G, geom.n, geom.q_w, geom.quota, geom.L)
    wn = geom.cap // W
    groups = geom.groups
    seg_rows = q_w + 32
    # Mosaic requires dynamic-slice offsets in dim 0 provably 8-aligned:
    # wg * n is only provable when n is a multiple of 8, so the per-window
    # running-count matrix pads its partition rows (pids never reach the
    # padding, so the extra rows stay zero and drop out of the rank sum)
    n_pad = (n + 7) // 8 * 8

    def kernel(pid_ref, data_ref, out_ref, cnt_ref, run_ref, cs_ref):
        # 2D grid (group, window-in-group): index maps stay arithmetic-free
        # (any jnp arithmetic on grid indices under jax_enable_x64 either
        # recurses in dtype promotion or fails Mosaic legalization)
        wg = pl.program_id(1)

        @pl.when(wg == np.int32(0))
        def _prepass():
            # inclusive running per-partition counts for EVERY window of the
            # group in one wide dot (a narrow n-lane dot per window would
            # waste the MXU's 128 output lanes); cumsum does not lower
            r_i = jax.lax.broadcasted_iota(jnp.int32, (W, W), 0)
            c_i = jax.lax.broadcasted_iota(jnp.int32, (W, W), 1)
            tri = (c_i <= r_i).astype(jnp.int8)
            pids = pid_ref[0]                       # (G, W)
            jj = jax.lax.broadcasted_iota(jnp.int32, (G, n_pad, W), 1)
            m = (pids[:, None, :] == jj).astype(jnp.int8)
            m2 = m.reshape(G * n_pad, W)
            cs = jax.lax.dot_general(m2, tri, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.int32)
            cs_ref[:] = cs
            for j in range(n):
                # pinned: a weak 0 traces as int64 under jax_enable_x64 and
                # the interpret-mode ref store rejects the dtype mismatch
                run_ref[j] = jnp.int32(0)
            cnt_ref[...] = jnp.zeros((1, n, 128), jnp.int32)

        p = pid_ref[0, wg, :]
        d8 = data_ref[0].astype(jnp.int8)
        # (n_pad, W) inclusive counts; offset wg*n_pad is 8-aligned
        cs_w = cs_ref[pl.ds(wg * np.int32(n_pad), n_pad), :]
        rank = jnp.sum(jnp.where(p[None, :] ==
                                 jax.lax.broadcasted_iota(
                                     jnp.int32, (n_pad, W), 0),
                                 cs_w, np.int32(0)),
                       axis=0, dtype=jnp.int32) - np.int32(1)
        base_max = np.int32((quota - seg_rows) // 32 * 32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (n * seg_rows, W), 0)
        stack = jnp.full((W,), -1, jnp.int32)
        bases, offs, cnts = [], [], []
        for j in range(n):
            run = run_ref[j]
            base = jnp.minimum((run // np.int32(32)) * np.int32(32),
                               base_max)
            off = run - base
            bases.append(base)
            offs.append(off)
            cnts.append(cs_w[j, W - 1])
            stack = jnp.where(p == np.int32(j),
                              rank + off + np.int32(j * seg_rows), stack)
        oh = (rows == stack[None, :]).astype(jnp.int8)
        segs = jax.lax.dot_general(oh, d8, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.int32)
        segs = (segs & 255).astype(jnp.uint8)

        ovf = jnp.int32(0)
        for j in range(n):
            seg = segs[j * seg_rows:(j + 1) * seg_rows, :]
            # u8 dynamic stores must be 32-aligned on this backend: write at
            # the aligned floor (the one-hot already shifted rows by the
            # residue) and blend the first tile with rows appended earlier
            bb = pl.multiple_of(bases[j], 32)
            old = out_ref[j, 0, pl.ds(bb, 32), :]
            head = jax.lax.broadcasted_iota(jnp.int32, (32, 1), 0) < offs[j]
            seg = jnp.concatenate(
                [jnp.where(head, old, seg[:32]), seg[32:]], axis=0)
            out_ref[j, 0, pl.ds(bb, seg_rows), :] = seg
            over = jnp.logical_or(
                cnts[j] > np.int32(q_w),
                run_ref[j] + cnts[j] > np.int32(quota - seg_rows))
            ovf = jnp.where(over, jnp.int32(1), ovf)
            run_ref[j] = run_ref[j] + cnts[j]

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, n, 128), 2)

        @pl.when(wg == np.int32(G - 1))
        def _publish():
            counts = jnp.stack([run_ref[j] for j in range(n)])
            stats = jnp.where(lane == np.int32(0), counts[None, :, None],
                              jnp.where(lane == np.int32(1), ovf,
                                        np.int32(0)))
            cnt_ref[...] = jnp.maximum(stats, cnt_ref[...])

        @pl.when(jnp.logical_and(ovf > np.int32(0),
                                 wg < np.int32(G - 1)))
        def _early_ovf():
            cnt_ref[...] = jnp.maximum(
                cnt_ref[...],
                jnp.where(lane == np.int32(1), np.int32(1), np.int32(0)))

    out_shapes = (
        jax.ShapeDtypeStruct((n, groups, quota, L), jnp.uint8),
        jax.ShapeDtypeStruct((groups, n, 128), jnp.int32),
    )
    # index-map literals pinned to int32: weak-typed 0s trace as int64
    # under jax_enable_x64 and the Mosaic func.return cannot legalize them
    z = np.int32(0)
    grid = (groups, G)
    in_specs = [
        pl.BlockSpec((1, G, W), lambda g, wg: (g, z, z),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, W, L), lambda g, wg: (g, wg, z),
                     memory_space=pltpu.VMEM),
    ]
    out_specs = (
        pl.BlockSpec((n, 1, quota, L), lambda g, wg: (z, g, z, z),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n, 128), lambda g, wg: (g, z, z),
                     memory_space=pltpu.VMEM),
    )

    def run(pid2d, data, interpret=False):
        return pl.pallas_call(
            kernel, out_shape=out_shapes, grid=grid,
            in_specs=in_specs, out_specs=out_specs,
            scratch_shapes=[pltpu.SMEM((n,), jnp.int32),
                            pltpu.VMEM((G * n_pad, W), jnp.int32)],
            interpret=interpret,
        )(pid2d.reshape(groups, G, W),
          data.reshape(groups, G * W, L))
    return run


# ------------------------------------------------------------------ driver
def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


_PROGRAMS: dict = {}


def reorder_program(spec: PackSpec, geom: KernelGeom, cap: int,
                    interpret: bool):
    """The cached pack+kernel jit: fn(num_rows, pids, *flat) ->
    (out, stats, pack_exact_ok). ``flat`` is `_deflate` order."""
    key = ("pkern", spec, geom, cap, interpret)
    fn = _PROGRAMS.get(key)
    if fn is not None:
        return fn
    kern = _make_kernel(geom)

    def fn(num_rows, pids, *flat):
        cols = _reflate(spec, flat)
        mat, ok = _pack(spec, cols)
        # materialize the packed matrix as-is before it feeds the Pallas
        # custom call: letting XLA fuse the bitcast/concatenate chain into
        # the operand zeroes low nibbles of some lanes on this backend
        mat = jax.lax.optimization_barrier(mat)
        cap_in = mat.shape[0]
        live = jnp.arange(cap_in, dtype=jnp.int32) < num_rows
        pids2 = jnp.where(live, pids, np.int32(-1))
        pad = geom.cap - cap_in
        if pad:
            mat = jnp.concatenate(
                [mat, jnp.zeros((pad, geom.L), jnp.uint8)], axis=0)
            pids2 = jnp.concatenate(
                [pids2, jnp.full((pad,), -1, jnp.int32)])
        out, stats = kern(pids2.reshape(geom.cap // W, W), mat,
                          interpret=interpret)
        # one SMALL host download serves counts + overflow + pack-ok: the
        # tunnel round trip dominates, so ship a compact summary vector
        # [ok, counts(groups*n), ovf_max] instead of the padded stats block
        counts = stats[:, :, 0].reshape(-1)
        ovf = jnp.max(stats[:, :, 1])
        summary = jnp.concatenate(
            [ok.astype(jnp.int32)[None], counts,
             ovf.astype(jnp.int32)[None]])
        return out, summary

    fn = jax.jit(fn)
    _PROGRAMS[key] = fn
    return fn


def split_batch_kernel(batch: DeviceBatch, pids, n: int,
                       interpret: Optional[bool] = None):
    """Run pack+kernel for one batch. Returns (out, stats_host, spec, geom)
    or None when the batch/partitioning is outside the fast path's envelope
    (caller falls back to the sort path)."""
    if n < 2 or n > MAX_PARTS:
        return None
    spec = PackSpec.for_batch(batch)
    if spec is None:
        return None
    geom = KernelGeom.plan(batch.capacity, n, spec.lanes)
    if interpret is None:
        interpret = _use_interpret()
    fn = reorder_program(spec, geom, batch.capacity, interpret)
    out, summary = fn(np.int32(batch.num_rows), pids,
                      *_deflate(spec, batch))
    return finalize_split(out, summary, spec, geom)


def finalize_split(out, summary, spec: PackSpec, geom: KernelGeom):
    """Unpack the compact summary vector of a reorder run into host stats.
    Returns (out, stats_host, spec, geom) or None on pack-inexact/overflow
    (caller falls back to the sort path). Shared by the standalone kernel
    entry above and the engine's fused pids+pack+kernel program
    (execs/exchange_execs.py _kernel_split)."""
    summary = np.asarray(summary)          # ONE small host round trip
    ok, counts, ovf = summary[0], summary[1:-1], summary[-1]
    if not ok or ovf > 0:
        return None                    # inexact f64 expansion or overflow
    stats_host = np.zeros((geom.groups, geom.n, 2), np.int32)
    stats_host[:, :, 0] = counts.reshape(geom.groups, geom.n)
    return out, stats_host, spec, geom


def _deflate(spec: PackSpec, batch: DeviceBatch) -> List:
    flat: List = []
    for plan, c in zip(spec.plans, batch.columns):
        if plan.kind == "f64bits":
            flat.append(c.bits)
        else:
            flat.append(c.data)
        flat.append(c.validity)
        if plan.kind == "string":
            flat.append(c.lengths)
    return flat


class _PackCol:
    __slots__ = ("data", "bits", "validity", "lengths")

    def __init__(self, data, bits, validity, lengths):
        self.data = data
        self.bits = bits
        self.validity = validity
        self.lengths = lengths


def _reflate(spec: PackSpec, flat) -> List[_PackCol]:
    cols = []
    i = 0
    for plan in spec.plans:
        main = flat[i]
        validity = flat[i + 1]
        i += 2
        lengths = None
        if plan.kind == "string":
            lengths = flat[i]
            i += 1
        if plan.kind == "f64bits":
            cols.append(_PackCol(None, main, validity, lengths))
        else:
            cols.append(_PackCol(main, None, validity, lengths))
    return cols


def _pack(spec: PackSpec, cols: Sequence[_PackCol]):
    return pack_matrix(spec, cols, [c.validity for c in cols])


def consolidate_all(out, stats_host: np.ndarray, spec: PackSpec,
                    schema: Schema, geom: KernelGeom
                    ) -> Optional[List[Optional[DeviceBatch]]]:
    """EVERY partition's quota-padded pieces -> per-partition DeviceBatches
    via ONE pipelined-DMA compaction (round-4 perf-notes "next lever"):

    - grid (group, partition), partition innermost: consecutive steps hit
      DISJOINT destination slices, so n DMA copies ride in flight at once;
      a per-partition semaphore orders the only overlapping pair — group
      g's copy overwrites group g-1's padding tail within one partition.
    - remainder rows (< BLOCK per group; a few hundred rows total) are
      pre-gathered into a packed block and DMA'd at the 8-aligned full-
      block boundary as the grid's final step, so the compact is COMPLETE
      when the program returns.
    - the unpack then reads the materialized pallas output directly — no
      optimization barrier, no second full materialization (the barrier in
      `consolidate` exists because fusing a take() gather into the lane
      extraction corrupts lanes; a pallas output has no such fusion).

    TPU-only (DMA semantics); returns None to send the caller down the
    per-partition `consolidate` path (CPU tests, interpret mode)."""
    if jax.default_backend() != "tpu":
        return None
    counts = stats_host[:, :, 0].astype(np.int64)       # [groups, n]
    totals = counts.sum(axis=0)                         # [n]
    if totals.max(initial=0) == 0:
        return [None] * geom.n
    prefix8, nb8, ridx, ri_cap, dst_rows = dma_index_plan(counts, geom)

    key = ("pdma", spec, geom, ri_cap, dst_rows)
    fn = _PROGRAMS.get(key)
    if fn is None:
        fn = jax.jit(_build_dma_compact(spec, geom, ri_cap, dst_rows))
        _PROGRAMS[key] = fn
    compact = fn(jnp.asarray(prefix8), jnp.asarray(nb8),
                 jnp.asarray(ridx), out)

    batches: List[Optional[DeviceBatch]] = []
    for j in range(geom.n):
        total = int(totals[j])
        if total == 0:
            batches.append(None)
            continue
        bucket = int(bucket_capacity(total))
        ukey = ("pdma-unpack", spec, geom.L, bucket, dst_rows,
                tuple(f.dtype for f in schema))
        ufn = _PROGRAMS.get(ukey)
        if ufn is None:
            def build(bucket=bucket):
                def f(compact_j):
                    # the compact is a materialized pallas output: unpack
                    # reads it directly, no optimization barrier needed
                    return _flatten_unpacked(
                        unpack_columns(spec, schema, compact_j[:bucket]))
                return f
            ufn = jax.jit(build())
            _PROGRAMS[ukey] = ufn
        batches.append(_res_to_batch(spec, schema, ufn(compact[j]), total))
    return batches


def dma_index_plan(counts: np.ndarray, geom: KernelGeom):
    """Pure host-side index math for the DMA consolidation (testable off-
    TPU): counts [groups, n] -> (prefix8 [n, groups] 8-aligned destination
    offsets of each group's full-block run, nb8 [n] total full-block rows,
    ridx [n, ri_cap] remainder-row source indices into the flattened
    groups*quota staging rows, ri_cap, dst_rows)."""
    n, groups, quota = geom.n, geom.groups, geom.quota
    totals = counts.sum(axis=0)
    nb = counts // BLOCK
    rem = counts - nb * BLOCK
    nb8 = (nb.sum(axis=0) * BLOCK).astype(np.int32)
    prefix8 = np.zeros((n, groups), np.int32)
    prefix8[:, 1:] = np.cumsum((nb.T * BLOCK)[:, :-1], axis=1)
    ri_cap = int(bucket_capacity(max(1, int(rem.sum(axis=0).max()))))
    ridx = np.zeros((n, ri_cap), np.int32)
    for j in range(n):
        rj = rem[:, j]
        rem_tot = int(rj.sum())
        rgid = np.repeat(np.arange(groups), rj)
        rwithin = np.arange(rem_tot) - np.repeat(np.cumsum(rj) - rj, rj)
        ridx[j, :rem_tot] = (rgid * quota + nb[:, j][rgid] * BLOCK
                             + rwithin).astype(np.int32)
    dst_rows = int(bucket_capacity(int(totals.max()))) + max(quota, ri_cap)
    return prefix8, nb8, ridx, ri_cap, dst_rows


def _build_dma_compact(spec: PackSpec, geom: KernelGeom, ri_cap: int,
                       dst_rows: int):
    """The jitted remainder-gather + pipelined-DMA program builder. Pays
    ONE pad pass to 128 lanes before the DMA (Mosaic lane tiling): padding
    the reorder kernel's staging output instead was tried and REGRESSED
    suite exchanges up to 6x — narrow schemas (L ~ 20) amplified every
    kernel write and consolidation read by Lp/L (round-5 perf-notes)."""
    n, groups, quota, L = geom.n, geom.groups, geom.quota, geom.L
    Lp = padded_lanes(L)

    def compact_fn(prefix8, nb8, ridx, out_arr):
        # pre-gather the (tiny) per-partition remainder rows into one
        # packed block the kernel can DMA whole
        flat = out_arr.reshape(n, groups * quota, L)
        rrows = jnp.take_along_axis(flat, ridx[:, :, None].astype(jnp.int32),
                                    axis=1)
        if Lp != L:
            rrows = jnp.pad(rrows, ((0, 0), (0, 0), (0, Lp - L)))
            src = jnp.pad(out_arr, ((0, 0), (0, 0), (0, 0), (0, Lp - L)))
        else:
            src = out_arr

        def kernel(prefix_ref, nb8_ref, src_ref, rem_ref, dst_ref, sems):
            g = pl.program_id(0)
            j = pl.program_id(1)

            def piece_copy(gv):
                off = pl.multiple_of(prefix_ref[j, gv], 8)
                return pltpu.make_async_copy(
                    src_ref.at[j, gv],
                    dst_ref.at[j, pl.ds(off, quota), :],
                    sems.at[j])

            @pl.when(g == np.int32(0))
            def _first():
                piece_copy(np.int32(0)).start()

            @pl.when(jnp.logical_and(g > np.int32(0),
                                     g < np.int32(groups)))
            def _mid():
                # wait the previous copy of THIS partition before starting
                # the next: group g overwrites g-1's padding tail. Copies
                # of the other n-1 partitions stay in flight meanwhile.
                piece_copy(g - np.int32(1)).wait()
                piece_copy(g).start()

            @pl.when(g == np.int32(groups))
            def _tail():
                piece_copy(np.int32(groups - 1)).wait()
                off8 = pl.multiple_of(nb8_ref[j], 8)
                rc = pltpu.make_async_copy(
                    rem_ref.at[j],
                    dst_ref.at[j, pl.ds(off8, ri_cap), :],
                    sems.at[j])
                rc.start()
                rc.wait()

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(groups + 1, n),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                      pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((n,))])
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, dst_rows, Lp), jnp.uint8),
            grid_spec=grid_spec)(prefix8, nb8, src, rrows)
    return compact_fn


def consolidate(out, stats_host: np.ndarray, j: int, spec: PackSpec,
                schema: Schema, geom: KernelGeom) -> Optional[DeviceBatch]:
    """Partition j's quota-padded pieces -> ONE DeviceBatch: block-gather of
    every full 8-row block plus a row-gather of per-group remainders
    (shuffle makes no intra-partition order promise). Returns None for an
    empty partition.

    The program is SHAPE-STABLE: gather index vectors are padded to
    power-of-two buckets and the partition index rides as data, so one
    compiled program serves every partition of every exchange with this
    geometry — per-exchange counts only change the (tiny) index uploads."""
    counts = stats_host[:, j, 0].astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return None
    quota = geom.quota
    nb = counts // BLOCK
    rem = counts - nb * BLOCK
    qb = quota // BLOCK
    # vectorized index build: block b of group g -> flat block g*qb + b;
    # remainder row r of group g -> flat row g*quota + nb[g]*BLOCK + r
    nb_tot = int(nb.sum())
    gid = np.repeat(np.arange(len(nb)), nb)
    within = np.arange(nb_tot) - np.repeat(np.cumsum(nb) - nb, nb)
    block_idx = (gid * qb + within).astype(np.int32)
    rem_tot = int(rem.sum())
    rgid = np.repeat(np.arange(len(rem)), rem)
    rwithin = np.arange(rem_tot) - np.repeat(np.cumsum(rem) - rem, rem)
    rem_idx = (rgid * quota + nb[rgid] * BLOCK + rwithin).astype(np.int32)

    bucket = bucket_capacity(total)
    bi_cap = bucket_capacity(max(1, nb_tot))
    ri_cap = bucket_capacity(max(1, rem_tot))
    # pad with repeats of slot 0: the gathered garbage rows land beyond the
    # live prefix of the bucketed matrix (positional aliveness masks them)
    bi = np.zeros(bi_cap, np.int32)
    bi[:nb_tot] = block_idx
    ri = np.zeros(ri_cap, np.int32)
    ri[:rem_tot] = rem_idx

    key = ("pconsol", spec, geom, bi_cap, ri_cap, bucket)
    fn = _PROGRAMS.get(key)
    if fn is None:
        def build(bi_cap=bi_cap, ri_cap=ri_cap, bucket=bucket):
            def f(out_arr, jv, nb8, bidx, ridx):
                x = jax.lax.dynamic_index_in_dim(
                    out_arr, jv, axis=0, keepdims=False)
                x = x.reshape(geom.groups * geom.quota, geom.L)
                xb = x.reshape(geom.groups * geom.quota // BLOCK,
                               BLOCK * geom.L)
                full = jnp.take(xb, bidx, axis=0).reshape(
                    bi_cap * BLOCK, geom.L)
                rows = jnp.take(x, ridx, axis=0)
                # contiguity under bucketed index shapes: write the padded
                # full-block region first, then the remainder rows AT the
                # live boundary (nb8 = true full-block rows) — remainder
                # data overwrites the block padding, its own padding tail
                # lands beyond the live prefix
                work = jnp.zeros((bucket + bi_cap * BLOCK + ri_cap,
                                  geom.L), jnp.uint8)
                work = jax.lax.dynamic_update_slice(
                    work, full, (np.int32(0), np.int32(0)))
                work = jax.lax.dynamic_update_slice(
                    work, rows, (nb8, np.int32(0)))
                mat = work[:bucket]
                # materialize before decoding: fusing the gather into the
                # lane extraction corrupts lanes on this backend
                mat = jax.lax.optimization_barrier(mat)
                return _flatten_unpacked(unpack_columns(spec, schema, mat))
            return jax.jit(f)
        fn = build()
        _PROGRAMS[key] = fn

    res = fn(out, np.int32(j), np.int32(nb_tot * BLOCK),
             jnp.asarray(bi), jnp.asarray(ri))
    return _res_to_batch(spec, schema, res, total)


def _flatten_unpacked(cols) -> tuple:
    """DeviceColumns -> the flat jit-output tuple (one layout, shared by
    every consolidation program)."""
    out_flat = []
    for c in cols:
        out_flat.append(c.data)
        out_flat.append(c.validity)
        if c.lengths is not None:
            out_flat.append(c.lengths)
        b = getattr(c, "bits", None)
        if b is not None:
            out_flat.append(b)
    return tuple(out_flat)


def _res_to_batch(spec: PackSpec, schema: Schema, res,
                  total: int) -> DeviceBatch:
    """Flat jit-output tuple -> DeviceBatch (inverse of _flatten_unpacked,
    driven by the same plan kinds)."""
    cols: List[DeviceColumn] = []
    i = 0
    for plan, f in zip(spec.plans, schema):
        data = res[i]
        validity = res[i + 1]
        i += 2
        lengths = None
        if plan.kind == "string":
            lengths = res[i]
            i += 1
        bits = None
        if plan.kind == "f64bits":
            bits = res[i]
            i += 1
        cols.append(DeviceColumn(f.dtype, data, validity, lengths, bits))
    return DeviceBatch(schema, tuple(cols), total)
