"""In-process shuffle transport: tag-matched rendezvous over threads + queues.

Reference analog: the UCX transport (shuffle-plugin ucx/UCX.scala) — a
tag-matching transport with a progress thread, connection handshake, and
registered memory. Here executors are threads in one process (the local-cluster
/ multi-executor-per-host topology and the test transport): sends and receives
meet in a shared tag table (UCX tag-matching analog); completions run on a
dedicated progress thread per endpoint pair, matching the reference's
single-progress-thread model (UCX.scala:70-112). A cross-host DCN transport
implements the same traits over sockets/gRPC.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Optional, Tuple

from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                ClientConnection, Connection,
                                                ServerConnection,
                                                ShuffleTransport, Transaction,
                                                TransactionStatus)


class _TagTable:
    """Shared tag-matching table: whichever of (send, receive) arrives second
    copies the payload and completes both transactions on the progress queue."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending_sends: Dict[Tuple[str, int], Tuple[AddressLengthTag, Transaction]] = {}
        self._pending_recvs: Dict[Tuple[str, int], Tuple[AddressLengthTag, Transaction]] = {}
        self._progress: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._thread = threading.Thread(target=self._run, name="shuffle-progress",
                                        daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            fn = self._progress.get()
            if fn is None:
                return
            fn()

    def _complete_pair(self, salt: Tuple[AddressLengthTag, Transaction],
                       ralt: Tuple[AddressLengthTag, Transaction]):
        (s_alt, s_tx), (r_alt, r_tx) = salt, ralt

        def do():
            n = min(s_alt.length, r_alt.length)
            r_alt.buffer[:n] = s_alt.buffer[:n]
            s_tx.stats.sent_bytes = n
            r_tx.stats.received_bytes = n
            s_tx.complete(TransactionStatus.SUCCESS)
            r_tx.complete(TransactionStatus.SUCCESS)
        self._progress.put(do)

    def send(self, dest: str, alt: AddressLengthTag, tx: Transaction):
        key = (dest, alt.tag)
        with self._lock:
            recv = self._pending_recvs.pop(key, None)
            if recv is None:
                self._pending_sends[key] = (alt, tx)
                return
        self._complete_pair((alt, tx), recv)

    def receive(self, owner: str, alt: AddressLengthTag, tx: Transaction):
        key = (owner, alt.tag)
        with self._lock:
            send = self._pending_sends.pop(key, None)
            if send is None:
                self._pending_recvs[key] = (alt, tx)
                return
        self._complete_pair(send, (alt, tx))

    def shutdown(self):
        self._progress.put(None)


class _Endpoint:
    """One executor's presence in the in-process fabric."""

    def __init__(self, executor_id: str, fabric: "_Fabric"):
        self.executor_id = executor_id
        self.fabric = fabric
        self.handlers: Dict[str, Callable[[str, bytes], bytes]] = {}
        self._rpc_pool = []
        self._rpc_queue: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        for i in range(2):
            t = threading.Thread(target=self._rpc_run,
                                 name=f"shuffle-server-{executor_id}-{i}",
                                 daemon=True)
            t.start()
            self._rpc_pool.append(t)

    def _rpc_run(self):
        while True:
            fn = self._rpc_queue.get()
            if fn is None:
                return
            fn()

    def submit_rpc(self, fn: Callable[[], None]):
        self._rpc_queue.put(fn)

    def shutdown(self):
        for _ in self._rpc_pool:
            self._rpc_queue.put(None)


class _Fabric:
    """Process-wide registry of endpoints + the shared tag table
    (the 'network')."""

    _instance: Optional["_Fabric"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self.endpoints: Dict[str, _Endpoint] = {}
        self.tags = _TagTable()
        self._lock = threading.Lock()
        self._transports: list = []

    @classmethod
    def get(cls) -> "_Fabric":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = _Fabric()
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._ilock:
            if cls._instance is not None:
                cls._instance.tags.shutdown()
                for ep in cls._instance.endpoints.values():
                    ep.shutdown()
            cls._instance = None

    def register(self, executor_id: str) -> _Endpoint:
        with self._lock:
            ep = self.endpoints.get(executor_id)
            if ep is None:
                ep = _Endpoint(executor_id, self)
                self.endpoints[executor_id] = ep
            return ep

    def endpoint(self, executor_id: str) -> _Endpoint:
        with self._lock:
            ep = self.endpoints.get(executor_id)
        if ep is None:
            raise ConnectionError(f"no executor {executor_id!r} on the fabric")
        return ep

    def attach_transport(self, transport: "InProcessTransport") -> None:
        with self._lock:
            self._transports.append(transport)

    def detach_transport(self, transport: "InProcessTransport") -> None:
        with self._lock:
            if transport in self._transports:
                self._transports.remove(transport)

    def kill(self, executor_id: str) -> None:
        """Simulate an executor dying: deregister its endpoint and fire every
        transport's peer-lost listeners (the in-process analog of a TCP
        reader thread hitting a closed socket) — chaos tests use this to
        exercise the evict-and-reconnect path without real sockets."""
        with self._lock:
            ep = self.endpoints.pop(executor_id, None)
            transports = list(self._transports)
        if ep is not None:
            ep.shutdown()
        for t in transports:
            if t.executor_id != executor_id:
                t._drop_client(executor_id)
                t.notify_peer_lost(executor_id)


class InProcessClientConnection(ClientConnection):
    def __init__(self, local: _Endpoint, peer: _Endpoint):
        self._local = local
        self._peer = peer
        self.peer_executor_id = peer.executor_id

    def request(self, req_type: str, payload: bytes,
                cb: Callable[[Transaction], None]) -> Transaction:
        tx = Transaction().start(cb)
        handler = self._peer.handlers.get(req_type)
        if handler is None:
            tx.complete(TransactionStatus.ERROR,
                        f"peer {self.peer_executor_id} has no handler for "
                        f"{req_type!r}")
            return tx
        local_id = self._local.executor_id

        def run():
            try:
                resp = handler(local_id, payload)
            except Exception as e:  # noqa: BLE001 - propagate as transaction error
                tx.response = b""
                tx.complete(TransactionStatus.ERROR, f"{type(e).__name__}: {e}")
                return
            # handler succeeded; a raising completion callback must not
            # re-complete the transaction as a peer error
            tx.response = resp
            tx.stats.received_bytes = len(resp)
            tx.complete(TransactionStatus.SUCCESS)
        self._peer.submit_rpc(run)
        return tx

    def send(self, alt: AddressLengthTag, cb) -> Transaction:
        tx = Transaction(alt.tag).start(cb)
        self._local.fabric.tags.send(self.peer_executor_id, alt, tx)
        return tx

    def receive(self, alt: AddressLengthTag, cb) -> Transaction:
        tx = Transaction(alt.tag).start(cb)
        self._local.fabric.tags.receive(self._local.executor_id, alt, tx)
        return tx


class InProcessServerConnection(ServerConnection):
    def __init__(self, endpoint: _Endpoint):
        self._endpoint = endpoint

    def register_request_handler(self, req_type: str,
                                 handler: Callable[[str, bytes], bytes]) -> None:
        self._endpoint.handlers[req_type] = handler

    def send(self, peer_executor_id: str, alt: AddressLengthTag,
             cb) -> Transaction:
        """Server sends are addressed to the requesting peer's tag space."""
        tx = Transaction(alt.tag).start(cb)
        self._endpoint.fabric.tags.send(peer_executor_id, alt, tx)
        return tx


class InProcessTransport(ShuffleTransport):
    """Default transport (conf spark.rapids.tpu.shuffle.transport.class)."""

    def __init__(self, executor_id: str, conf=None):
        super().__init__(executor_id, conf)
        self._fabric = _Fabric.get()
        self._endpoint = self._fabric.register(executor_id)
        self._fabric.attach_transport(self)
        self._server = InProcessServerConnection(self._endpoint)
        self._clients: Dict[str, InProcessClientConnection] = {}
        self._lock = threading.Lock()

    def connect(self, peer_executor_id: str) -> InProcessClientConnection:
        with self._lock:
            conn = self._clients.get(peer_executor_id)
            if conn is None:
                conn = InProcessClientConnection(
                    self._endpoint, _Fabric.get().endpoint(peer_executor_id))
                self._clients[peer_executor_id] = conn
            return conn

    def _drop_client(self, peer_executor_id: str) -> None:
        with self._lock:
            self._clients.pop(peer_executor_id, None)

    @property
    def server(self) -> InProcessServerConnection:
        return self._server

    def shutdown(self) -> None:
        # detach so kill() stops notifying this transport and the fabric
        # singleton doesn't pin its bounce pools forever; the ENDPOINT stays
        # registered (peers may still hold live connections to it — the
        # multi-executor-per-host topology shares one fabric for the
        # process lifetime)
        self._fabric.detach_transport(self)
