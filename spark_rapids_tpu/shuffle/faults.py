"""Deterministic fault injection for the shuffle stack.

The chaos-testing harness the fault-tolerance layer is proved against:
a seeded, conf-driven ``FaultPlan`` describes WHAT breaks and WHEN
(drop a connection after N frames, corrupt/delay/duplicate a frame, fail a
request once), and ``FaultInjectingTransport`` wraps any real transport
(in-process fabric or TCP) injecting those faults at the connection layer —
so chaos tests assert that queries still return correct results under each
fault class, deterministically under a fixed seed.

conf::

    spark.rapids.tpu.shuffle.transport.class =
        spark_rapids_tpu.shuffle.faults.FaultInjectingTransport
    spark.rapids.tpu.shuffle.faults.transport.class = <wrapped transport>
    spark.rapids.tpu.shuffle.faults.plan  = drop_conn:after=2;corrupt_frame:after=1
    spark.rapids.tpu.shuffle.faults.seed  = 7

Plan grammar: ``kind[:key=val[,key=val...]][;spec...]``. Kinds and their
injection points:

- ``drop_conn``   — the Nth frame RECEIVED from a peer kills the connection:
  that frame and every in-flight receive on the connection fail, the
  connection epoch goes dead (all later ops fail fast), and peer-lost
  listeners fire so ShuffleEnv evicts the cached client. A later connect()
  opens a fresh epoch — exactly a TCP reader thread dying mid-fetch.
- ``corrupt_frame`` — a frame SENT to a peer has one byte flipped (seeded
  choice), exercising the end-to-end checksum → retry path.
- ``delay_frame``  — a sent frame is held back ``delay_ms`` (slow peer).
- ``dup_frame``    — a sent frame is transmitted twice (duplicate delivery;
  the reader's (block, table) dedup must absorb it).
- ``fail_request`` — a client request (``req_type`` filter, default any)
  fails without reaching the peer (lost/failed RPC handler).
- ``kill_peer``    — process-local peer DEATH on this (server) side: the
  Nth matching event kills the whole wrapped transport — listener and
  every peer socket close AND the liveness heartbeat stops (the registry
  entry is deliberately left behind, exactly like SIGKILL) — so remotes
  observe a dead replica and must fail over. Countable events: a handled
  request (``req_type`` filters, e.g. ``serve.submit``) or an outgoing
  data frame (``req_type=data`` — the Nth result frame, mid-stream death).

Keys: ``peer`` (exact executor id, default ``*``), ``after`` (1-based Nth
matching event, default 1), ``count`` (how many consecutive events fire,
default 1, ``0`` = every event from ``after`` on), ``delay_ms``,
``req_type``, ``owner`` (exact executor id of the transport that INJECTS
the fault, default ``*``). Event counters run PER PEER, so
``drop_conn:after=2`` drops each remote peer's connection once.

``owner`` exists because the conf — and therefore the plan — is shared by
every executor in a cluster session: ``kill_peer:req_type=data`` alone
would kill ALL executors on their first data frame. With
``kill_peer:owner=exec-1,req_type=data,after=2`` only exec-1's transport
honors the spec, the deterministic single-executor death the recompute
tests are built on (``peer`` filters the REMOTE side of the event;
``owner`` filters the local, injecting side).
"""
from __future__ import annotations

import importlib
import queue
import random
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                ClientConnection,
                                                ServerConnection,
                                                ShuffleTransport, Transaction,
                                                TransactionStatus)

KINDS = ("drop_conn", "corrupt_frame", "delay_frame", "dup_frame",
         "fail_request", "kill_peer")
#: spec kinds probed on the server→client data path
_SEND_KINDS = ("corrupt_frame", "delay_frame", "dup_frame")


@dataclass
class FaultSpec:
    """One scheduled fault. ``after``/``count`` select which of the matching
    events fire: events ``after .. after+count-1`` (1-based, per peer)."""
    kind: str
    peer: str = "*"
    after: int = 1
    count: int = 1
    delay_ms: float = 50.0
    req_type: str = "*"
    owner: str = "*"

    def matches(self, peer: str, req_type: str = "*") -> bool:
        return (self.peer in ("*", peer)
                and self.req_type in ("*", req_type))

    def fires(self, event_num: int) -> bool:
        if event_num < self.after:
            return False
        return self.count == 0 or event_num < self.after + self.count

    @staticmethod
    def parse(text: str) -> "FaultSpec":
        kind, _, rest = text.strip().partition(":")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; known: {KINDS}")
        spec = FaultSpec(kind)
        if rest:
            for kv in rest.split(","):
                key, _, val = kv.partition("=")
                key = key.strip()
                if key == "peer":
                    spec.peer = val.strip()
                elif key == "after":
                    spec.after = int(val)
                elif key == "count":
                    spec.count = int(val)
                elif key == "delay_ms":
                    spec.delay_ms = float(val)
                elif key == "req_type":
                    spec.req_type = val.strip()
                elif key == "owner":
                    spec.owner = val.strip()
                else:
                    raise ValueError(f"unknown fault key {key!r} in {text!r}")
        return spec


class FaultPlan:
    """The full chaos schedule: specs + per-(spec, peer) event counters +
    one seeded PRNG for the plan's random choices. ``fired`` records every
    injected fault for test assertions."""

    def __init__(self, specs: Tuple[FaultSpec, ...] = (), seed: int = 0,
                 owner: str = "*"):
        self.specs = tuple(specs)
        self.seed = seed
        #: executor id of the transport this plan instance belongs to —
        #: specs with an ``owner`` filter only fire on that transport
        self.owner = owner
        self._rng = random.Random(seed)
        self._counts: Dict[Tuple[int, str], int] = {}
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, str, int]] = []   # (kind, peer, event#)

    @classmethod
    def parse(cls, text: str, seed: int = 0, owner: str = "*") -> "FaultPlan":
        specs = [FaultSpec.parse(s) for s in text.split(";") if s.strip()]
        return cls(tuple(specs), seed, owner)

    @property
    def empty(self) -> bool:
        return not self.specs

    def _advance(self, kinds: Tuple[str, ...], peer: str,
                 req_type: str = "*") -> List[FaultSpec]:
        """Advance the event counter of every matching spec; return those
        whose window covers this event."""
        hits: List[FaultSpec] = []
        with self._lock:
            for i, spec in enumerate(self.specs):
                if spec.kind not in kinds or not spec.matches(peer, req_type):
                    continue
                if spec.owner not in ("*", self.owner):
                    continue
                key = (i, peer)
                n = self._counts.get(key, 0) + 1
                self._counts[key] = n
                if spec.fires(n):
                    self.fired.append((spec.kind, peer, n))
                    hits.append(spec)
        return hits

    # ---- probes (each is ONE countable event at its injection point) -------
    def on_request(self, peer: str, req_type: str) -> Optional[str]:
        """fail_request probe: error message when a request should fail."""
        if self._advance(("fail_request",), peer, req_type):
            return f"injected request failure ({req_type})"
        return None

    def on_frame_send(self, peer: str) -> List[FaultSpec]:
        """corrupt/delay/dup probe for one outgoing data frame."""
        return self._advance(_SEND_KINDS, peer)

    def on_frame_recv(self, peer: str) -> bool:
        """drop_conn probe for one received data frame."""
        return bool(self._advance(("drop_conn",), peer))

    def on_kill_request(self, peer: str, req_type: str) -> bool:
        """kill_peer probe for one server-handled request (submit/stream/
        drain phase targeting via the ``req_type`` filter)."""
        return bool(self._advance(("kill_peer",), peer, req_type))

    def on_kill_frame(self, peer: str) -> bool:
        """kill_peer probe for one outgoing data frame (``req_type=data``
        specs: the Nth result frame = mid-stream replica death)."""
        return bool(self._advance(("kill_peer",), peer, "data"))

    def corrupt(self, data: bytearray) -> bytearray:
        """Flip one seeded byte (in place) — the minimal corruption a
        checksum must catch."""
        if len(data):
            with self._lock:
                idx = self._rng.randrange(len(data))
            data[idx] ^= 0xFF
        return data


class _FaultyClientConnection(ClientConnection):
    """Client connection epoch: passes traffic through the wrapped
    connection until a drop_conn fault fires, then the epoch is dead —
    every in-flight receive fails (scoped to THIS peer), later ops fail
    fast, and the transport evicts it so connect() starts a new epoch.

    Receives are staged through a private buffer so a stale completion from
    a dropped epoch can never scribble a bounce buffer the retry reuses."""

    def __init__(self, transport: "FaultInjectingTransport", peer: str,
                 inner: ClientConnection):
        self._t = transport
        self._inner = inner
        self.peer_executor_id = peer
        self._lock = threading.Lock()
        self._dead = False
        self._inflight: List[Transaction] = []

    @property
    def dead(self) -> bool:
        with self._lock:
            return self._dead

    def _dead_tx(self, tx: Transaction) -> Transaction:
        self._t._defer(lambda: tx.complete(
            TransactionStatus.ERROR,
            f"peer {self.peer_executor_id!r} lost: injected connection drop"))
        return tx

    def request(self, req_type: str, payload: bytes,
                cb: Callable[[Transaction], None]) -> Transaction:
        if self.dead:
            return self._dead_tx(Transaction().start(cb))
        err = self._t.plan.on_request(self.peer_executor_id, req_type)
        if err is not None:
            tx = Transaction().start(cb)
            self._t._defer(lambda: tx.complete(TransactionStatus.ERROR, err))
            return tx
        return self._inner.request(req_type, payload, cb)

    def send(self, alt: AddressLengthTag, cb) -> Transaction:
        if self.dead:
            return self._dead_tx(Transaction(alt.tag).start(cb))
        return self._inner.send(alt, cb)

    def receive(self, alt: AddressLengthTag, cb) -> Transaction:
        tx = Transaction(alt.tag).start(cb)
        with self._lock:
            if self._dead:
                return self._dead_tx(tx)
            self._inflight.append(tx)
        priv = bytearray(alt.length)
        ialt = AddressLengthTag(priv, alt.length, alt.tag)

        def icb(itx: Transaction):
            with self._lock:
                if tx in self._inflight:
                    self._inflight.remove(tx)
                dead = self._dead
            if dead:
                return                      # tx already failed by the drop
            if itx.status is not TransactionStatus.SUCCESS:
                self._t._defer(lambda: tx.complete(
                    TransactionStatus.ERROR, itx.error_message))
                return
            if self._t.plan.on_frame_recv(self.peer_executor_id):
                self._drop()
                self._dead_tx(tx)           # the triggering frame is lost too
                return
            n = min(len(priv), alt.length)
            alt.buffer[:n] = priv[:n]
            tx.stats.received_bytes = itx.stats.received_bytes

            def ok():
                tx.complete(TransactionStatus.SUCCESS)
            self._t._defer(ok)
        self._inner.receive(ialt, icb)
        return tx

    def cancel_receive(self, tag: int) -> None:
        """Pass-through of the transport's receive abandonment (tcp.py):
        drop the matching staged transaction too, so a late completion of
        a cancelled tag is a no-op instead of a surprise."""
        with self._lock:
            self._inflight = [t for t in self._inflight if t.tag != tag]
        inner = getattr(self._inner, "cancel_receive", None)
        if inner is not None:
            inner(tag)

    def _drop(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            victims = list(self._inflight)
            self._inflight.clear()
        msg = (f"peer {self.peer_executor_id!r} lost: "
               f"injected connection drop")

        def fail():
            for v in victims:
                v.complete(TransactionStatus.ERROR, msg)
        self._t._defer(fail)
        self._t._connection_dropped(self)


class _FaultyServerConnection(ServerConnection):
    """Server side: handlers pass through untouched; outgoing data frames
    run the send-side fault probes (corrupt / delay / duplicate)."""

    def __init__(self, transport: "FaultInjectingTransport",
                 inner: ServerConnection):
        self._t = transport
        self._inner = inner

    def register_request_handler(self, req_type: str,
                                 handler: Callable[[str, bytes], bytes]
                                 ) -> None:
        def probed(peer: str, payload: bytes) -> bytes:
            # kill_peer probe at request dispatch: phase-targeted replica
            # death (req_type=serve.submit dies at submit, =serve.drain
            # dies mid-drain); the kill closes every socket, so the error
            # below never reaches the peer — it observes a dead replica
            if self._t.plan.on_kill_request(peer, req_type):
                self._t.kill()
                raise ConnectionError(
                    f"injected peer death handling {req_type}")
            return handler(peer, payload)
        self._inner.register_request_handler(req_type, probed)

    def send(self, peer_executor_id: str, alt: AddressLengthTag,
             cb) -> Transaction:
        if self._t.plan.on_kill_frame(peer_executor_id):
            # mid-stream replica death: the frame is never sent and the
            # whole transport dies (listener + sockets + heartbeat)
            self._t.kill()
            tx = Transaction(alt.tag).start(cb)
            self._t._defer(lambda: tx.complete(
                TransactionStatus.ERROR, "injected peer death (kill_peer)"))
            return tx
        hits = self._t.plan.on_frame_send(peer_executor_id)
        if not hits:
            return self._inner.send(peer_executor_id, alt, cb)
        # a faulted frame always rides a COPY: the caller (BufferSendState)
        # re-stages its bounce buffer on completion, and a duplicated or
        # delayed send must not observe that reuse
        data = bytearray(alt.buffer[:alt.length])
        delay_ms = 0.0
        for spec in hits:
            if spec.kind == "corrupt_frame":
                self._t.plan.corrupt(data)
            elif spec.kind == "dup_frame":
                self._inner.send(
                    peer_executor_id,
                    AddressLengthTag(bytearray(data), len(data), alt.tag),
                    lambda t: None)
            elif spec.kind == "delay_frame":
                delay_ms = max(delay_ms, spec.delay_ms)
        salt = AddressLengthTag(data, len(data), alt.tag)
        if delay_ms <= 0:
            return self._inner.send(peer_executor_id, salt, cb)
        tx = Transaction(alt.tag).start(cb)

        def later():
            def icb(itx: Transaction):
                tx.stats.sent_bytes = itx.stats.sent_bytes
                tx.complete(itx.status, itx.error_message)
            self._inner.send(peer_executor_id, salt, icb)
        retry.call_later(delay_ms, later)
        return tx


class FaultInjectingTransport(ShuffleTransport):
    """conf spark.rapids.tpu.shuffle.transport.class =
    spark_rapids_tpu.shuffle.faults.FaultInjectingTransport

    Wraps the transport named by shuffle.faults.transport.class and injects
    the conf-driven FaultPlan. With an empty plan it is a pass-through (plus
    the private-buffer receive staging), so it can soak in stress runs."""

    def __init__(self, executor_id: str, conf=None):
        super().__init__(executor_id, conf)
        self.killed = False
        cls_name = self.conf.shuffle_faults_transport_class
        mod_name, _, cls = cls_name.rpartition(".")
        self._inner: ShuffleTransport = getattr(
            importlib.import_module(mod_name), cls)(executor_id, self.conf)
        # ONE set of pools/throttle/counters for the pair: retries counted
        # inside the wrapped transport (e.g. TCP connect) must be visible
        # through ShuffleEnv.metrics, and duplicate bounce pools would
        # double the staging memory for no isolation benefit
        self.send_bounce = self._inner.send_bounce
        self.recv_bounce = self._inner.recv_bounce
        self.throttle = self._inner.throttle
        self.metrics = self._inner.metrics
        self.plan = FaultPlan.parse(self.conf.shuffle_faults_plan,
                                    self.conf.shuffle_faults_seed,
                                    owner=executor_id)
        # real peer deaths in the wrapped transport surface through us too
        self._inner.add_peer_lost_listener(self.notify_peer_lost)
        self._conns: Dict[str, _FaultyClientConnection] = {}
        self._conns_lock = threading.Lock()
        self._server = _FaultyServerConnection(self, self._inner.server)
        # completions are deferred to this thread, NEVER run inline on the
        # caller: posters hold their own state locks when issuing ops (the
        # same single-progress-thread contract the real transports honor)
        self._dq: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        threading.Thread(target=self._defer_loop, daemon=True,
                         name=f"fault-transport-{executor_id}").start()

    def _defer_loop(self) -> None:
        while True:
            fn = self._dq.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 — completions must keep flowing
                import traceback
                traceback.print_exc()

    def _defer(self, fn: Callable[[], None]) -> None:
        self._dq.put(fn)

    def _connection_dropped(self, conn: _FaultyClientConnection) -> None:
        with self._conns_lock:
            if self._conns.get(conn.peer_executor_id) is conn:
                self._conns.pop(conn.peer_executor_id)
        self.notify_peer_lost(conn.peer_executor_id)

    def connect(self, peer_executor_id: str) -> _FaultyClientConnection:
        with self._conns_lock:
            conn = self._conns.get(peer_executor_id)
            if conn is not None and not conn.dead:
                return conn
        inner = self._inner.connect(peer_executor_id)
        conn = _FaultyClientConnection(self, peer_executor_id, inner)
        with self._conns_lock:
            self._conns[peer_executor_id] = conn
        return conn

    @property
    def server(self) -> _FaultyServerConnection:
        return self._server

    def heartbeat(self) -> None:
        """A killed replica stops heartbeating — its registry entry ages
        out of the liveness window like a real SIGKILL'd process's."""
        if not self.killed:
            self._inner.heartbeat()

    def kill(self) -> None:
        if self.killed:
            return
        self.killed = True
        self._inner.kill()

    def shutdown(self) -> None:
        self._inner.shutdown()
        self._dq.put(None)
