"""TableMeta: metadata describing a batch packed into ONE contiguous buffer.

Reference analogs: MetaUtils.buildTableMeta (MetaUtils.scala:41-116) +
getBatchFromMeta (MetaUtils.scala:212) and the flatbuffer TableMeta/ColumnMeta/
SubBufferMeta schemas (sql-plugin/src/main/format/*.fbs). The reference packs a
cuDF contiguous table (Table.contiguousSplit) and describes sub-buffer offsets
with flatbuffers; here the pack format is fixed-width struct headers (no
flatbuffer toolchain needed) and two symmetric pack paths:

- **host pack** (`pack_host_batch`) — numpy buffers copied into one bytearray,
  64-byte aligned; used by shuffle spill, network transfer, broadcast.
- **device pack** (`device_pack` / `device_unpack`) — a *jittable* bitcast+concat
  producing one uint8 vector on device, with a static `DevicePackLayout` per
  (schema, capacity, string_max_bytes); this is what rides the ICI all_to_all
  (the contiguousSplit analog — XLA moves one buffer per peer, not K columns).
"""
from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field, replace
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn

MAGIC = b"TPUM"
VERSION = 2          # v2: header carries a crc32 of the packed buffer
ALIGN = 64

_DTYPE_CODES = {dt: i for i, dt in enumerate(DType)}
_CODES_DTYPE = {i: dt for dt, i in _DTYPE_CODES.items()}


def _align(n: int, a: int = ALIGN) -> int:
    return (n + a - 1) & ~(a - 1)


@dataclass(frozen=True)
class SubBufferMeta:
    """Offset/length of one sub-buffer inside the contiguous buffer
    (SubBufferMeta.fbs analog)."""
    offset: int
    length: int


@dataclass(frozen=True)
class ColumnMeta:
    """ColumnMeta.fbs analog: one column's dtype + sub-buffer locations."""
    name: str
    dtype: DType
    nullable: bool
    string_max_bytes: int                 # 0 for non-strings
    data: SubBufferMeta
    validity: SubBufferMeta
    lengths: SubBufferMeta                # length 0 for non-strings


@dataclass(frozen=True)
class TableMeta:
    """TableMeta.fbs analog. ``codec`` names the compression codec applied to
    the packed buffer ("copy" = uncompressed, CodecType.fbs analog);
    ``uncompressed_size`` is the unpacked buffer size either way.
    ``checksum`` is a crc32 over the packed buffer the meta describes
    (0 = not computed, e.g. device-pack layouts sized before the bytes
    exist); end-to-end verification uses the TransferResponse checksum over
    the on-wire bytes, this one survives spill/reload round trips."""
    num_rows: int
    columns: Tuple[ColumnMeta, ...]
    packed_size: int
    uncompressed_size: int
    codec: str = "copy"
    checksum: int = 0

    @property
    def schema(self) -> Schema:
        return Schema([Field(c.name, c.dtype, c.nullable) for c in self.columns])

    # ---- wire format ------------------------------------------------------------
    # header: magic(4s) version(H) codec_len(B) pad(B) num_rows(Q) num_cols(H)
    #         packed_size(Q) uncompressed_size(Q) checksum(I)
    _HDR = struct.Struct("<4sHBxQHQQI")
    # per column: name_len(H) dtype(B) nullable(B) smax(I) 3×(offset Q, length Q)
    _COL = struct.Struct("<HBBIQQQQQQ")

    def to_bytes(self) -> bytes:
        out = bytearray()
        codec_b = self.codec.encode()
        out += self._HDR.pack(MAGIC, VERSION, len(codec_b), self.num_rows,
                              len(self.columns), self.packed_size,
                              self.uncompressed_size, self.checksum)
        out += codec_b
        for c in self.columns:
            nb = c.name.encode()
            out += self._COL.pack(len(nb), _DTYPE_CODES[c.dtype],
                                  1 if c.nullable else 0, c.string_max_bytes,
                                  c.data.offset, c.data.length,
                                  c.validity.offset, c.validity.length,
                                  c.lengths.offset, c.lengths.length)
            out += nb
        return bytes(out)

    @staticmethod
    def from_bytes(b: bytes) -> "TableMeta":
        magic, ver, codec_len, num_rows, ncols, psize, usize, crc = \
            TableMeta._HDR.unpack_from(b, 0)
        if magic != MAGIC:
            raise ValueError(f"bad TableMeta magic {magic!r}")
        if ver != VERSION:
            raise ValueError(f"unsupported TableMeta version {ver}")
        pos = TableMeta._HDR.size
        codec = b[pos:pos + codec_len].decode()
        pos += codec_len
        cols: List[ColumnMeta] = []
        for _ in range(ncols):
            (nlen, dcode, nullable, smax, doff, dlen, voff, vlen, loff,
             llen) = TableMeta._COL.unpack_from(b, pos)
            pos += TableMeta._COL.size
            name = b[pos:pos + nlen].decode()
            pos += nlen
            cols.append(ColumnMeta(name, _CODES_DTYPE[dcode], bool(nullable),
                                   smax, SubBufferMeta(doff, dlen),
                                   SubBufferMeta(voff, vlen),
                                   SubBufferMeta(loff, llen)))
        return TableMeta(num_rows, tuple(cols), psize, usize, codec, crc)

    def with_codec(self, codec: str, packed_size: int) -> "TableMeta":
        # the described bytes change with the codec, so the old crc no
        # longer applies — reset to "not computed" unless re-stamped
        return replace(self, codec=codec, packed_size=packed_size,
                       checksum=0)

    def with_checksum(self, checksum: int) -> "TableMeta":
        return replace(self, checksum=checksum)


# ---------------------------------------------------------------------------------
# host pack / unpack
# ---------------------------------------------------------------------------------

def pack_host_batch(batch: HostBatch) -> Tuple[bytes, TableMeta]:
    """Copy all column buffers into one contiguous, 64-byte-aligned buffer."""
    chunks: List[Tuple[int, bytes]] = []       # (offset, raw)
    cols: List[ColumnMeta] = []
    pos = 0

    def put(arr: Optional[np.ndarray]) -> SubBufferMeta:
        nonlocal pos
        if arr is None:
            return SubBufferMeta(0, 0)
        raw = np.ascontiguousarray(arr).tobytes()
        off = pos
        chunks.append((off, raw))
        pos = _align(off + len(raw))
        return SubBufferMeta(off, len(raw))

    for f, c in zip(batch.schema, batch.columns):
        smax = int(c.data.shape[1]) if f.dtype is DType.STRING else 0
        cols.append(ColumnMeta(f.name, f.dtype, f.nullable, smax,
                               put(c.data), put(c.validity), put(c.lengths)))
    buf = bytearray(pos)
    for off, raw in chunks:
        buf[off:off + len(raw)] = raw
    data = bytes(buf)        # the one copy the caller gets; crc over it too
    meta = TableMeta(batch.num_rows, tuple(cols), len(data), len(data),
                     checksum=zlib.crc32(data) & 0xFFFFFFFF)
    return data, meta


class ChecksumError(ValueError):
    """The buffer does not match the checksum its meta promises — corruption
    between pack and unpack (wire, spill, or staging). Retryable on fetch
    paths; re-exported by shuffle.codec for the transfer pipeline."""


def unpack_host_batch(buf: bytes, meta: TableMeta) -> HostBatch:
    """Rebuild a HostBatch from a contiguous buffer (getBatchFromMeta analog).
    When the meta carries a checksum (pack_host_batch stamps one; codec
    transforms reset it), the buffer is verified first — the last line of
    defense before corrupted bytes become rows."""
    if meta.codec != "copy":
        raise ValueError(f"buffer still compressed with {meta.codec!r}; "
                         f"decompress first (BatchedBufferDecompressor analog)")
    if meta.checksum:
        actual = zlib.crc32(buf) & 0xFFFFFFFF
        if actual != meta.checksum:
            raise ChecksumError(
                f"packed buffer checksum mismatch (expected "
                f"{meta.checksum:#010x}, got {actual:#010x}, {len(buf)} bytes)")
    mv = memoryview(buf)
    cols: List[HostColumn] = []
    for cm in meta.columns:
        npdt = cm.dtype.np_dtype()

        def sub(s: SubBufferMeta, dt, shape=None):
            a = np.frombuffer(mv[s.offset:s.offset + s.length], dtype=dt)
            return a.reshape(shape) if shape is not None else a

        validity = sub(cm.validity, np.bool_)
        n_cap = len(validity)
        if cm.dtype is DType.STRING:
            data = sub(cm.data, np.uint8, (n_cap, cm.string_max_bytes))
            lengths = sub(cm.lengths, np.int32)
            cols.append(HostColumn(cm.dtype, data, validity, lengths))
        else:
            cols.append(HostColumn(cm.dtype, sub(cm.data, npdt), validity))
    return HostBatch(meta.schema, tuple(cols), meta.num_rows)


# ---------------------------------------------------------------------------------
# device pack / unpack (jittable; static layout per schema+capacity)
# ---------------------------------------------------------------------------------

@dataclass(frozen=True)
class DevicePackLayout:
    """Static byte layout of a device-packed batch — computed from
    (schema, capacity, string_max_bytes) only, so the pack/unpack programs
    compile once per layout and the ICI all_to_all moves fixed-size buffers."""
    schema: Schema
    capacity: int
    string_max_bytes: int
    subs: Tuple[Tuple[SubBufferMeta, SubBufferMeta, SubBufferMeta], ...] = field(
        default=())
    total_size: int = 0

    @staticmethod
    def for_batch_shape(schema: Schema, capacity: int,
                        string_max_bytes: int) -> "DevicePackLayout":
        pos = 0
        subs = []
        for f in schema:
            if f.dtype is DType.STRING:
                dsize = capacity * string_max_bytes
                lsize = capacity * 4
            else:
                dsize = capacity * f.dtype.element_size()
                lsize = 0
            d = SubBufferMeta(pos, dsize); pos = _align(pos + dsize)
            v = SubBufferMeta(pos, capacity); pos = _align(pos + capacity)
            if lsize:
                l = SubBufferMeta(pos, lsize); pos = _align(pos + lsize)
            else:
                l = SubBufferMeta(0, 0)
            subs.append((d, v, l))
        return DevicePackLayout(schema, capacity, string_max_bytes,
                                tuple(subs), pos)


def uniform_string_batch(batch):
    """Pad every string column to the batch's max width — DevicePackLayout
    describes ONE width per batch, so per-column adaptive widths normalize
    here before packing/shuffling."""
    widths = [int(c.data.shape[1]) for c in batch.columns
              if c.dtype is not None and c.lengths is not None]
    if not widths or len(set(widths)) <= 1:
        return batch
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.ops.strings import pad_width
    W = max(widths)
    cols = []
    for c in batch.columns:
        if c.lengths is not None and int(c.data.shape[1]) != W:
            cols.append(DeviceColumn(c.dtype, pad_width(jnp, c.data, W),
                                     c.validity, c.lengths))
        else:
            cols.append(c)
    return batch.with_columns(batch.schema, cols)


def batch_string_max(batch) -> int:
    """String matrix width of a batch (0 if no string columns). One width per
    batch is a layout invariant: writer meta and server pack must agree."""
    for c in batch.columns:
        if c.dtype is DType.STRING:
            return int(c.data.shape[1])
    return 0


def layout_to_meta(layout: DevicePackLayout, num_rows: int) -> TableMeta:
    """TableMeta describing a device-packed buffer. Because device packing and
    host packing use the same 64-byte alignment over capacity-sized buffers,
    this meta also round-trips through unpack_host_batch on downloaded bytes."""
    cols = []
    for f, (d, v, l) in zip(layout.schema, layout.subs):
        smax = layout.string_max_bytes if f.dtype is DType.STRING else 0
        cols.append(ColumnMeta(f.name, f.dtype, f.nullable, smax, d, v, l))
    return TableMeta(num_rows, tuple(cols), layout.total_size, layout.total_size)


def host_to_device_batch(hb: HostBatch):
    """Upload an unpacked (capacity-sized) HostBatch to the device."""
    import jax
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.columnar.column import DeviceColumn
    cols = []
    for c in hb.columns:
        cols.append(DeviceColumn(
            c.dtype, jax.device_put(c.data), jax.device_put(c.validity),
            jax.device_put(c.lengths) if c.lengths is not None else None))
    return DeviceBatch(hb.schema, tuple(cols), hb.num_rows)


def _to_u8(arr):
    """Bitcast any fixed-width device array to a flat uint8 vector (jittable).

    64-bit integers route through a u32 intermediate: TPU emulates x64 as u32
    pairs and its X64 rewriter implements i64<->u32 bitcasts but not i64<->u8;
    the two-step chain produces the same little-endian bytes as a direct cast
    (verified against numpy tobytes on TPU and CPU backends). float64 has no
    working device bitcast on TPU at all — callers with f64 columns use the
    host pack path instead (see server._pack_spillable)."""
    import jax.numpy as jnp
    from jax import lax
    if arr.dtype == jnp.bool_:
        arr = arr.astype(jnp.uint8)
    if arr.dtype in (jnp.int64, jnp.uint64):
        arr = lax.bitcast_convert_type(arr, jnp.uint32)
    if arr.dtype != jnp.uint8:
        arr = lax.bitcast_convert_type(arr, jnp.uint8)
    return arr.reshape(-1)


def _from_u8(flat, dtype, shape):
    import jax.numpy as jnp
    from jax import lax
    npdt = np.dtype(dtype)
    if npdt == np.bool_:
        return flat.reshape(shape).astype(jnp.bool_)
    if npdt == np.uint8:
        return flat.reshape(shape)
    itemsize = npdt.itemsize
    if npdt in (np.dtype(np.int64), np.dtype(np.uint64)):
        words = lax.bitcast_convert_type(
            flat.reshape(tuple(shape) + (2, 4)), jnp.uint32)
        return lax.bitcast_convert_type(words, jnp.dtype(npdt))
    return lax.bitcast_convert_type(
        flat.reshape(tuple(shape) + (itemsize,)), jnp.dtype(npdt))


def device_pack(batch, layout: DevicePackLayout):
    """DeviceBatch -> one uint8[layout.total_size] device array. Jittable."""
    import jax.numpy as jnp
    out = jnp.zeros((layout.total_size,), dtype=jnp.uint8)
    for col, (d, v, l) in zip(batch.columns, layout.subs):
        out = out.at[d.offset:d.offset + d.length].set(_to_u8(col.data))
        out = out.at[v.offset:v.offset + v.length].set(_to_u8(col.validity))
        if l.length:
            out = out.at[l.offset:l.offset + l.length].set(_to_u8(col.lengths))
    return out


def device_unpack(buf, layout: DevicePackLayout, num_rows):
    """uint8 device buffer -> DeviceBatch (padding rows already invalid).
    Jittable in the arrays; ``num_rows`` is host-side."""
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.columnar.column import DeviceColumn
    cap = layout.capacity
    cols = []
    for f, (d, v, l) in zip(layout.schema, layout.subs):
        validity = _from_u8(buf[v.offset:v.offset + v.length], np.bool_, (cap,))
        if f.dtype is DType.STRING:
            data = _from_u8(buf[d.offset:d.offset + d.length], np.uint8,
                            (cap, layout.string_max_bytes))
            lengths = _from_u8(buf[l.offset:l.offset + l.length], np.int32, (cap,))
            cols.append(DeviceColumn(f.dtype, data, validity, lengths))
        else:
            data = _from_u8(buf[d.offset:d.offset + d.length],
                            f.dtype.np_dtype(), (cap,))
            cols.append(DeviceColumn(f.dtype, data, validity))
    return DeviceBatch(layout.schema, tuple(cols), num_rows)
