"""Accelerated columnar shuffle (L0).

Reference analogs (SURVEY.md §2.8): the transport-agnostic shuffle layer
(sql-plugin shuffle/RapidsShuffleTransport.scala, RapidsShuffleClient.scala,
RapidsShuffleServer.scala, RapidsShuffleIterator.scala), the catalog-backed
caching writer/reader (RapidsShuffleInternalManager.scala, RapidsCachingReader.scala)
and the UCX transport (shuffle-plugin ucx/).

TPU re-design: batches are packed into one contiguous buffer described by a
``TableMeta`` (MetaUtils.scala analog, struct-packed instead of flatbuffers);
data moves either

- **in-process / DCN path**: tag-addressed transfers through bounce-buffer
  pools over a pluggable ``ShuffleTransport`` (the UCX-trait analog), with
  metadata riding the control plane (MapOutputTracker analog); or
- **ICI path** (``ici.py``): when all partitions live in one SPMD program, the
  exchange is a single XLA ``all_to_all`` over the device mesh — device-to-device
  over the interconnect with no host round-trip, the TPU-native replacement for
  UCX RDMA.
"""
from spark_rapids_tpu.shuffle.table_meta import (TableMeta, pack_host_batch,
                                                 unpack_host_batch)
from spark_rapids_tpu.shuffle.codec import get_codec
