"""LZ4 block-format codec with a dependency-free fallback.

The shuffle's lz4 TableCompressionCodec (shuffle/codec.py) needs to exist
on every executor — codec negotiation is only useful when the fast codec is
actually available to negotiate — so this module implements the standard
LZ4 *block* format (token / literals / little-endian u16 offset / 4+ match
length, spec: lz4_Block_format.md) in pure Python and transparently uses
the C ``lz4.block`` implementation when the package is installed. The two
interoperate: both read and write the same block format (the pure
decompressor accepts C-compressed frames and vice versa), so mixed
deployments negotiate "lz4" safely.

The pure compressor is a greedy single-probe hash-chain matcher with the
reference implementation's skip acceleration on miss streaks — spec-valid
output, not bit-identical to the C encoder (LZ4 only fixes the DEcoder).
Throughput is Python-speed; fine for the shuffle's request-sized buffers in
tests and small clusters, and the C path takes over wherever it exists.
"""
from __future__ import annotations

from typing import Optional

try:                                     # C implementation when available
    import lz4.block as _c_lz4
except ImportError:                      # pure-Python fallback below
    _c_lz4 = None

_MIN_MATCH = 4
#: spec: the last 5 bytes are always literals, and a match may not start
#: within the last 12 bytes of the input
_LAST_LITERALS = 5
_MFLIMIT = 12
_MAX_OFFSET = 0xFFFF


def _write_len(out: bytearray, n: int) -> None:
    while n >= 255:
        out.append(255)
        n -= 255
    out.append(n)


def _compress_pure(src: bytes) -> bytes:
    n = len(src)
    out = bytearray()
    if n == 0:
        return b"\x00"                   # one empty-literal token
    anchor = 0
    if n >= _MFLIMIT + 1:
        table: dict = {}
        i = 0
        limit = n - _MFLIMIT
        misses = 0
        while i <= limit:
            seq = src[i:i + _MIN_MATCH]
            j = table.get(seq)
            table[seq] = i
            if j is not None and i - j <= _MAX_OFFSET:
                # extend the match forward (must leave 5 literal bytes)
                m = i + _MIN_MATCH
                p = j + _MIN_MATCH
                max_m = n - _LAST_LITERALS
                while m < max_m and src[m] == src[p]:
                    m += 1
                    p += 1
                lit_len = i - anchor
                match_len = m - i - _MIN_MATCH
                token = ((15 if lit_len >= 15 else lit_len) << 4) | \
                    (15 if match_len >= 15 else match_len)
                out.append(token)
                if lit_len >= 15:
                    _write_len(out, lit_len - 15)
                out += src[anchor:i]
                out += (i - j).to_bytes(2, "little")
                if match_len >= 15:
                    _write_len(out, match_len - 15)
                anchor = i = m
                misses = 0
                continue
            # reference-style acceleration: long miss streaks skip ahead
            misses += 1
            i += 1 + (misses >> 6)
    lit_len = n - anchor
    out.append((15 if lit_len >= 15 else lit_len) << 4)
    if lit_len >= 15:
        _write_len(out, lit_len - 15)
    out += src[anchor:]
    return bytes(out)


def _decompress_pure(src: bytes, out_size: int) -> bytes:
    out = bytearray()
    i, n = 0, len(src)
    if out_size == 0:
        return b""
    while i < n:
        token = src[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = src[i]
                i += 1
                lit += b
                if b != 255:
                    break
        out += src[i:i + lit]
        i += lit
        if i >= n:
            break                        # final sequence: literals only
        offset = src[i] | (src[i + 1] << 8)
        i += 2
        if offset == 0 or offset > len(out):
            raise ValueError(f"lz4: invalid match offset {offset} at "
                             f"output position {len(out)}")
        ml = token & 0x0F
        if ml == 15:
            while True:
                b = src[i]
                i += 1
                ml += b
                if b != 255:
                    break
        ml += _MIN_MATCH
        start = len(out) - offset
        if offset >= ml:
            out += out[start:start + ml]
        else:
            # overlapping match: the copy source grows as we write
            # (RLE-style); double the copied span instead of per-byte
            remaining = ml
            while remaining > 0:
                span = out[start:start + min(remaining, len(out) - start)]
                out += span
                remaining -= len(span)
    if len(out) != out_size:
        raise ValueError(f"lz4: decompressed to {len(out)} bytes, "
                         f"expected {out_size}")
    return bytes(out)


def compress(buf: bytes) -> bytes:
    """LZ4 block-compress ``buf`` (no size header; the shuffle meta carries
    uncompressed_size)."""
    if _c_lz4 is not None:
        return _c_lz4.compress(bytes(buf), store_size=False)
    return _compress_pure(bytes(buf))


def decompress(buf: bytes, out_size: int) -> bytes:
    if _c_lz4 is not None:
        return _c_lz4.decompress(bytes(buf), uncompressed_size=out_size)
    return _decompress_pure(bytes(buf), out_size)


def backend() -> str:
    return "c" if _c_lz4 is not None else "pure-python"
