"""Transport-agnostic shuffle data plane.

Reference analog: shuffle/RapidsShuffleTransport.scala (659 LoC) — the trait
family the UCX plugin implements: AddressLengthTag memory descriptors,
Connection/ClientConnection/ServerConnection, Transaction lifecycle with stats,
bounce-buffer pools, and the inflight-bytes throttle. Implementations here:
``inprocess.InProcessTransport`` (threads + queues, the multi-executor-per-host
and test transport) — cross-host DCN/gRPC transports plug in through the same
trait, selected by class name via conf ``spark.rapids.tpu.shuffle.transport.class``
(mirroring the reference's spark.rapids.shuffle.transport.class).
"""
from __future__ import annotations

import enum
import importlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


class TransactionStatus(enum.Enum):
    NOT_STARTED = "not_started"
    IN_PROGRESS = "in_progress"
    SUCCESS = "success"
    ERROR = "error"
    CANCELLED = "cancelled"


@dataclass
class TransactionStats:
    """Per-transaction accounting (TransactionStats analog: tx time,
    send/receive sizes, throughput)."""
    tx_time_ms: float = 0.0
    sent_bytes: int = 0
    received_bytes: int = 0

    @property
    def throughput_mb_s(self) -> float:
        if self.tx_time_ms <= 0:
            return 0.0
        return (self.sent_bytes + self.received_bytes) / 1e6 / (self.tx_time_ms / 1e3)


class Transaction:
    """One async transfer: started by a connection op, completed exactly once;
    the completion callback runs on the transport's progress thread
    (UCXTransaction analog — pending-message accounting + status propagation)."""

    def __init__(self, tag: int = 0):
        self.tag = tag
        self.status = TransactionStatus.NOT_STARTED
        self.error_message: Optional[str] = None
        self.response: bytes = b""      # RPC-style requests park the reply here
        self.stats = TransactionStats()
        self._done = threading.Event()
        self._cb: Optional[Callable[["Transaction"], None]] = None
        self._start = time.perf_counter()

    def start(self, cb: Optional[Callable[["Transaction"], None]]) -> "Transaction":
        self._cb = cb
        self.status = TransactionStatus.IN_PROGRESS
        self._start = time.perf_counter()
        return self

    def complete(self, status: TransactionStatus,
                 error: Optional[str] = None) -> None:
        if self._done.is_set():            # exactly-once; late errors are no-ops
            return
        self.stats.tx_time_ms = (time.perf_counter() - self._start) * 1e3
        self.status = status
        self.error_message = error
        self._done.set()
        if self._cb is not None:
            self._cb(self)

    def wait(self, timeout: Optional[float] = None) -> "Transaction":
        if not self._done.wait(timeout):
            raise TimeoutError(f"transaction tag={self.tag:#x} timed out")
        return self


@dataclass
class AddressLengthTag:
    """Memory descriptor for a tag-addressed transfer (AddressLengthTag analog).
    ``buffer`` is host memory (bytearray/memoryview); device buffers are staged
    through bounce buffers before hitting the wire, as in the reference."""
    buffer: bytearray
    length: int
    tag: int

    @staticmethod
    def for_bytes(data: bytes, tag: int) -> "AddressLengthTag":
        return AddressLengthTag(bytearray(data), len(data), tag)


class BounceBuffer:
    """One slab slot. close() returns it to the pool."""

    def __init__(self, manager: "BounceBufferManager", index: int, size: int):
        self._manager = manager
        self.index = index
        self.buffer = bytearray(size)
        self.size = size

    def close(self) -> None:
        self._manager.release(self)


class BounceBufferManager:
    """Pool of N fixed-size staging buffers (BounceBufferManager.scala analog:
    slab + bitset allocation; here a free-list + condition variable). Transfers
    larger than one buffer walk the pool in chunks — bounding memory used by
    any in-flight fetch regardless of batch size."""

    def __init__(self, name: str, buffer_size: int, num_buffers: int):
        self.name = name
        self.buffer_size = buffer_size
        self._free: List[int] = list(range(num_buffers))
        self._all = [BounceBuffer(self, i, buffer_size) for i in range(num_buffers)]
        self._lock = threading.Lock()
        self._available = threading.Condition(self._lock)

    def acquire(self, count: int = 1, timeout: float = 30.0) -> List[BounceBuffer]:
        deadline = time.monotonic() + timeout
        with self._available:
            while len(self._free) < count:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._available.wait(remaining):
                    raise TimeoutError(
                        f"{self.name}: no bounce buffers after {timeout}s "
                        f"(want {count}, free {len(self._free)})")
            return [self._all[self._free.pop()] for _ in range(count)]

    def try_acquire(self, count: int = 1) -> Optional[List[BounceBuffer]]:
        with self._available:
            if len(self._free) < count:
                return None
            return [self._all[self._free.pop()] for _ in range(count)]

    def release(self, buf: BounceBuffer) -> None:
        with self._available:
            self._free.append(buf.index)
            self._available.notify_all()

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)


class InflightThrottle:
    """Caps bytes in flight for receives (the reference's queuePending /
    doneBytesInFlight flow, conf maxReceiveInflightBytes). Requests queue until
    headroom frees up; FIFO so one huge fetch cannot starve small ones."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._inflight = 0
        self._lock = threading.Lock()
        self._room = threading.Condition(self._lock)
        self._waiters: List[object] = []

    def acquire(self, nbytes: int, timeout: float = 120.0) -> None:
        nbytes = min(nbytes, self.max_bytes)  # oversized requests pass alone
        deadline = time.monotonic() + timeout
        ticket = object()
        with self._room:
            self._waiters.append(ticket)
            try:
                # head-of-line only: later (small) requests cannot overtake an
                # earlier large one and starve it
                while (self._waiters[0] is not ticket
                       or self._inflight + nbytes > self.max_bytes):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._room.wait(remaining):
                        raise TimeoutError("shuffle inflight throttle timed out")
                self._inflight += nbytes
            finally:
                self._waiters.remove(ticket)
                self._room.notify_all()

    def release(self, nbytes: int) -> None:
        nbytes = min(nbytes, self.max_bytes)
        with self._room:
            self._inflight -= nbytes
            self._room.notify_all()

    @property
    def inflight_bytes(self) -> int:
        with self._lock:
            return self._inflight


# ---------------------------------------------------------------------------------
# connection traits
# ---------------------------------------------------------------------------------

class Connection:
    """Base connection: tag-addressed send/receive of host buffers."""

    def send(self, alt: AddressLengthTag,
             cb: Callable[[Transaction], None]) -> Transaction:
        raise NotImplementedError

    def receive(self, alt: AddressLengthTag,
                cb: Callable[[Transaction], None]) -> Transaction:
        """Post a receive for ``alt.tag``; completes when a matching send lands."""
        raise NotImplementedError


class ClientConnection(Connection):
    """Executor-to-peer connection used by the shuffle client."""

    peer_executor_id: str = "?"

    def request(self, req_type: str, payload: bytes,
                cb: Callable[[Transaction], None]) -> Transaction:
        """RPC-style request (metadata / transfer-start); response bytes land in
        transaction.response."""
        raise NotImplementedError


class ServerConnection(Connection):
    """Server side: registers handlers for incoming requests."""

    def register_request_handler(
            self, req_type: str,
            handler: Callable[[str, bytes], bytes]) -> None:
        """handler(peer_executor_id, payload) -> response bytes."""
        raise NotImplementedError


class ShuffleTransport:
    """Top-level transport (RapidsShuffleTransport trait analog). Owns the
    bounce pools + throttle; creates client connections and the server."""

    def __init__(self, executor_id: str, conf=None):
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.utils.metrics import (SHUFFLE_METRIC_NAMES,
                                                    MetricSet)
        self.executor_id = executor_id
        self.conf = conf or TpuConf()
        bb_size = self.conf.shuffle_bounce_buffer_size
        bb_count = self.conf.shuffle_bounce_buffer_count
        self.send_bounce = BounceBufferManager("send", bb_size, bb_count)
        self.recv_bounce = BounceBufferManager("recv", bb_size, bb_count)
        self.throttle = InflightThrottle(self.conf.shuffle_max_inflight_bytes)
        #: fault-tolerance counters, shared with the env/client/reader layers
        self.metrics = MetricSet(*SHUFFLE_METRIC_NAMES)
        self._peer_lost_listeners: List[Callable[[str], None]] = []
        self._listeners_lock = threading.Lock()

    def add_peer_lost_listener(self, fn: Callable[[str], None]) -> None:
        """``fn(peer_executor_id)`` runs when a peer's connection dies —
        the hook ShuffleEnv uses to evict its cached client so the next
        fetch reconnects instead of reusing a dead socket."""
        with self._listeners_lock:
            self._peer_lost_listeners.append(fn)

    def notify_peer_lost(self, peer_executor_id: str) -> None:
        with self._listeners_lock:
            listeners = list(self._peer_lost_listeners)
        for fn in listeners:
            try:
                fn(peer_executor_id)
            except Exception:  # noqa: BLE001 — one listener must not mute the rest
                import traceback
                traceback.print_exc()

    def connect(self, peer_executor_id: str) -> ClientConnection:
        raise NotImplementedError

    @property
    def server(self) -> ServerConnection:
        raise NotImplementedError

    def heartbeat(self) -> None:
        """Refresh this transport's liveness signal (registry-file mtime
        on the TCP transport; no-op for transports without a registry)."""

    def kill(self) -> None:
        """Simulate abrupt process death for chaos testing: drop every
        peer-visible resource WITHOUT the graceful shutdown() cleanup
        (registry retraction stays undone, exactly like SIGKILL)."""
        self.shutdown()

    def shutdown(self) -> None:
        pass


def make_transport(executor_id: str, conf) -> ShuffleTransport:
    """Load the transport by class name (ShimLoader-style dynamic dispatch off
    conf ``spark.rapids.tpu.shuffle.transport.class``)."""
    cls_name = conf.shuffle_transport_class
    mod_name, _, cls = cls_name.rpartition(".")
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls)(executor_id, conf)
