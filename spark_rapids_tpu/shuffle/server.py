"""Shuffle server: serves metadata + chunked buffer sends from the cache.

Reference analog: RapidsShuffleServer.scala (671 LoC) — handleMetadataRequest:284
serving TableMetas from the catalog, and BufferSendState:380 which acquires a
possibly-spilled buffer (device/host/disk tier), stages it through send bounce
buffers, and issues tag-addressed sends on a copy-executor thread.

TPU specifics: a device-cached batch is packed on device (device_pack — one
jitted bitcast+concat, the contiguous-buffer analog) and downloaded once; a
spilled batch is packed on host from its spill arrays with identical offsets,
so the wire format is tier-independent.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from spark_rapids_tpu.shuffle import messages as msg
from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.codec import (checksum_of, compress_batch,
                                            get_codec)
from spark_rapids_tpu.shuffle.table_meta import (DevicePackLayout, TableMeta,
                                                 batch_string_max, device_pack,
                                                 uniform_string_batch,
                                                 pack_host_batch)
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                ServerConnection,
                                                ShuffleTransport, Transaction,
                                                TransactionStatus)
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.memory.buffer import (SpillCorruptionError,
                                            SpillableBuffer, StorageTier)


def _pack_spillable(buf: SpillableBuffer) -> bytes:
    """Packed host bytes of a cached buffer, tier-aware (BufferSendState's
    catalog acquire: device → device_pack + download, host/disk → host pack).

    DOUBLE columns force the host path: TPU's x64 emulation has no f64 bitcast,
    so such batches download their typed arrays and pack on host (same offsets,
    tier-independent wire format either way)."""
    if (buf.tier == StorageTier.DEVICE
            and not any(f.dtype is DType.DOUBLE for f in buf.schema)):
        batch = uniform_string_batch(buf.get_batch())
        layout = DevicePackLayout.for_batch_shape(
            batch.schema, batch.capacity, batch_string_max(batch))
        packed = device_pack(batch, layout)
        return bytes(np.asarray(packed).tobytes())
    hb = buf.get_host_batch(slice_rows=False)
    raw, _ = pack_host_batch(hb)
    return raw


class BufferSendState:
    """Walks one packed buffer through send bounce buffers as tag-addressed
    chunk sends (BufferSendState analog). Window = however many bounce buffers
    the pool yields; each completed chunk re-arms its bounce buffer for the
    next chunk until the buffer is fully sent."""

    def __init__(self, server: "ShuffleServer", peer: str, data: bytes,
                 base_tag: int, chunk_size: int):
        self.server = server
        self.peer = peer
        self.data = data
        self.base_tag = base_tag
        self.chunk_size = chunk_size
        self.num_chunks = max(1, -(-len(data) // chunk_size))
        self._next_chunk = 0
        self._outstanding = 0
        self._lock = threading.Lock()
        self.error: Optional[str] = None
        self.done = threading.Event()

    def start(self) -> None:
        window = min(self.num_chunks, 4)
        bounces = self.server.transport.send_bounce.acquire(window)
        with self._lock:
            for bb in bounces:
                self._arm(bb)

    def _arm(self, bounce) -> None:
        """Stage the next chunk into ``bounce`` and send it. Caller holds lock."""
        i = self._next_chunk
        if i >= self.num_chunks:
            bounce.close()
            if self._outstanding == 0 and not self.done.is_set():
                self.done.set()
            return
        self._next_chunk += 1
        self._outstanding += 1
        start = i * self.chunk_size
        chunk = self.data[start:start + self.chunk_size]
        bounce.buffer[:len(chunk)] = chunk
        alt = AddressLengthTag(bounce.buffer, len(chunk), self.base_tag + i)

        def on_done(tx: Transaction, bounce=bounce):
            with self._lock:
                self._outstanding -= 1
                if tx.status is not TransactionStatus.SUCCESS:
                    self.error = tx.error_message or "send failed"
                    bounce.close()
                    self.done.set()
                    return
                self._arm(bounce)
        self.server.server_conn.send(self.peer, alt, on_done)


class ShuffleServer:
    """Registers the request handlers and owns send-state lifecycles
    (RapidsShuffleServer analog; the copy executor is the transport's
    progress/rpc threads).

    ``supported_codecs`` restricts which compression codecs this server
    will apply (None = everything the local registry can construct). A
    TransferRequest naming a codec outside that set NEGOTIATES DOWN to the
    copy pseudo-codec instead of failing: the response's TableMeta.codec
    records what was actually applied, so a new client fetching from an
    old/codec-less peer still gets its data — uncompressed — rather than an
    error (the reference's CodecType negotiation role)."""

    def __init__(self, transport: ShuffleTransport,
                 catalog: ShuffleBufferCatalog, codec_name: str = "none",
                 supported_codecs=None):
        self.transport = transport
        self.server_conn: ServerConnection = transport.server
        self.catalog = catalog
        self.codec_name = codec_name
        self.supported_codecs = (None if supported_codecs is None
                                 else {c.lower() for c in supported_codecs})
        self.server_conn.register_request_handler(msg.REQ_METADATA,
                                                  self.handle_metadata_request)
        self.server_conn.register_request_handler(msg.REQ_TRANSFER,
                                                  self.handle_transfer_request)

    def _negotiate_codec(self, requested: str):
        """The codec actually applied for a request: the requested one when
        this server supports it, else copy (graceful degradation — never
        fail a fetch over a codec mismatch)."""
        from spark_rapids_tpu.shuffle.codec import codec_available
        from spark_rapids_tpu.utils import metrics as mt
        name = (requested or "copy").lower()
        if ((self.supported_codecs is not None
             and name not in self.supported_codecs)
                or not codec_available(name)):
            if name not in ("copy", "none"):
                self.transport.metrics[mt.SHUFFLE_CODEC_FALLBACKS].add(1)
            name = "copy"
        return get_codec(name, getattr(self.transport, "conf", None))

    # ---- handlers (run on transport rpc threads) --------------------------------
    def handle_metadata_request(self, peer: str, payload: bytes) -> bytes:
        req = msg.MetadataRequest.from_bytes(payload)
        tables = []
        for block in req.blocks:
            for idx, meta in enumerate(self.catalog.metas(block)):
                tables.append((block, idx, meta))
        return msg.MetadataResponse(tuple(tables)).to_bytes()

    def handle_transfer_request(self, peer: str, payload: bytes) -> bytes:
        req = msg.TransferRequest.from_bytes(payload)
        acquired = self.catalog.acquire_buffers(req.block)
        if req.table_idx >= len(acquired):
            for b, _ in acquired:
                b.close()
            raise KeyError(f"{req.block} has no table {req.table_idx}")
        for i, (b, _) in enumerate(acquired):
            if i != req.table_idx:
                b.close()
        buf, meta = acquired[req.table_idx]
        try:
            raw = _pack_spillable(buf)
        except SpillCorruptionError:
            # a spill file that fails its crc is a LOST block, not a
            # transient transfer error: drop the whole map task's outputs
            # from the catalog so the peer's next metadata request reports
            # them missing — the permanent lost-block signal that feeds
            # the lineage-recompute path (the replayed map task replaces
            # the dropped blocks exactly-once)
            self.catalog.remove_map_outputs(req.block.shuffle_id,
                                            req.block.map_id)
            raise
        finally:
            buf.close()
        codec = self._negotiate_codec(req.codec)
        wire, wire_meta = compress_batch(raw, meta, codec)
        # crc over the exact bytes that ride the wire (post-compression):
        # the client verifies the assembled buffer against this before
        # decompressing, so corruption anywhere in flight is retryable
        crc = checksum_of(wire)
        state = BufferSendState(self, peer, wire, req.base_tag, req.chunk_size)
        state.start()
        return msg.TransferResponse(len(wire), wire_meta.with_checksum(crc),
                                    crc).to_bytes()
