"""Cross-host TCP shuffle transport.

Reference analog: the UCX transport (shuffle-plugin ucx/UCX.scala:53) — a
management-port handshake (UCX.scala:113 startManagementPort), a dedicated
progress thread per connection draining completions, and tag-addressed
transfers. This is the DCN-path equivalent over plain sockets: executors in
DIFFERENT PROCESSES (or hosts) exchange shuffle buffers through framed
messages; the in-process transport remains the intra-host fast path, exactly
as the reference keeps host-local optimizations next to UCX.

Wire format (all big-endian):
  frame   := kind(1) tag(8) length(4) payload[length]
  kinds   := H (hello: payload = executor id)
             Q (request: payload = type_len(2) type body; tag = request id)
             P (response: payload = status(1) body; tag = request id)
             D (data: tag-addressed buffer)

Peer discovery uses a registry directory (the management rendezvous): every
transport writes ``<registry>/<executor_id>`` containing ``host:port``;
connect() polls the peer's file. On a cluster this directory is shared
storage or is replaced by the control plane's executor registry.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from spark_rapids_tpu.shuffle.retry import backoff_ms
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                ClientConnection,
                                                ServerConnection,
                                                ShuffleTransport, Transaction,
                                                TransactionStatus)
from spark_rapids_tpu.utils import metrics as mt

_HDR = struct.Struct(">cQI")


def scan_registry(registry_dir: str,
                  stale_after_s: Optional[float] = None
                  ) -> Dict[str, str]:
    """Scan a registry directory: ``{executor_id: "host:port"}`` of every
    published entry. With ``stale_after_s``, entries whose heartbeat mtime
    is older than the window are SKIPPED and garbage-collected — a
    SIGKILL'd process cannot retract its own file (``shutdown`` never
    ran), so without the GC dead entries would be handed out forever.
    Unlinks race benignly: losing the race to another scanner (or to the
    owner re-publishing) is a no-op."""
    out: Dict[str, str] = {}
    try:
        names = os.listdir(registry_dir)
    except FileNotFoundError:
        return out      # nothing published yet: a genuinely empty fleet
    # any OTHER listdir failure propagates: a transient EACCES/ESTALE on
    # a network FS must read as "registry unreadable right now", never as
    # "every replica is dead" — callers keep their previous view
    now = time.time()
    for name in names:
        if name.endswith(".tmp"):       # half-written publication
            continue
        path = os.path.join(registry_dir, name)
        try:
            if (stale_after_s is not None
                    and now - os.path.getmtime(path) > stale_after_s):
                os.unlink(path)         # dead: heartbeat stopped
                continue
            with open(path) as f:
                addr = f.read().strip()
        except OSError:
            continue
        if ":" in addr:
            out[name] = addr
    return out


def _send_frame(sock: socket.socket, lock: threading.Lock, kind: bytes,
                tag: int, payload: bytes) -> None:
    # justified per-socket writer lock: frames must hit the stream whole
    # (interleaved sendall calls would corrupt the wire format), and the
    # lock covers exactly one socket — contention is bounded to writers of
    # that peer, never the transport's shared state.
    with lock:
        sock.sendall(_HDR.pack(kind, tag, len(payload)) + payload)  # tpu-lint: disable=R006


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class _Peer:
    """One live socket + its writer lock and reader (progress) thread."""

    def __init__(self, transport: "TcpTransport", sock: socket.socket,
                 peer_id: str = "?"):
        self.transport = transport
        self.sock = sock
        self.peer_id = peer_id
        self.wlock = threading.Lock()
        self.reader = threading.Thread(target=self._read_loop,
                                       name=f"tcp-shuffle-reader-{peer_id}",
                                       daemon=True)
        self.reader.start()

    def _read_loop(self) -> None:
        t = self.transport
        try:
            while True:
                hdr = _recv_exact(self.sock, _HDR.size)
                if hdr is None:
                    break
                kind, tag, length = _HDR.unpack(hdr)
                payload = _recv_exact(self.sock, length) if length else b""
                if payload is None and length:
                    break
                if kind == b"H":
                    self.peer_id = payload.decode()
                    t._register_peer(self.peer_id, self)
                elif kind == b"D":
                    t._on_data(tag, payload)
                elif kind == b"P":
                    t._on_response(tag, payload)
                elif kind == b"Q":
                    t._on_request(self, tag, payload)
        except Exception as e:  # noqa: BLE001 - fail pending work, not hang
            t._peer_lost(self, f"{type(e).__name__}: {e}")
            return
        t._peer_lost(self, "connection closed")

    def close(self) -> None:
        # SHUT_RDWR first: a bare close() is deferred by CPython while the
        # reader thread is blocked in recv — no FIN goes out and neither
        # side's reader ever wakes; shutdown() interrupts the recv and
        # notifies the remote immediately
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class TcpClientConnection(ClientConnection):
    def __init__(self, transport: "TcpTransport", peer: _Peer):
        self._t = transport
        self._peer = peer
        self.peer_executor_id = peer.peer_id

    def request(self, req_type: str, payload: bytes,
                cb: Callable[[Transaction], None]) -> Transaction:
        tx = Transaction().start(cb)
        rid = self._t._register_rpc(tx, self._peer)
        body = (struct.pack(">H", len(req_type)) + req_type.encode()
                + payload)
        try:
            _send_frame(self._peer.sock, self._peer.wlock, b"Q", rid, body)
        except OSError as e:
            self._t._drop_rpc(rid)
            tx.complete(TransactionStatus.ERROR, f"send failed: {e}")
        return tx

    def send(self, alt: AddressLengthTag, cb) -> Transaction:
        return self._t._async_send(self._peer, alt, cb)

    def receive(self, alt: AddressLengthTag, cb) -> Transaction:
        tx = Transaction(alt.tag).start(cb)
        self._t._post_receive(alt, tx, self._peer)
        return tx

    def cancel_receive(self, tag: int) -> None:
        """Abandon a posted receive: a timed-out fetch that retries with a
        fresh tag must not pin its frame-sized buffer in the pending table
        (or let a late retransmit scribble an abandoned buffer) for the
        connection's lifetime."""
        self._t._cancel_receive(tag)


class TcpServerConnection(ServerConnection):
    def __init__(self, transport: "TcpTransport"):
        self._t = transport

    def register_request_handler(self, req_type: str,
                                 handler: Callable[[str, bytes], bytes]
                                 ) -> None:
        self._t._handlers[req_type] = handler

    def send(self, peer_executor_id: str, alt: AddressLengthTag,
             cb) -> Transaction:
        """Server-initiated data ride the SAME socket the peer opened (the
        reference's server sends to the client's tag space)."""
        peer = self._t._peer_by_id(peer_executor_id)
        if peer is None:
            tx = Transaction(alt.tag).start(cb)
            self._t._progress_put(lambda: tx.complete(
                TransactionStatus.ERROR,
                f"no connection from {peer_executor_id!r}"))
            return tx
        return self._t._async_send(peer, alt, cb)


class TcpTransport(ShuffleTransport):
    """conf spark.rapids.tpu.shuffle.transport.class =
    spark_rapids_tpu.shuffle.tcp.TcpTransport"""

    def __init__(self, executor_id: str, conf=None):
        super().__init__(executor_id, conf)
        self._handlers: Dict[str, Callable[[str, bytes], bytes]] = {}
        # pending tables track the OWNING peer per transaction, so a lost
        # peer fails only its own transactions (scoped failure domains).
        # _rpc_lock guards the rpc table AND the id counter: caller
        # threads insert while reader threads pop completions and the
        # peer-lost sweep iterates (R012)
        self._pending_rpcs: Dict[int, Tuple[Transaction, "_Peer"]] = {}
        self._rpc_id = 0
        self._rpc_lock = threading.Lock()
        self._tag_lock = threading.Lock()
        self._pending_recvs: Dict[
            int, Tuple[AddressLengthTag, Transaction, "_Peer"]] = {}
        self._early_data: Dict[int, bytes] = {}
        # _peers_lock guards the peer table: reader threads register on
        # hello, the accept loop creates, connect() callers register,
        # peer-lost evicts with a check-then-act that must be atomic
        # (a NEWER peer registered between the check and the pop must
        # survive the old reader's eviction) — R012
        self._peers: Dict[str, _Peer] = {}
        self._peers_lock = threading.Lock()
        self._clients: Dict[str, TcpClientConnection] = {}
        self._clients_lock = threading.Lock()
        self._server_conn = TcpServerConnection(self)
        # init-before-spawn (R012): every attribute the worker/progress/
        # accept/heartbeat threads read exists BEFORE the first spawn
        self._killed = False
        self._registry = self.conf.shuffle_tcp_registry
        # worker pool for request handlers (the server copy-executor role);
        # sized by conf: the shuffle data plane needs few, the serving wire
        # protocol raises it so bounded-poll serve.next handlers from many
        # clients do not head-of-line-block each other
        import queue as _q
        from spark_rapids_tpu import config as _cfg
        self._num_workers = self.conf.get(_cfg.SHUFFLE_TCP_WORKER_THREADS)
        self._work: "_q.Queue[Optional[Callable[[], None]]]" = _q.Queue()
        for i in range(self._num_workers):
            threading.Thread(target=self._work_loop, daemon=True,
                             name=f"tcp-shuffle-server-{executor_id}-{i}"
                             ).start()
        # progress thread: ALL send completions run here, never inline on the
        # caller (the reference's single-progress-thread contract — callers
        # hold their own state locks when issuing sends, UCX.scala:70-112)
        self._progress: "_q.Queue[Optional[Callable[[], None]]]" = _q.Queue()
        threading.Thread(target=self._progress_loop, daemon=True,
                         name=f"tcp-shuffle-progress-{executor_id}").start()
        # management port: listen + registry publication (UCX.scala:113)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", self.conf.shuffle_tcp_port))
        self._listener.listen(16)
        self.address = self._listener.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"tcp-shuffle-accept-{executor_id}").start()
        if self._registry:
            os.makedirs(self._registry, exist_ok=True)
            self._publish_registry()

    def _publish_registry(self) -> None:
        path = os.path.join(self._registry, self.executor_id)
        with open(path + ".tmp", "w") as f:
            f.write(f"{self.address[0]}:{self.address[1]}")
        os.replace(path + ".tmp", path)

    # ---- plumbing ----------------------------------------------------------
    def _progress_loop(self) -> None:
        while True:
            fn = self._progress.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 — completions must keep flowing
                import traceback
                traceback.print_exc()

    def _progress_put(self, fn: Callable[[], None]) -> None:
        self._progress.put(fn)

    def _async_send(self, peer: _Peer, alt: AddressLengthTag,
                    cb) -> Transaction:
        tx = Transaction(alt.tag).start(cb)
        data = bytes(alt.buffer[:alt.length])

        def run():
            try:
                _send_frame(peer.sock, peer.wlock, b"D", alt.tag, data)
                tx.stats.sent_bytes = len(data)
                tx.complete(TransactionStatus.SUCCESS)
            except OSError as e:
                tx.complete(TransactionStatus.ERROR, f"send failed: {e}")
        self._progress_put(run)
        return tx

    def _work_loop(self) -> None:
        while True:
            fn = self._work.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 — a handler error must not
                import traceback  # kill the worker (peers would hang)
                traceback.print_exc()

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _Peer(self, sock)

    def _register_peer(self, peer_id: str, peer: _Peer) -> None:
        with self._peers_lock:
            self._peers[peer_id] = peer

    def _peer_lost(self, peer: _Peer, reason: str) -> None:
        """A reader exited: every pending transaction OWNED BY THAT PEER
        fails NOW (a silent hang until the fetch timeout is strictly worse
        than an error — the error drives the reader's reconnect-and-retry,
        then ShuffleFetchFailedError and the stage retry). Transactions of
        healthy peers are untouched: one lost executor must not fail
        fetches that were never routed through it."""
        with self._tag_lock:
            dead_tags = [t for t, (_, _, owner) in self._pending_recvs.items()
                         if owner is peer]
            recvs = [self._pending_recvs.pop(t)[1] for t in dead_tags]
        with self._rpc_lock:
            dead_rids = [r for r, (_, owner) in self._pending_rpcs.items()
                         if owner is peer]
            rpcs = [tx for rid in dead_rids
                    for tx in (self._pending_rpcs.pop(rid, (None,))[0],)
                    if tx is not None]
        # drop the dead peer from the connection tables so the next
        # connect() dials a fresh socket instead of reusing a corpse —
        # guard against a STALE reader (a replaced connection's old socket)
        # evicting the live one. Check-then-act is atomic under the peers
        # lock: a NEWER peer registered between the check and the pop
        # must survive the old reader's eviction (R012).
        with self._peers_lock:
            was_current = self._peers.get(peer.peer_id) is peer
            if was_current:
                self._peers.pop(peer.peer_id, None)
        if was_current:
            with self._clients_lock:
                self._clients.pop(peer.peer_id, None)

        def fail():
            msg = f"peer {peer.peer_id!r} lost: {reason}"
            for tx in recvs:
                tx.complete(TransactionStatus.ERROR, msg)
            for tx in rpcs:
                tx.complete(TransactionStatus.ERROR, msg)
        self._progress_put(fail)
        if was_current and peer.peer_id != "?":
            self.notify_peer_lost(peer.peer_id)

    def _peer_by_id(self, peer_id: str) -> Optional[_Peer]:
        with self._peers_lock:
            return self._peers.get(peer_id)

    def _register_rpc(self, tx: Transaction, peer: _Peer) -> int:
        with self._rpc_lock:
            self._rpc_id += 1
            self._pending_rpcs[self._rpc_id] = (tx, peer)
            return self._rpc_id

    def _drop_rpc(self, rid: int) -> None:
        with self._rpc_lock:
            self._pending_rpcs.pop(rid, None)

    def _post_receive(self, alt: AddressLengthTag, tx: Transaction,
                      peer: _Peer) -> None:
        with self._tag_lock:
            data = self._early_data.pop(alt.tag, None)
            if data is None:
                self._pending_recvs[alt.tag] = (alt, tx, peer)
                return
        # complete on the progress thread, NEVER inline: the poster holds its
        # own state lock (inprocess._TagTable defers the same way)
        self._progress_put(lambda: self._fill(alt, tx, data))

    def _cancel_receive(self, tag: int) -> None:
        with self._tag_lock:
            self._pending_recvs.pop(tag, None)
            self._early_data.pop(tag, None)

    #: bound on frames parked for not-yet-posted receives: legit early
    #: data (a send racing its recv post) is transient and small in
    #: count; an UNBOUNDED table would let orphaned tags (duplicate
    #: frames, retransmits landing after a cancel_receive) accumulate
    #: frame-sized buffers for the connection's lifetime. Evicting the
    #: oldest degrades to a receive timeout + retry, never corruption.
    _EARLY_DATA_CAP = 512

    def _on_data(self, tag: int, payload: bytes) -> None:
        with self._tag_lock:
            pending = self._pending_recvs.pop(tag, None)
            if pending is None:
                self._early_data[tag] = payload   # send raced ahead of recv
                while len(self._early_data) > self._EARLY_DATA_CAP:
                    self._early_data.pop(next(iter(self._early_data)))
                return
        alt, tx, _owner = pending
        self._fill(alt, tx, payload)

    @staticmethod
    def _fill(alt: AddressLengthTag, tx: Transaction, data: bytes) -> None:
        n = min(len(data), alt.length)
        alt.buffer[:n] = data[:n]
        tx.stats.received_bytes = n
        tx.complete(TransactionStatus.SUCCESS)

    def _on_response(self, rid: int, payload: bytes) -> None:
        with self._rpc_lock:
            entry = self._pending_rpcs.pop(rid, None)
        if entry is None:
            return
        tx, _owner = entry
        ok = payload[:1] == b"\x00"
        tx.response = payload[1:]
        tx.stats.received_bytes = len(tx.response)
        if ok:
            tx.complete(TransactionStatus.SUCCESS)
        else:
            tx.complete(TransactionStatus.ERROR,
                        payload[1:].decode(errors="replace"))

    def _on_request(self, peer: _Peer, rid: int, body: bytes) -> None:
        (tlen,) = struct.unpack(">H", body[:2])
        req_type = body[2:2 + tlen].decode()
        payload = body[2 + tlen:]

        def run():
            handler = self._handlers.get(req_type)
            try:
                if handler is None:
                    raise KeyError(f"no handler for {req_type!r}")
                resp = b"\x00" + handler(peer.peer_id, payload)
            except Exception as e:  # noqa: BLE001 - propagated to the peer
                resp = b"\x01" + f"{type(e).__name__}: {e}".encode()
            try:
                _send_frame(peer.sock, peer.wlock, b"P", rid, resp)
            except OSError:
                pass
        self._work.put(run)

    # ---- transport API -----------------------------------------------------
    def connect(self, peer_executor_id: str) -> TcpClientConnection:
        """Dial a peer, retrying transient failures (slow registry, peer
        restarting, connection refused) with exponential backoff + jitter
        under shuffle.maxRetries / .retryBackoffMs; each attempt is bounded
        by shuffle.connectTimeout. On peer loss the cached connection was
        evicted by _peer_lost, so calling connect() again re-dials."""
        with self._clients_lock:
            conn = self._clients.get(peer_executor_id)
            if conn is not None:
                return conn
        timeout = self.conf.shuffle_connect_timeout
        max_retries = self.conf.shuffle_max_retries
        attempt = 0
        while True:
            try:
                host, port = self._resolve(peer_executor_id, timeout)
                sock = socket.create_connection((host, port), timeout=timeout)
                break
            except (OSError, ConnectionError) as e:
                if attempt >= max_retries:
                    raise ConnectionError(
                        f"connect to {peer_executor_id!r} failed after "
                        f"{attempt + 1} attempts: {e}") from e
                self.metrics[mt.SHUFFLE_CONNECT_RETRIES].add(1)
                time.sleep(backoff_ms(
                    attempt, self.conf.shuffle_retry_backoff_ms,
                    self.conf.shuffle_faults_seed,
                    key=f"connect:{peer_executor_id}") / 1e3)
                attempt += 1
        # connectTimeout applies to establishment only; a long-idle but
        # healthy connection must not trip the reader's recv timeout
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        peer = _Peer(self, sock, peer_executor_id)
        self._register_peer(peer_executor_id, peer)
        _send_frame(sock, peer.wlock, b"H", 0, self.executor_id.encode())
        conn = TcpClientConnection(self, peer)
        with self._clients_lock:
            self._clients[peer_executor_id] = conn
        return conn

    def _resolve(self, peer_executor_id: str, timeout: Optional[float] = None
                 ) -> Tuple[str, int]:
        if timeout is None:
            timeout = self.conf.shuffle_connect_timeout
        if ":" in peer_executor_id:          # direct host:port addressing
            host, _, port = peer_executor_id.rpartition(":")
            return host, int(port)
        if not self._registry:
            raise ConnectionError(
                f"cannot resolve {peer_executor_id!r}: no registry dir "
                f"(spark.rapids.tpu.shuffle.tcp.registryDir)")
        path = os.path.join(self._registry, peer_executor_id)
        deadline = time.monotonic() + timeout
        while True:
            try:
                with open(path) as f:
                    host, _, port = f.read().strip().rpartition(":")
                    return host, int(port)
            except (FileNotFoundError, ValueError):
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"executor {peer_executor_id!r} never registered "
                        f"in {self._registry}") from None
                time.sleep(0.05)

    @property
    def server(self) -> TcpServerConnection:
        return self._server_conn

    def heartbeat(self) -> None:
        """Refresh the registry entry's mtime — the liveness signal
        serving-replica discovery reads (``scan_registry`` with a
        staleness window). A killed transport stops heartbeating, so
        its entry ages out exactly like a SIGKILL'd process's would."""
        if not self._registry or self._killed:
            return
        try:
            os.utime(os.path.join(self._registry, self.executor_id))
        except OSError:
            # the entry vanished — a liveness-window GC raced a stall
            # (pause longer than the window, then resume). A LIVE replica
            # must re-enter discovery, not stay ejected forever, so
            # republish instead of silently shrugging.
            try:
                self._publish_registry()
            except OSError:
                pass

    def kill(self) -> None:
        """Simulate abrupt process death (SIGKILL) process-locally: close
        the listener and every peer socket so remotes observe a dead
        replica, stop heartbeating — and deliberately LEAVE the registry
        file behind (a killed process never runs its shutdown), which is
        exactly the stale entry ``scan_registry``'s GC must absorb."""
        self._killed = True
        self._close_listener()
        with self._peers_lock:
            peers = list(self._peers.values())
        for p in peers:
            p.close()

    def _close_listener(self) -> None:
        # SHUT_RDWR first, same discipline as _Peer.close: a bare close()
        # is deferred by CPython while the accept thread is blocked in
        # accept(), leaving the port LIVE — new dials would still land
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    def shutdown(self) -> None:
        # retract the registry entry FIRST: a restarted executor re-binds an
        # ephemeral port, and a stale file would hand peers a dead address
        # (or worse, someone else's re-used port) to resolve forever
        if self._registry:
            try:
                os.remove(os.path.join(self._registry, self.executor_id))
            except OSError:
                pass
        self._close_listener()
        with self._peers_lock:
            peers = list(self._peers.values())
        for p in peers:
            p.close()
        for _ in range(self._num_workers):
            self._work.put(None)
        self._progress.put(None)
