"""Shuffle manager: caching writer/reader + map-output tracking + task iterator.

Reference analogs:
- RapidsShuffleInternalManagerBase (RapidsShuffleInternalManager.scala:194) —
  registerShuffle → GpuShuffleHandle, getWriter → RapidsCachingWriter,
  getReader → RapidsCachingReader;
- RapidsCachingWriter (same file :73-160) — per-partition batches into the
  device store + ShuffleBufferCatalog, metadata-only MapStatus;
- RapidsCachingReader.scala — local blocks from the catalog, remote via the
  transport client;
- RapidsShuffleIterator.scala:46 — task-facing blocking iterator resolving
  block locations from the MapOutputTracker, semaphore acquire on materialize,
  fetch-failure surfacing;
- GpuShuffleEnv.scala:52-70 — wiring stores/catalogs/transport per executor.

Data stays cached ON DEVICE between map and reduce (spilling host→disk under
pressure); Spark's control plane is replaced by the in-process MapOutputTracker.
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.memory.store import BufferCatalog, build_store_chain
from spark_rapids_tpu.shuffle.catalog import (ReceivedBufferCatalog,
                                              ShuffleBlockId,
                                              ShuffleBufferCatalog)
from spark_rapids_tpu.shuffle.client import ShuffleClient, ShuffleFetchHandler
from spark_rapids_tpu.shuffle.server import ShuffleServer
from spark_rapids_tpu.shuffle.table_meta import (DevicePackLayout,
                                                 batch_string_max,
                                                 uniform_string_batch,
                                                 host_to_device_batch,
                                                 layout_to_meta,
                                                 unpack_host_batch)
from spark_rapids_tpu.shuffle.transport import make_transport


class ShuffleFetchFailedError(RuntimeError):
    """RapidsShuffleFetchFailedException analog — callers re-run the map stage
    (Spark's lineage recompute is the recovery story, SURVEY.md §5)."""


@dataclass(frozen=True)
class MapStatus:
    """Metadata-only map-completion record (sizes, no data — the data stays
    cached on the mapper's device)."""
    executor_id: str
    map_id: int
    partition_sizes: Tuple[int, ...]


class MapOutputTracker:
    """Driver-side registry of map outputs (org.apache.spark.MapOutputTracker
    stand-in for the in-process cluster)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shuffles: Dict[int, Dict[int, MapStatus]] = {}

    def register_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.setdefault(shuffle_id, {})

    def register_map_output(self, shuffle_id: int, status: MapStatus) -> None:
        with self._lock:
            self._shuffles[shuffle_id][status.map_id] = status

    def blocks_by_executor(self, shuffle_id: int, partition_id: int
                           ) -> Dict[str, List[ShuffleBlockId]]:
        """Non-empty blocks of one reduce partition, grouped by executor."""
        with self._lock:
            statuses = list(self._shuffles.get(shuffle_id, {}).values())
        out: Dict[str, List[ShuffleBlockId]] = {}
        for st in statuses:
            if st.partition_sizes[partition_id] > 0:
                out.setdefault(st.executor_id, []).append(
                    ShuffleBlockId(shuffle_id, st.map_id, partition_id))
        return out

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.pop(shuffle_id, None)


class ShuffleEnv:
    """Per-executor shuffle wiring (GpuShuffleEnv analog): tiered stores,
    shuffle catalog, transport + server, client cache."""

    def __init__(self, executor_id: str, conf: Optional[TpuConf] = None,
                 device_budget: int = 1 << 30, host_budget: int = 1 << 30,
                 disk_dir: Optional[str] = None):
        self.executor_id = executor_id
        self.conf = conf or TpuConf()
        self.buffer_catalog = BufferCatalog()
        self.device_store, self.host_store, self.disk_store = build_store_chain(
            self.buffer_catalog, device_budget, host_budget, disk_dir)
        self.shuffle_catalog = ShuffleBufferCatalog(self.buffer_catalog,
                                                    self.device_store)
        self.received_catalog = ReceivedBufferCatalog()
        self.transport = make_transport(executor_id, self.conf)
        self.server = ShuffleServer(self.transport, self.shuffle_catalog,
                                    self.conf.shuffle_codec)
        self._clients: Dict[str, ShuffleClient] = {}
        self._lock = threading.Lock()
        self._connect_locks: Dict[str, threading.Lock] = {}

    def client_for(self, peer_executor_id: str) -> ShuffleClient:
        # connect() blocks (TCP handshake + registry polling, up to 30 s):
        # holding the client-table lock across it would serialize every
        # fetch in the process behind the slowest peer. A per-peer connect
        # lock serializes only callers of the SAME unconnected peer, so no
        # duplicate connection is ever created (a dropped loser would leak
        # its socket + reader thread and desync the transport peer table).
        with self._lock:
            c = self._clients.get(peer_executor_id)
            if c is not None:
                return c
            plock = self._connect_locks.setdefault(peer_executor_id,
                                                   threading.Lock())
        with plock:
            with self._lock:
                c = self._clients.get(peer_executor_id)
                if c is not None:
                    return c
            # justified block-under-lock: plock guards one peer's connect
            # only; other peers never contend  # tpu-lint: disable=R006
            conn = self.transport.connect(peer_executor_id)
            c = ShuffleClient(self.transport, conn, self.received_catalog,
                              self.conf.shuffle_codec)
            with self._lock:
                self._clients[peer_executor_id] = c
            return c

    def close(self) -> None:
        self.transport.shutdown()
        self.device_store.close()
        self.host_store.close()
        self.disk_store.close()


class CachingShuffleWriter:
    """Map-side writer: cache each partition's device batch + register meta
    (RapidsCachingWriter analog)."""

    def __init__(self, env: ShuffleEnv, tracker: MapOutputTracker,
                 shuffle_id: int, map_id: int, num_partitions: int):
        self.env = env
        self.tracker = tracker
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.num_partitions = num_partitions

    def write(self, partitions: Iterable[Tuple[int, object]]) -> MapStatus:
        """``partitions`` yields (partition_id, DeviceBatch). Batches with zero
        rows are recorded as empty (DegenerateRapidsBuffer analog: size 0)."""
        sizes = [0] * self.num_partitions
        for pid, batch in partitions:
            if batch.num_rows == 0:
                continue
            batch = uniform_string_batch(batch)
            layout = DevicePackLayout.for_batch_shape(
                batch.schema, batch.capacity, batch_string_max(batch))
            meta = layout_to_meta(layout, batch.num_rows)
            block = ShuffleBlockId(self.shuffle_id, self.map_id, pid)
            self.env.shuffle_catalog.add_batch(block, batch, meta)
            sizes[pid] += meta.packed_size
        status = MapStatus(self.env.executor_id, self.map_id, tuple(sizes))
        self.tracker.register_map_output(self.shuffle_id, status)
        return status


class _QueueHandler(ShuffleFetchHandler):
    """Bridges async client callbacks into the iterator's blocking queue."""

    def __init__(self, q: "queue.Queue", peer: str):
        self.q = q
        self.peer = peer
        self.expected = None

    def start(self, expected_tables: int) -> None:
        self.expected = expected_tables
        self.q.put(("start", self.peer, expected_tables))

    def batch_received(self, received_id: int) -> None:
        self.q.put(("batch", self.peer, received_id))

    def transfer_error(self, message: str) -> None:
        self.q.put(("error", self.peer, message))


class CachingShuffleReader:
    """Reduce-side reader (RapidsCachingReader + RapidsShuffleIterator analog):
    local blocks come straight off the catalog (device tier → zero-copy), remote
    blocks are fetched via the transport client, uploaded on arrival."""

    def __init__(self, env: ShuffleEnv, tracker: MapOutputTracker,
                 shuffle_id: int, partition_id: int, semaphore=None,
                 timeout: Optional[float] = None):
        from spark_rapids_tpu import config as _cfg
        self.env = env
        self.tracker = tracker
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        self.semaphore = semaphore
        self.timeout = (timeout if timeout is not None
                        else float(env.conf.get(_cfg.SHUFFLE_FETCH_TIMEOUT)))

    def read(self):
        """Yields DeviceBatch for this reduce partition."""
        by_exec = self.tracker.blocks_by_executor(self.shuffle_id,
                                                  self.partition_id)
        local_blocks = by_exec.pop(self.env.executor_id, [])

        # kick off remote fetches first (overlap with local materialization)
        q: "queue.Queue" = queue.Queue()
        expected = 0
        started = 0
        for peer, blocks in by_exec.items():
            self.env.client_for(peer).fetch(blocks, _QueueHandler(q, peer))
            started += 1

        if self.semaphore is not None:
            self.semaphore.acquire_if_necessary()

        for block in local_blocks:
            for buf, _meta in self.env.shuffle_catalog.acquire_buffers(block):
                try:
                    yield buf.get_batch()
                finally:
                    buf.close()

        # drain remote results
        starts_seen = 0
        received = 0
        while starts_seen < started or received < expected:
            try:
                kind, peer, value = q.get(timeout=self.timeout)
            except queue.Empty:
                raise ShuffleFetchFailedError(
                    f"shuffle {self.shuffle_id} partition {self.partition_id}: "
                    f"timed out waiting for remote blocks")
            if kind == "start":
                starts_seen += 1
                expected += value
            elif kind == "error":
                raise ShuffleFetchFailedError(
                    f"fetch from {peer} failed: {value}")
            else:
                received += 1
                raw, meta = self.env.received_catalog.take(value)
                hb = unpack_host_batch(raw, meta)
                yield host_to_device_batch(hb)


class ShuffleManager:
    """Driver-facing registry (RapidsShuffleInternalManagerBase analog)."""

    def __init__(self, tracker: Optional[MapOutputTracker] = None):
        self.tracker = tracker or MapOutputTracker()
        self._next_shuffle = 0
        self._lock = threading.Lock()

    def register_shuffle(self, num_partitions: int) -> Tuple[int, int]:
        with self._lock:
            sid = self._next_shuffle
            self._next_shuffle += 1
        self.tracker.register_shuffle(sid)
        return sid, num_partitions

    def get_writer(self, env: ShuffleEnv, shuffle_id: int, map_id: int,
                   num_partitions: int) -> CachingShuffleWriter:
        return CachingShuffleWriter(env, self.tracker, shuffle_id, map_id,
                                    num_partitions)

    def get_reader(self, env: ShuffleEnv, shuffle_id: int, partition_id: int,
                   semaphore=None) -> CachingShuffleReader:
        return CachingShuffleReader(env, self.tracker, shuffle_id,
                                    partition_id, semaphore)

    def unregister_shuffle(self, shuffle_id: int,
                           envs: Iterable[ShuffleEnv] = ()) -> None:
        self.tracker.unregister_shuffle(shuffle_id)
        for env in envs:
            env.shuffle_catalog.remove_shuffle(shuffle_id)
