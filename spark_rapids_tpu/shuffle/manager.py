"""Shuffle manager: caching writer/reader + map-output tracking + task iterator.

Reference analogs:
- RapidsShuffleInternalManagerBase (RapidsShuffleInternalManager.scala:194) —
  registerShuffle → GpuShuffleHandle, getWriter → RapidsCachingWriter,
  getReader → RapidsCachingReader;
- RapidsCachingWriter (same file :73-160) — per-partition batches into the
  device store + ShuffleBufferCatalog, metadata-only MapStatus;
- RapidsCachingReader.scala — local blocks from the catalog, remote via the
  transport client;
- RapidsShuffleIterator.scala:46 — task-facing blocking iterator resolving
  block locations from the MapOutputTracker, semaphore acquire on materialize,
  fetch-failure surfacing;
- GpuShuffleEnv.scala:52-70 — wiring stores/catalogs/transport per executor.

Data stays cached ON DEVICE between map and reduce (spilling host→disk under
pressure); Spark's control plane is replaced by the in-process MapOutputTracker.
"""
from __future__ import annotations

import queue
import threading
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.memory.store import BufferCatalog, build_store_chain
from spark_rapids_tpu.shuffle.catalog import (ReceivedBufferCatalog,
                                              ShuffleBlockId,
                                              ShuffleBufferCatalog)
from spark_rapids_tpu.shuffle.client import ShuffleClient, ShuffleFetchHandler
from spark_rapids_tpu.shuffle.server import ShuffleServer
from spark_rapids_tpu.shuffle.table_meta import (DevicePackLayout,
                                                 batch_string_max,
                                                 uniform_string_batch,
                                                 host_to_device_batch,
                                                 layout_to_meta,
                                                 unpack_host_batch)
from spark_rapids_tpu.shuffle.transport import make_transport


class ShuffleFetchFailedError(RuntimeError):
    """RapidsShuffleFetchFailedException analog — callers re-run the map stage
    (Spark's lineage recompute is the recovery story, SURVEY.md §5).

    Raised only after the reader's own retries (reconnect + re-fetch under
    spark.rapids.tpu.shuffle.maxRetries) are exhausted. ``executor_id`` and
    ``blocks`` scope the failure so callers can recompute only the affected
    map outputs instead of the whole stage."""

    def __init__(self, message: str, executor_id: Optional[str] = None,
                 blocks: Tuple[ShuffleBlockId, ...] = ()):
        super().__init__(message)
        self.executor_id = executor_id
        self.blocks = tuple(blocks)


@dataclass(frozen=True)
class MapStatus:
    """Metadata-only map-completion record (sizes, no data — the data stays
    cached on the mapper's device)."""
    executor_id: str
    map_id: int
    partition_sizes: Tuple[int, ...]


class MapOutputTracker:
    """Driver-side registry of map outputs (org.apache.spark.MapOutputTracker
    stand-in for the in-process cluster)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shuffles: Dict[int, Dict[int, MapStatus]] = {}

    def register_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.setdefault(shuffle_id, {})

    def register_map_output(self, shuffle_id: int, status: MapStatus) -> None:
        with self._lock:
            self._shuffles[shuffle_id][status.map_id] = status

    def blocks_by_executor(self, shuffle_id: int, partition_id: int
                           ) -> Dict[str, List[ShuffleBlockId]]:
        """Non-empty blocks of one reduce partition, grouped by executor."""
        with self._lock:
            statuses = list(self._shuffles.get(shuffle_id, {}).values())
        out: Dict[str, List[ShuffleBlockId]] = {}
        for st in statuses:
            if st.partition_sizes[partition_id] > 0:
                out.setdefault(st.executor_id, []).append(
                    ShuffleBlockId(shuffle_id, st.map_id, partition_id))
        return out

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._shuffles.pop(shuffle_id, None)


class ShuffleEnv:
    """Per-executor shuffle wiring (GpuShuffleEnv analog): tiered stores,
    shuffle catalog, transport + server, client cache."""

    def __init__(self, executor_id: str, conf: Optional[TpuConf] = None,
                 device_budget: int = 1 << 30, host_budget: int = 1 << 30,
                 disk_dir: Optional[str] = None):
        self.executor_id = executor_id
        self.conf = conf or TpuConf()
        self.buffer_catalog = BufferCatalog()
        self.device_store, self.host_store, self.disk_store = build_store_chain(
            self.buffer_catalog, device_budget, host_budget, disk_dir)
        self.shuffle_catalog = ShuffleBufferCatalog(self.buffer_catalog,
                                                    self.device_store)
        self.received_catalog = ReceivedBufferCatalog()
        self.transport = make_transport(executor_id, self.conf)
        self.server = ShuffleServer(self.transport, self.shuffle_catalog,
                                    self.conf.shuffle_codec)
        self.metrics = self.transport.metrics
        self._clients: Dict[str, ShuffleClient] = {}
        self._lock = threading.Lock()
        self._connect_locks: Dict[str, threading.Lock] = {}
        # a dead peer's cached client holds a dead connection; evicting it
        # here makes the next client_for() reconnect instead of failing
        # every future fetch against a corpse socket
        self.transport.add_peer_lost_listener(self.invalidate_client)

    def client_for(self, peer_executor_id: str) -> ShuffleClient:
        # connect() blocks (TCP handshake + registry polling, up to 30 s):
        # holding the client-table lock across it would serialize every
        # fetch in the process behind the slowest peer. A per-peer connect
        # lock serializes only callers of the SAME unconnected peer, so no
        # duplicate connection is ever created (a dropped loser would leak
        # its socket + reader thread and desync the transport peer table).
        with self._lock:
            c = self._clients.get(peer_executor_id)
            if c is not None:
                return c
            plock = self._connect_locks.setdefault(peer_executor_id,
                                                   threading.Lock())
        with plock:
            with self._lock:
                c = self._clients.get(peer_executor_id)
                if c is not None:
                    return c
            # justified block-under-lock: plock guards one peer's connect
            # only; other peers never contend  # tpu-lint: disable=R006
            conn = self.transport.connect(peer_executor_id)
            c = ShuffleClient(self.transport, conn, self.received_catalog,
                              self.conf.shuffle_codec)
            with self._lock:
                self._clients[peer_executor_id] = c
            return c

    def invalidate_client(self, peer_executor_id: str) -> None:
        """Drop the cached client for a peer whose connection died
        (peer-lost listener target), so the next client_for() reconnects.
        The per-peer connect LOCK is kept: replacing it while an in-flight
        connect holds the old one would let a second caller dial a
        duplicate connection (leaked socket + reader thread, desynced peer
        table); the lock is tiny and reusable across reconnects. Safe to
        call for unknown peers."""
        from spark_rapids_tpu.utils import metrics as mt
        with self._lock:
            evicted = self._clients.pop(peer_executor_id, None)
        if evicted is not None:
            self.metrics[mt.SHUFFLE_PEER_EVICTIONS].add(1)

    def close(self) -> None:
        self.transport.shutdown()
        self.device_store.close()
        self.host_store.close()
        self.disk_store.close()


class CachingShuffleWriter:
    """Map-side writer: cache each partition's device batch + register meta
    (RapidsCachingWriter analog)."""

    def __init__(self, env: ShuffleEnv, tracker: MapOutputTracker,
                 shuffle_id: int, map_id: int, num_partitions: int):
        self.env = env
        self.tracker = tracker
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.num_partitions = num_partitions

    def write(self, partitions: Iterable[Tuple[int, object]]) -> MapStatus:
        """``partitions`` yields (partition_id, DeviceBatch). Batches with zero
        rows are recorded as empty (DegenerateRapidsBuffer analog: size 0)."""
        sizes = [0] * self.num_partitions
        for pid, batch in partitions:
            if batch.num_rows == 0:
                continue
            batch = uniform_string_batch(batch)
            layout = DevicePackLayout.for_batch_shape(
                batch.schema, batch.capacity, batch_string_max(batch))
            meta = layout_to_meta(layout, batch.num_rows)
            block = ShuffleBlockId(self.shuffle_id, self.map_id, pid)
            self.env.shuffle_catalog.add_batch(block, batch, meta)
            sizes[pid] += meta.packed_size
        status = MapStatus(self.env.executor_id, self.map_id, tuple(sizes))
        self.tracker.register_map_output(self.shuffle_id, status)
        return status


class _QueueHandler(ShuffleFetchHandler):
    """Bridges async client callbacks into the iterator's blocking queue."""

    def __init__(self, q: "queue.Queue", peer: str):
        self.q = q
        self.peer = peer

    def start(self, expected_tables: int, tables=()) -> None:
        self.q.put(("start", self.peer, tuple(tables)))

    def batch_received(self, received_id: int, block=None,
                       table_idx: int = 0) -> None:
        self.q.put(("batch", self.peer, (received_id, block, table_idx)))

    def transfer_error(self, message: str, failed_blocks=(),
                       permanent: bool = False) -> None:
        self.q.put(("error", self.peer,
                    (message, tuple(failed_blocks), permanent)))


class _PeerFetch:
    """One peer's fetch-in-progress: the blocks still owed, the tables the
    current attempt will deliver (None until its metadata lands), and how
    many attempts were spent."""

    def __init__(self, blocks):
        self.blocks = list(blocks)
        self.needed = None    # set[(block, table_idx)] of the current attempt
        self.attempts = 0

    def done(self, delivered) -> bool:
        return self.needed is not None and self.needed <= delivered


class CachingShuffleReader:
    """Reduce-side reader (RapidsCachingReader + RapidsShuffleIterator analog):
    local blocks come straight off the catalog (device tier → zero-copy), remote
    blocks are fetched via the transport client, uploaded on arrival.

    Failure handling: when a peer's fetch errors (connection drop, repeated
    corruption, handler failure beyond the client's own retries), the reader
    retries THAT peer — reconnecting through client_for (the dead client was
    evicted by the peer-lost listener) and re-fetching only the blocks the
    error reported undelivered. Tables are deduplicated by (block, table_idx),
    so a retry racing a late delivery (or a duplicated frame) never yields a
    row twice. Only after maxRetries per peer (or immediately for permanent
    failures — lost blocks that only a map recompute brings back) does
    ShuffleFetchFailedError surface, scoped to the failing executor +
    blocks. The fetch timeout is one overall WAIT budget across the whole
    drain — a trickling-but-stuck fetch cannot reset it per event."""

    def __init__(self, env: ShuffleEnv, tracker: MapOutputTracker,
                 shuffle_id: int, partition_id: int, semaphore=None,
                 timeout: Optional[float] = None):
        from spark_rapids_tpu import config as _cfg
        self.env = env
        self.tracker = tracker
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        self.semaphore = semaphore
        self.timeout = (timeout if timeout is not None
                        else float(env.conf.get(_cfg.SHUFFLE_FETCH_TIMEOUT)))
        self.max_retries = env.conf.shuffle_max_retries
        self.backoff_ms = env.conf.shuffle_retry_backoff_ms
        self.retry_seed = env.conf.shuffle_faults_seed

    def read(self):
        """Yields DeviceBatch for this reduce partition."""
        import time as _time

        from spark_rapids_tpu.shuffle import retry as _retry
        from spark_rapids_tpu.utils import metrics as mt
        from spark_rapids_tpu.utils import tracing as _tracing
        by_exec = self.tracker.blocks_by_executor(self.shuffle_id,
                                                  self.partition_id)
        local_blocks = by_exec.pop(self.env.executor_id, [])
        t_fetch_ns = _time.perf_counter_ns() if by_exec else 0

        # kick off remote fetches first (overlap with local materialization)
        q: "queue.Queue" = queue.Queue()
        peers: Dict[str, _PeerFetch] = {}
        for peer, blocks in by_exec.items():
            peers[peer] = _PeerFetch(blocks)
            self._start_fetch(q, peer, blocks)

        # SCOPED hold (R008 fix): the old bare acquire_if_necessary never
        # released, so a reader driven outside a task's held() scope pinned
        # a device permit for the thread's lifetime. held() nests when the
        # owning task already holds (the normal exec path) and releases at
        # generator close when this reader was the first acquirer.
        hold = (self.semaphore.held() if self.semaphore is not None
                else nullcontext())
        with hold:
            for block in local_blocks:
                # acquire_buffers retains EVERY buffer of the block upfront;
                # an early generator close (LIMIT downstream) must release
                # the not-yet-yielded tail, not just the buffer in hand
                acquired = self.env.shuffle_catalog.acquire_buffers(block)
                try:
                    while acquired:
                        buf, _meta = acquired.pop(0)
                        try:
                            yield buf.get_batch()
                        finally:
                            buf.close()
                finally:
                    for buf, _meta in acquired:
                        buf.close()

            # drain remote results under ONE overall WAIT budget: the
            # timeout counts only time this reader spends blocked on the
            # fetch (queue waits + retry backoffs), never the consumer's
            # compute between yields — a slow join downstream must not fake
            # a fetch failure, while a trickling-but-stuck fetch still
            # exhausts the budget
            wait_budget = self.timeout
            delivered: set = set()  # (block, table_idx) pairs yielded already
            while not all(st.done(delivered) for st in peers.values()):
                if wait_budget <= 0:
                    self._raise_timeout(peers, delivered)
                t0 = _time.monotonic()
                try:
                    kind, peer, value = q.get(timeout=wait_budget)
                except queue.Empty:
                    self._raise_timeout(peers, delivered)
                finally:
                    wait_budget -= _time.monotonic() - t0
                st = peers[peer]
                if kind == "start":
                    st.needed = set(value)
                elif kind == "error":
                    message, failed_blocks, permanent = value
                    st.attempts += 1
                    if permanent or st.attempts > self.max_retries:
                        raise ShuffleFetchFailedError(
                            f"fetch from {peer} failed after {st.attempts} "
                            f"attempts: {message}", executor_id=peer,
                            blocks=tuple(failed_blocks) or tuple(st.blocks))
                    self.env.metrics[mt.SHUFFLE_FETCH_RETRIES].add(1)
                    _tracing.instant("shuffle.fetch_retry", "shuffle",
                                     {"peer": peer, "attempt": st.attempts,
                                      "shuffle_id": self.shuffle_id})
                    # bounded pause, then re-fetch only the undelivered
                    # blocks on a fresh client (the dead one was evicted on
                    # peer loss)
                    pause = min(
                        _retry.backoff_ms(st.attempts - 1, self.backoff_ms,
                                          self.retry_seed,
                                          key=f"read:{peer}") / 1e3,
                        max(wait_budget, 0))
                    _time.sleep(pause)
                    wait_budget -= pause
                    if failed_blocks:
                        st.blocks = list(failed_blocks)
                    st.needed = None
                    self._start_fetch(q, peer, st.blocks)
                else:
                    rid, block, table_idx = value
                    raw, meta = self.env.received_catalog.take(rid)
                    if (block, table_idx) in delivered:
                        continue    # duplicate from a retried/duped transfer
                    delivered.add((block, table_idx))
                    hb = unpack_host_batch(raw, meta)
                    yield host_to_device_batch(hb)
            if t_fetch_ns and _tracing.TRACER.on:
                # the remote-drain window (start-of-fetch -> last block in;
                # consumer compute between yields is included — it is a
                # window, not busy time; retries show as instants inside)
                _tracing.record(
                    "shuffle.fetch", "shuffle", t_fetch_ns,
                    _time.perf_counter_ns() - t_fetch_ns,
                    {"peers": len(peers), "blocks_delivered": len(delivered),
                     "shuffle_id": self.shuffle_id,
                     "partition": self.partition_id})

    def _start_fetch(self, q: "queue.Queue", peer: str, blocks) -> None:
        """Kick off (or re-kick after an error) one peer's fetch. A connect
        failure — client_for dialing a dead peer past ITS retries — is not
        an unscoped crash: it queues as an error event, so it consumes a
        reader-level attempt like any other transient and surfaces as a
        scoped ShuffleFetchFailedError once those run out."""
        try:
            client = self.env.client_for(peer)
        except (ConnectionError, OSError) as e:
            q.put(("error", peer,
                   (f"connect failed: {e}", tuple(blocks), False)))
            return
        client.fetch(blocks, _QueueHandler(q, peer))

    def _raise_timeout(self, peers: Dict[str, "_PeerFetch"],
                       delivered: set) -> None:
        stuck = {p: [b for b in st.blocks
                     if st.needed is None
                     or any(k not in delivered for k in st.needed
                            if k[0] == b)]
                 for p, st in peers.items() if not st.done(delivered)}
        peer = next(iter(stuck), None)
        raise ShuffleFetchFailedError(
            f"shuffle {self.shuffle_id} partition {self.partition_id}: "
            f"timed out after {self.timeout}s waiting for remote blocks "
            f"from {sorted(stuck)}", executor_id=peer,
            blocks=tuple(stuck.get(peer, ())))


class ShuffleManager:
    """Driver-facing registry (RapidsShuffleInternalManagerBase analog)."""

    def __init__(self, tracker: Optional[MapOutputTracker] = None):
        self.tracker = tracker or MapOutputTracker()
        self._next_shuffle = 0
        self._lock = threading.Lock()

    def register_shuffle(self, num_partitions: int) -> Tuple[int, int]:
        with self._lock:
            sid = self._next_shuffle
            self._next_shuffle += 1
        self.tracker.register_shuffle(sid)
        return sid, num_partitions

    def get_writer(self, env: ShuffleEnv, shuffle_id: int, map_id: int,
                   num_partitions: int) -> CachingShuffleWriter:
        return CachingShuffleWriter(env, self.tracker, shuffle_id, map_id,
                                    num_partitions)

    def get_reader(self, env: ShuffleEnv, shuffle_id: int, partition_id: int,
                   semaphore=None) -> CachingShuffleReader:
        return CachingShuffleReader(env, self.tracker, shuffle_id,
                                    partition_id, semaphore)

    def unregister_shuffle(self, shuffle_id: int,
                           envs: Iterable[ShuffleEnv] = ()) -> None:
        self.tracker.unregister_shuffle(shuffle_id)
        for env in envs:
            env.shuffle_catalog.remove_shuffle(shuffle_id)
