"""Retry/backoff schedule shared by every layer of the shuffle stack.

Reference analog: Spark's RetryingBlockTransferor (network-shuffle) — a
bounded number of retries with a backoff between attempts. Two deltas for
this engine:

- **deterministic jitter**: attempt i of key k sleeps
  ``base * 2^i * (0.5 + u)`` where ``u`` is drawn from a PRNG seeded by
  ``(seed, key, i)``. Reducers retrying against one recovering peer spread
  out (no stampede), yet a fixed seed replays the exact same schedule —
  the property the fault-injection tests assert on.
- **off-thread re-issue**: transports complete transactions on their
  progress threads; sleeping there would head-of-line-block every other
  completion. ``call_later`` runs the retry continuation on a daemon timer
  thread instead.
"""
from __future__ import annotations

import random
import threading
from typing import Callable, List

#: retries are meant for *transient* faults; one attempt never waits more
#: than this regardless of the exponential schedule (10 s)
MAX_BACKOFF_MS = 10_000.0


def backoff_ms(attempt: int, base_ms: float, seed: int = 0,
               key: str = "") -> float:
    """Delay in milliseconds before retry ``attempt`` (0-based: the delay
    between the initial try and the first retry is attempt 0)."""
    rng = random.Random(f"{seed}:{key}:{attempt}")
    raw = base_ms * (2 ** attempt) * (0.5 + rng.random())
    return min(raw, MAX_BACKOFF_MS)


def backoff_schedule(max_retries: int, base_ms: float, seed: int = 0,
                     key: str = "") -> List[float]:
    """The full delay schedule (milliseconds) for ``max_retries`` retries."""
    return [backoff_ms(i, base_ms, seed, key) for i in range(max_retries)]


def call_later(delay_ms: float, fn: Callable[[], None]) -> threading.Timer:
    """Run ``fn`` after ``delay_ms`` on a daemon timer thread — never on the
    caller (which is typically a transport progress/reader thread that must
    keep draining completions)."""
    t = threading.Timer(max(delay_ms, 0.0) / 1e3, fn)
    t.daemon = True
    t.start()
    return t
