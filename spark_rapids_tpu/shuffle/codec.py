"""Pluggable batch compression for shuffle buffers.

Reference analogs: TableCompressionCodec.scala:42 (trait + registry getCodec:100)
with batched compressor/decompressor (BatchedTableCompressor:127,
BatchedBufferDecompressor:297), and CopyCompressionCodec.scala (memcpy
pseudo-codec). The reference compresses on-device via cuDF; here compression is
a host-side stage of the transfer pipeline (TPU has no general-purpose
device codec), so codecs operate on the packed host buffer between
pack_host_batch and the transport send.
"""
from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.shuffle.table_meta import (  # noqa: F401 - re-export
    ChecksumError, TableMeta)


def checksum_of(buf: bytes) -> int:
    """crc32 (unsigned) over a packed/on-wire buffer."""
    return zlib.crc32(buf) & 0xFFFFFFFF


def verify_checksum(buf: bytes, expected: int, context: str = "") -> None:
    """Raise ChecksumError unless ``buf`` hashes to ``expected``.
    ``expected == 0`` means "not computed" and is never checked (crc32 of
    real payloads hitting exactly 0 is a 2^-32 event; senders always fill
    the field, so 0 only appears for legacy/device-layout metas)."""
    if expected == 0:
        return
    actual = checksum_of(buf)
    if actual != expected:
        raise ChecksumError(
            f"shuffle payload checksum mismatch{': ' + context if context else ''}"
            f" (expected {expected:#010x}, got {actual:#010x}, "
            f"{len(buf)} bytes)")


class TableCompressionCodec:
    """One codec. ``name`` is recorded in TableMeta.codec on the wire."""

    name: str = "?"

    def compress(self, buf: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, buf: bytes, uncompressed_size: int) -> bytes:
        raise NotImplementedError


class CopyCodec(TableCompressionCodec):
    """Pass-through (CopyCompressionCodec analog)."""

    name = "copy"

    def compress(self, buf: bytes) -> bytes:
        return buf

    def decompress(self, buf: bytes, uncompressed_size: int) -> bytes:
        if len(buf) != uncompressed_size:
            raise ValueError(f"copy codec size mismatch: {len(buf)} != "
                             f"{uncompressed_size}")
        return buf


class ZlibCodec(TableCompressionCodec):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, buf: bytes) -> bytes:
        return zlib.compress(buf, self.level)

    def decompress(self, buf: bytes, uncompressed_size: int) -> bytes:
        out = zlib.decompress(buf)
        if len(out) != uncompressed_size:
            raise ValueError(f"zlib decompressed to {len(out)}, expected "
                             f"{uncompressed_size}")
        return out


class ZstdCodec(TableCompressionCodec):
    """zstd at low level: ~5-10x zlib's speed at similar ratios — the right
    default for a network-bound DCN shuffle (the reference ships only the
    copy pseudo-codec in-repo; real codecs live in cuDF).

    (De)compressor objects are built PER CALL: zstandard contexts are not
    thread-safe and the shuffle server runs request handlers on a worker
    pool, all sharing the registry's codec instance."""

    name = "zstd"

    def __init__(self, level: int = 1):
        import zstandard
        self._zstd = zstandard
        self.level = level

    def compress(self, buf: bytes) -> bytes:
        return self._zstd.ZstdCompressor(level=self.level).compress(buf)

    def decompress(self, buf: bytes, uncompressed_size: int) -> bytes:
        out = self._zstd.ZstdDecompressor().decompress(
            buf, max_output_size=uncompressed_size)
        if len(out) != uncompressed_size:
            raise ValueError(f"zstd decompressed to {len(out)}, expected "
                             f"{uncompressed_size}")
        return out


class Lz4Codec(TableCompressionCodec):
    """LZ4 block format — always available: shuffle/lz4.py carries a pure-
    Python implementation and upgrades to the C ``lz4.block`` package when
    installed (both speak the standard block format, so mixed peers
    interoperate). The right default for network-bound shuffles that cannot
    assume zstandard on every executor."""

    name = "lz4"

    def compress(self, buf: bytes) -> bytes:
        from spark_rapids_tpu.shuffle import lz4
        return lz4.compress(buf)

    def decompress(self, buf: bytes, uncompressed_size: int) -> bytes:
        from spark_rapids_tpu.shuffle import lz4
        out = lz4.decompress(buf, uncompressed_size)
        if len(out) != uncompressed_size:
            raise ValueError(f"lz4 decompressed to {len(out)}, expected "
                             f"{uncompressed_size}")
        return out


def _zlib_factory(conf) -> TableCompressionCodec:
    from spark_rapids_tpu import config as cfg
    level = conf.get(cfg.SHUFFLE_ZLIB_LEVEL) if conf is not None else 1
    return ZlibCodec(level)


#: THE codec registry: one name->factory table shared by the client (which
#: validates its configured codec at construction) and the server (which
#: resolves each TransferRequest's codec) — TableCompressionCodec.getCodec
#: analog. A factory may raise ImportError for an uninstalled backend.
_REGISTRY: Dict[str, Callable[[Optional[object]],
                              TableCompressionCodec]] = {}


def register_codec(name: str,
                   factory: Callable[[Optional[object]],
                                     TableCompressionCodec]) -> None:
    _REGISTRY[name.lower()] = factory


register_codec("copy", lambda conf: CopyCodec())
register_codec("none", lambda conf: CopyCodec())
register_codec("zlib", _zlib_factory)
register_codec("zstd", lambda conf: ZstdCodec())
register_codec("lz4", lambda conf: Lz4Codec())


def codec_available(name: str) -> bool:
    """Can this executor actually construct the named codec? (The server's
    negotiation check: a requested codec that fails here degrades the
    transfer to 'copy' instead of failing it.)"""
    factory = _REGISTRY.get(name.lower())
    if factory is None:
        return False
    try:
        factory(None)
        return True
    except ImportError:
        return False


def available_codecs() -> List[str]:
    return sorted(n for n in _REGISTRY if codec_available(n))


def get_codec(name: str, conf=None) -> TableCompressionCodec:
    """Registry lookup (TableCompressionCodec.getCodec analog): ONE
    well-formed error for an unknown or unavailable codec name, raised at
    configuration/validation time instead of deep inside a decompress."""
    factory = _REGISTRY.get(name.lower())
    if factory is None:
        raise ValueError(f"unknown shuffle codec {name!r}; known: "
                         f"{sorted(_REGISTRY)}")
    try:
        return factory(conf)
    except ImportError as e:
        raise ValueError(f"shuffle codec {name!r} is not available on this "
                         f"executor ({e}); install its backend or pick one "
                         f"of {available_codecs()}") from None


def compress_batch(buf: bytes, meta: TableMeta,
                   codec: TableCompressionCodec) -> Tuple[bytes, TableMeta]:
    """One table through the codec, meta updated (BatchedTableCompressor analog,
    minus the device temp-space estimation which host codecs don't need)."""
    if isinstance(codec, CopyCodec):
        return buf, meta
    out = codec.compress(buf)
    return out, meta.with_codec(codec.name, len(out))


def decompress_batch(buf: bytes, meta: TableMeta) -> Tuple[bytes, TableMeta]:
    """Inverse of compress_batch (BatchedBufferDecompressor analog)."""
    if meta.codec == "copy":
        return buf, meta
    codec = get_codec(meta.codec)
    out = codec.decompress(buf, meta.uncompressed_size)
    return out, meta.with_codec("copy", len(out))
