"""ICI shuffle: hash repartition as ONE XLA all_to_all over the device mesh.

This is the TPU-native replacement for the reference's UCX RDMA data plane
(shuffle-plugin ucx/UCX.scala): when every reduce partition lives on a device
of the same SPMD program, the entire map->reduce exchange is a single
compiled collective riding the inter-chip interconnect — no host round-trip,
no bounce buffers, no tag matching. The in-process/DCN transport (client.py/
server.py) remains the path for cross-program topologies, exactly as the
reference keeps a host fallback next to UCX.

Kernel design (all static shapes, no data-dependent control flow):
1. per device, stable-argsort local rows by target partition id — the
   Table.partition + contiguousSplit analog (GpuPartitioning.scala:44-75);
2. slice the sorted rows into n_dev fixed-capacity chunks via one gather
   (chunk j = rows destined for device j, padded to chunk_capacity);
3. lax.all_to_all every column buffer (XLA fuses the per-column collectives
   into few ICI transfers) plus the per-chunk row counts;
4. compact received chunks to the front with one more stable argsort, so the
   output batch obeys the padding invariant (live rows first).

Skew bound: a device can receive at most n_dev * chunk_capacity rows. Rows
beyond chunk_capacity for one destination on one source device cannot ride
that exchange, so the program RETURNS an overflow count (the collision-flag
pattern of the aggregation fast path): callers must check it and re-run with
a larger chunk capacity — ``ici_repartition`` below does exactly that,
doubling until clean. The default chunk_capacity = local_capacity is always
safe because a source holds only local_capacity rows.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DType, Schema
from spark_rapids_tpu.exprs.core import (ColV, flat_len, flatten_colvs,
                                         unflatten_colvs)


def _a2a(x, axis: str):
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)


def build_ici_repartition(mesh: Mesh, schema: Schema, local_capacity: int,
                          chunk_capacity: Optional[int] = None,
                          axis: str = "data"):
    """Build the jitted SPMD repartition step.

    Returns fn(num_rows_local [n_dev] int32, pids [n_dev*cap] int32 sharded,
    *flat sharded column arrays) -> (out_rows [n_dev] int32,
    overflow_rows [] int32 replicated, *flat resharded columns with capacity
    n_dev*chunk_capacity per device).

    ``pids`` is the target partition id per row (device index), computed by the
    caller from hash exprs — the GpuHashPartitioning.columnarEval analog.
    ``overflow_rows`` counts rows clamped away by chunk_capacity across ALL
    devices; a nonzero value means the output is incomplete and the exchange
    must re-run with a larger chunk capacity (never ignore it — that is
    silent row loss).
    """
    n_dev = int(np.prod([mesh.shape[a] for a in (axis,)]))
    chunk_cap = chunk_capacity or local_capacity
    out_cap = n_dev * chunk_cap

    def local_step(num_rows_local, pids, *flat_local):
        colvs = unflatten_colvs(schema, flat_local)
        my_rows = num_rows_local[0]
        live = jnp.arange(local_capacity, dtype=np.int32) < my_rows
        pid = jnp.where(live, pids, n_dev)        # dead rows -> sentinel bucket

        # 1. group rows by destination (stable keeps intra-partition order)
        order = jnp.argsort(pid, stable=True)
        sorted_pid = pid[order]

        # 2. chunk index matrix [n_dev, chunk_cap]
        counts = jnp.sum(
            (sorted_pid[None, :] == jnp.arange(n_dev, dtype=np.int32)[:, None]),
            axis=1, dtype=np.int32)               # rows per destination
        starts = jnp.concatenate(
            [jnp.zeros((1,), np.int32), jnp.cumsum(counts)[:-1].astype(np.int32)])
        offsets = jnp.arange(chunk_cap, dtype=np.int32)[None, :]
        idx = jnp.clip(starts[:, None] + offsets, 0, local_capacity - 1)
        within = offsets < counts[:, None]        # [n_dev, chunk_cap]
        sent = jnp.minimum(counts, chunk_cap)     # overflow clamps (flagged)
        # clamped rows are DETECTED, not silently dropped: global count of
        # rows that could not ride this exchange, replicated to every device
        overflow = jax.lax.psum(
            jnp.sum(counts - sent).astype(np.int32), axis)
        gidx = order[idx]                         # chunk row -> original row

        # 3. exchange: counts + every column buffer
        recv_counts = _a2a(sent, axis)            # [n_dev] rows from each peer
        out_cols = []
        for v in colvs:
            data = _a2a(v.data[gidx], axis)
            validity = _a2a(v.validity[gidx] & within, axis)
            lengths = (_a2a(jnp.where(within, v.lengths[gidx], 0), axis)
                       if v.lengths is not None else None)
            out_cols.append((v.dtype, data, validity, lengths))

        # 4. compact received rows to the front (padding invariant)
        recv_live = (jnp.arange(chunk_cap, dtype=np.int32)[None, :]
                     < recv_counts[:, None]).reshape(out_cap)
        corder = jnp.argsort(~recv_live, stable=True)
        total = jnp.sum(recv_counts).astype(np.int32)
        compacted = []
        for dt, data, validity, lengths in out_cols:
            flat_shape = (out_cap,) + data.shape[2:]
            compacted.append(ColV(
                dt, data.reshape(flat_shape)[corder],
                validity.reshape(out_cap)[corder],
                lengths.reshape(out_cap)[corder] if lengths is not None else None))
        return (total[None], overflow) + tuple(flatten_colvs(compacted))

    nflat = flat_len(schema)
    in_specs = (P(axis), P(axis)) + tuple(P(axis) for _ in range(nflat))
    out_specs = (P(axis), P()) + tuple(P(axis) for _ in range(nflat))
    # cached per (mesh, schema, capacities): same-shaped batch streams reuse
    # the compiled exchange instead of paying XLA compilation per call
    from spark_rapids_tpu.execs.tpu_execs import _cached_jit
    from spark_rapids_tpu import shims
    # shim resolved here, once: its identity is part of the key, so a
    # provider swap can never serve the old backend's program (R016)
    shim = shims.get()
    key = ("ici-repart", type(shim).__name__, mesh, schema, local_capacity,
           chunk_cap, axis)
    return _cached_jit(key, lambda: shim.shard_map(
        local_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False))


def ici_repartition(mesh: Mesh, schema: Schema, local_capacity: int,
                    num_rows_local, pids, flat_cols,
                    chunk_capacity: Optional[int] = None,
                    axis: str = "data"):
    """Overflow-safe repartition driver: runs the exchange, checks the
    overflow flag, and re-runs with a doubled chunk capacity until no row was
    clamped (the detect-and-re-run pattern of the aggregation hash fast
    path). Returns (out_rows [n_dev], flat resharded columns)."""
    global RERUN_COUNT
    chunk = chunk_capacity or local_capacity
    while True:
        fn = build_ici_repartition(mesh, schema, local_capacity,
                                   chunk_capacity=chunk, axis=axis)
        res = fn(num_rows_local, pids, *flat_cols)
        if int(res[1]) == 0:
            return res[0], res[2:]
        if chunk >= local_capacity:
            raise AssertionError(
                "ici repartition overflowed at chunk_capacity == "
                "local_capacity — impossible unless inputs violate the "
                "padding invariant")
        chunk = min(chunk * 2, local_capacity)
        RERUN_COUNT += 1


#: process-wide count of overflow-triggered re-runs (fault-path
#: observability; tests assert the detect-and-re-run loop really fires)
RERUN_COUNT = 0
