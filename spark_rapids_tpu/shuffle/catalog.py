"""Shuffle buffer catalogs: shuffle-block-id -> spillable buffer mapping.

Reference analogs: ShuffleBufferCatalog.scala (shuffleId -> bufferIds over the
RapidsBufferCatalog, 222 LoC) and ShuffleReceivedBufferCatalog.scala (119 LoC)
for client-received buffers. Buffers live in the tiered store chain (memory/
store.py) so cached shuffle data spills HBM -> host -> disk under pressure,
exactly like the reference's device-store-backed shuffle cache.
"""
from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.memory.buffer import BufferId, SpillableBuffer
from spark_rapids_tpu.memory.store import (BufferCatalog, DeviceMemoryStore,
                                           SHUFFLE_BUFFER_PRIORITY)
from spark_rapids_tpu.shuffle.table_meta import TableMeta


@dataclass(frozen=True, order=True)
class ShuffleBlockId:
    """(shuffle, map, partition) address of one cached batch
    (ShuffleBufferId analog)."""
    shuffle_id: int
    map_id: int
    partition_id: int


class ShuffleBufferCatalog:
    """Maps shuffle block ids to buffer-store ids + TableMeta; owns the
    registration/removal lifecycle for the map-side shuffle cache."""

    _ids = itertools.count(1 << 20)   # table_id namespace distinct from execs

    def __init__(self, catalog: BufferCatalog, device_store: DeviceMemoryStore):
        self._catalog = catalog
        self._device_store = device_store
        self._lock = threading.RLock()
        self._blocks: Dict[ShuffleBlockId, List[Tuple[BufferId, TableMeta]]] = {}
        self._by_shuffle: Dict[int, List[ShuffleBlockId]] = {}

    def add_batch(self, block: ShuffleBlockId, batch, meta: TableMeta) -> BufferId:
        """Cache one device batch for ``block`` in the spillable device store."""
        buffer_id = BufferId(next(self._ids), block.partition_id)
        self._device_store.add_batch(buffer_id, batch,
                                     spill_priority=SHUFFLE_BUFFER_PRIORITY)
        with self._lock:
            entries = self._blocks.setdefault(block, [])
            if not entries:
                # one index entry per block id: a map task emitting SEVERAL
                # batches for the same (map, partition) block appends extra
                # buffers to the block, not duplicate index entries —
                # blocks_for_partition would otherwise hand consumers the
                # block once per batch and every buffer re-reads N times
                self._by_shuffle.setdefault(block.shuffle_id, []).append(block)
            entries.append((buffer_id, meta))
        return buffer_id

    def blocks_for_partition(self, shuffle_id: int,
                             partition_id: int) -> List[ShuffleBlockId]:
        with self._lock:
            return [b for b in self._by_shuffle.get(shuffle_id, [])
                    if b.partition_id == partition_id]

    def metas(self, block: ShuffleBlockId) -> List[TableMeta]:
        with self._lock:
            return [m for _, m in self._blocks.get(block, [])]

    def acquire_buffers(self, block: ShuffleBlockId
                        ) -> List[Tuple[SpillableBuffer, TableMeta]]:
        """Acquire (retain) every buffer of a block, fastest tier first;
        callers close() each buffer after use."""
        with self._lock:
            entries = list(self._blocks.get(block, []))
        out = []
        buf = None
        try:
            for buffer_id, meta in entries:
                buf = self._catalog.acquire(buffer_id)
                if buf is None:
                    raise KeyError(
                        f"shuffle buffer {buffer_id} vanished for {block}")
                out.append((buf, meta))
                buf = None      # handed off to `out`; the except owns it not
        except BaseException:
            # a later acquire failing must not strand the refcounts the
            # earlier ones already took (found during the R008 audit)
            if buf is not None:
                buf.close()
            for b, _m in out:
                b.close()
            raise
        return out

    def remove_map_outputs(self, shuffle_id: int, map_id: int) -> int:
        """Unregister every block one map task produced — the exactly-once
        half of lineage recompute: a replayed map task REPLACES its old
        blocks (this call, then fresh add_batch registrations) instead of
        appending to them, so a recompute landing on an executor that
        still holds stale entries can never double rows for a later
        reader. Readers that already consumed the old buffers are safe —
        their (block, table_idx) dedup is per-read() and a removed buffer
        stays alive until its refcount drains."""
        with self._lock:
            keep, victims = [], []
            for block in self._by_shuffle.get(shuffle_id, []):
                (victims if block.map_id == map_id else keep).append(block)
            if not victims:
                return 0
            self._by_shuffle[shuffle_id] = keep
            removed = 0
            for block in victims:
                for buffer_id, _ in self._blocks.pop(block, []):
                    buf = self._catalog.acquire(buffer_id)
                    if buf is not None:
                        owner = buf.owner_store or self._device_store
                        buf.close()
                        owner.remove(buffer_id)
                        removed += 1
            return removed

    def remove_shuffle(self, shuffle_id: int) -> int:
        """Unregister a completed shuffle (unregisterShuffle analog)."""
        with self._lock:
            blocks = self._by_shuffle.pop(shuffle_id, [])
            removed = 0
            for block in blocks:
                for buffer_id, _ in self._blocks.pop(block, []):
                    store = self._device_store
                    # the buffer may have spilled; remove wherever it lives now
                    buf = self._catalog.acquire(buffer_id)
                    if buf is not None:
                        owner = buf.owner_store or store
                        buf.close()
                        owner.remove(buffer_id)
                        removed += 1
            return removed


class ReceivedBufferCatalog:
    """Client-side catalog of fetched buffers (ShuffleReceivedBufferCatalog
    analog): holds host-packed buffers + metas until the task materializes them."""

    _ids = itertools.count()

    def __init__(self):
        self._lock = threading.Lock()
        self._received: Dict[int, Tuple[bytes, TableMeta]] = {}

    def add(self, buf: bytes, meta: TableMeta) -> int:
        with self._lock:
            rid = next(self._ids)
            self._received[rid] = (buf, meta)
            return rid

    def take(self, rid: int) -> Tuple[bytes, TableMeta]:
        with self._lock:
            return self._received.pop(rid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._received)
