"""Shuffle control-plane messages (struct-packed wire format).

Reference analog: the flatbuffer shuffle messages in MetaUtils.scala
ShuffleMetadata:247 + format/*.fbs — MetadataRequest/Response,
TransferRequest/Response. Same message set, struct packing instead of
flatbuffers (no codegen toolchain needed, format versioned by MAGIC/VERSION
in table_meta)."""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Sequence, Tuple

from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId
from spark_rapids_tpu.shuffle.table_meta import TableMeta

REQ_METADATA = "metadata"
REQ_TRANSFER = "transfer"

_BLOCK = struct.Struct("<III")          # shuffle_id, map_id, partition_id
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _pack_block(b: ShuffleBlockId) -> bytes:
    return _BLOCK.pack(b.shuffle_id, b.map_id, b.partition_id)


def _unpack_block(buf: bytes, pos: int) -> Tuple[ShuffleBlockId, int]:
    s, m, p = _BLOCK.unpack_from(buf, pos)
    return ShuffleBlockId(s, m, p), pos + _BLOCK.size


@dataclass(frozen=True)
class MetadataRequest:
    """Reducer asks a peer for the TableMetas of its blocks for one partition."""
    shuffle_id: int
    partition_id: int
    blocks: Tuple[ShuffleBlockId, ...]

    def to_bytes(self) -> bytes:
        out = bytearray(_U32.pack(self.shuffle_id) + _U32.pack(self.partition_id)
                        + _U32.pack(len(self.blocks)))
        for b in self.blocks:
            out += _pack_block(b)
        return bytes(out)

    @staticmethod
    def from_bytes(buf: bytes) -> "MetadataRequest":
        shuffle_id, = _U32.unpack_from(buf, 0)
        partition_id, = _U32.unpack_from(buf, 4)
        n, = _U32.unpack_from(buf, 8)
        pos = 12
        blocks = []
        for _ in range(n):
            b, pos = _unpack_block(buf, pos)
            blocks.append(b)
        return MetadataRequest(shuffle_id, partition_id, tuple(blocks))


@dataclass(frozen=True)
class MetadataResponse:
    """Per requested block: the TableMetas of its cached tables."""
    tables: Tuple[Tuple[ShuffleBlockId, int, TableMeta], ...]  # (block, table_idx, meta)

    def to_bytes(self) -> bytes:
        out = bytearray(_U32.pack(len(self.tables)))
        for block, idx, meta in self.tables:
            mb = meta.to_bytes()
            out += _pack_block(block) + _U32.pack(idx) + _U32.pack(len(mb)) + mb
        return bytes(out)

    @staticmethod
    def from_bytes(buf: bytes) -> "MetadataResponse":
        n, = _U32.unpack_from(buf, 0)
        pos = 4
        tables = []
        for _ in range(n):
            block, pos = _unpack_block(buf, pos)
            idx, = _U32.unpack_from(buf, pos); pos += 4
            mlen, = _U32.unpack_from(buf, pos); pos += 4
            meta = TableMeta.from_bytes(buf[pos:pos + mlen]); pos += mlen
            tables.append((block, idx, meta))
        return MetadataResponse(tuple(tables))


@dataclass(frozen=True)
class TransferRequest:
    """Reducer asks the peer to start sending one table's packed buffer as
    chunked, tag-addressed sends (BufferTransferRequest analog). ``base_tag``
    is the client-chosen tag of chunk 0; chunk i uses base_tag + i."""
    block: ShuffleBlockId
    table_idx: int
    base_tag: int
    chunk_size: int
    codec: str = "copy"

    def to_bytes(self) -> bytes:
        cb = self.codec.encode()
        return (_pack_block(self.block) + _U32.pack(self.table_idx)
                + _U64.pack(self.base_tag) + _U32.pack(self.chunk_size)
                + _U32.pack(len(cb)) + cb)

    @staticmethod
    def from_bytes(buf: bytes) -> "TransferRequest":
        block, pos = _unpack_block(buf, 0)
        idx, = _U32.unpack_from(buf, pos); pos += 4
        tag, = _U64.unpack_from(buf, pos); pos += 8
        chunk, = _U32.unpack_from(buf, pos); pos += 4
        clen, = _U32.unpack_from(buf, pos); pos += 4
        codec = buf[pos:pos + clen].decode()
        return TransferRequest(block, idx, tag, chunk, codec)


@dataclass(frozen=True)
class TransferResponse:
    """Ack carrying the on-wire size (post-compression) + updated meta, so the
    receiver sizes its target buffer and chunk walk before data arrives.
    ``checksum`` is the server's crc32 over the on-wire bytes — the client
    verifies the assembled buffer against it before decompressing, turning
    silent corruption into a retryable error."""
    wire_size: int
    meta: TableMeta
    checksum: int = 0

    def to_bytes(self) -> bytes:
        mb = self.meta.to_bytes()
        return (_U64.pack(self.wire_size) + _U32.pack(self.checksum)
                + _U32.pack(len(mb)) + mb)

    @staticmethod
    def from_bytes(buf: bytes) -> "TransferResponse":
        size, = _U64.unpack_from(buf, 0)
        crc, = _U32.unpack_from(buf, 8)
        mlen, = _U32.unpack_from(buf, 12)
        return TransferResponse(size, TableMeta.from_bytes(buf[16:16 + mlen]),
                                crc)
