"""Composable random data generation for tests and fuzzing.

Reference analog: integration_tests/src/main/python/data_gen.py (~700 LoC) —
per-type generator classes with weighted special cases feeding the CPU-vs-GPU
compare harness — and FuzzerUtils.scala (random schemas/batches for operator
fuzzing). Same shape here: every generator owns a dtype, a nullability, and a
special-case pool that gets mixed into the random stream, so the edge values
(int extremes, ±0.0, ±inf, NaN, empty/unicode strings, epoch boundaries) hit
every operator the fuzz tests drive.
"""
from __future__ import annotations

import datetime
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.dtypes import DType

_EPOCH = datetime.date(1970, 1, 1)


class DataGen:
    """Base generator: draws from ``_gen`` with ``special_cases`` mixed in at
    ``special_weight`` and nulls at ``null_weight`` when nullable."""

    def __init__(self, dtype: DType, pa_type, nullable: bool = True,
                 special_cases: Sequence = (), special_weight: float = 0.05,
                 null_weight: float = 0.08):
        self.dtype = dtype
        self.pa_type = pa_type
        self.nullable = nullable
        self.special_cases = list(special_cases)
        self.special_weight = special_weight
        self.null_weight = null_weight

    def _gen(self, rng: np.random.Generator):
        raise NotImplementedError

    def value(self, rng: np.random.Generator):
        if self.nullable and rng.random() < self.null_weight:
            return None
        if self.special_cases and rng.random() < self.special_weight:
            return self.special_cases[rng.integers(0, len(self.special_cases))]
        return self._gen(rng)

    def values(self, rng: np.random.Generator, n: int) -> list:
        return [self.value(rng) for _ in range(n)]

    def with_special_case(self, case, weight: Optional[float] = None) -> "DataGen":
        self.special_cases.append(case)
        if weight is not None:
            self.special_weight = weight
        return self


class _IntegralGen(DataGen):
    def __init__(self, dtype, pa_type, lo, hi, nullable=True,
                 min_val=None, max_val=None):
        lo = lo if min_val is None else max(lo, min_val)
        hi = hi if max_val is None else min(hi, max_val)
        super().__init__(dtype, pa_type, nullable,
                         special_cases=[0, 1, -1, lo, hi])
        self.lo, self.hi = lo, hi

    def _gen(self, rng):
        return int(rng.integers(self.lo, self.hi + 1))


class ByteGen(_IntegralGen):
    def __init__(self, nullable=True, min_val=None, max_val=None):
        super().__init__(DType.BYTE, pa.int8(), -128, 127, nullable,
                         min_val, max_val)


class ShortGen(_IntegralGen):
    def __init__(self, nullable=True, min_val=None, max_val=None):
        super().__init__(DType.SHORT, pa.int16(), -(2**15), 2**15 - 1,
                         nullable, min_val, max_val)


class IntegerGen(_IntegralGen):
    def __init__(self, nullable=True, min_val=None, max_val=None):
        super().__init__(DType.INT, pa.int32(), -(2**31), 2**31 - 1,
                         nullable, min_val, max_val)


class LongGen(_IntegralGen):
    def __init__(self, nullable=True, min_val=None, max_val=None):
        super().__init__(DType.LONG, pa.int64(), -(2**63), 2**63 - 1,
                         nullable, min_val, max_val)


class _FloatingGen(DataGen):
    def __init__(self, dtype, pa_type, nullable=True, no_nans=False):
        cases = [0.0, -0.0, 1.0, -1.0, 1e-30, -1e-30, float("inf"),
                 float("-inf")]
        if not no_nans:
            cases.append(float("nan"))
        super().__init__(dtype, pa_type, nullable, special_cases=cases)

    def _gen(self, rng):
        return float(np.round(rng.normal(0, 1e4), 6))


class FloatGen(_FloatingGen):
    def __init__(self, nullable=True, no_nans=False):
        super().__init__(DType.FLOAT, pa.float32(), nullable, no_nans)

    def _gen(self, rng):
        return float(np.float32(super()._gen(rng)))


class DoubleGen(_FloatingGen):
    def __init__(self, nullable=True, no_nans=False):
        super().__init__(DType.DOUBLE, pa.float64(), nullable, no_nans)


class BooleanGen(DataGen):
    def __init__(self, nullable=True):
        super().__init__(DType.BOOLEAN, pa.bool_(), nullable)

    def _gen(self, rng):
        return bool(rng.integers(0, 2))


class StringGen(DataGen):
    """Random strings from a charset (the reference drives sre_yield with a
    regex; a charset + length range covers the same operator surface without
    a regex engine). Unicode and empty strings ride the special-case pool."""

    def __init__(self, charset: str = ("abcdefghijklmnopqrstuvwxyz"
                                       "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 "),
                 min_len: int = 0, max_len: int = 20, nullable=True):
        super().__init__(DType.STRING, pa.string(), nullable,
                         special_cases=["", " ", "  leading", "trailing  ",
                                        "Ω≈ç√∫", "æøå", "\t", "0"])
        self.charset = charset
        self.min_len, self.max_len = min_len, max_len

    def _gen(self, rng):
        n = int(rng.integers(self.min_len, self.max_len + 1))
        idx = rng.integers(0, len(self.charset), n)
        return "".join(self.charset[i] for i in idx)


class DateGen(DataGen):
    def __init__(self, nullable=True,
                 start: datetime.date = datetime.date(1590, 1, 1),
                 end: datetime.date = datetime.date(2099, 12, 31)):
        super().__init__(DType.DATE, pa.date32(), nullable,
                         special_cases=[_EPOCH, start, end])
        self.lo = (start - _EPOCH).days
        self.hi = (end - _EPOCH).days

    def _gen(self, rng):
        return _EPOCH + datetime.timedelta(days=int(rng.integers(self.lo,
                                                                 self.hi + 1)))


class TimestampGen(DataGen):
    def __init__(self, nullable=True):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        super().__init__(DType.TIMESTAMP, pa.timestamp("us", tz="UTC"),
                         nullable, special_cases=[epoch])

    def _gen(self, rng):
        micros = int(rng.integers(-(2**40), 2**41))
        return (datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
                + datetime.timedelta(microseconds=micros))


class NullGen(DataGen):
    def __init__(self):
        super().__init__(DType.NULL, pa.null(), True)

    def value(self, rng):
        return None


#: generators with full-range values, the default fuzz pool (FuzzerUtils set)
ALL_GENS: List[Callable[[], DataGen]] = [
    ByteGen, ShortGen, IntegerGen, LongGen, FloatGen, DoubleGen, BooleanGen,
    StringGen, DateGen, TimestampGen,
]
NUMERIC_GENS = [ByteGen, ShortGen, IntegerGen, LongGen, FloatGen, DoubleGen]


def gen_table(gens: Dict[str, DataGen], length: int, seed: int = 0) -> pa.Table:
    """One arrow table with ``length`` rows drawn from each named generator
    (data_gen.py gen_df analog)."""
    rng = np.random.default_rng(seed)
    cols = {}
    for name, g in gens.items():
        cols[name] = pa.array(g.values(rng, length), type=g.pa_type)
    return pa.table(cols)


def random_gens(rng: np.random.Generator, n_cols: int,
                pool: Optional[Sequence] = None) -> Dict[str, DataGen]:
    """A random schema (FuzzerUtils.createSchema analog)."""
    pool = list(pool or ALL_GENS)
    return {f"c{i}": pool[rng.integers(0, len(pool))]()
            for i in range(n_cols)}
