"""Mesh-sharded columnar batches.

The distributed execution unit: one logical batch whose column arrays live
partitioned across a ``jax.sharding.Mesh`` data axis. Global array shape is
``[n_dev * local_capacity, ...]`` with ``NamedSharding(mesh, P('data'))``;
device d owns rows ``[d*local_capacity, (d+1)*local_capacity)`` and the live
rows of each shard are a prefix (the same padding invariant as DeviceBatch,
per shard).

This replaces the reference's executor-task partitioning of batches
(ShuffledBatchRDD partitions, one GPU per executor): a partition IS a mesh
shard, and every exchange between partitions is an XLA collective over ICI
instead of a UCX transfer (shuffle-plugin/.../ucx/UCX.scala:53).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.columnar.batch import DeviceBatch, _arrow_to_staged
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.dtypes import DType, Schema, bucket_capacity
from spark_rapids_tpu.parallel.mesh import DATA_AXIS


@dataclass(frozen=True)
class MeshBatch:
    """Columns sharded over the mesh data axis + per-shard live row counts."""

    schema: Schema
    columns: Tuple[DeviceColumn, ...]
    #: host-side int32[n_dev]: live rows per shard (each shard's live rows are
    #: a prefix of its local slice)
    rows_per_shard: np.ndarray
    mesh: Mesh

    @property
    def n_dev(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def local_capacity(self) -> int:
        cap = self.columns[0].capacity if self.columns else 0
        return cap // self.n_dev

    @property
    def num_rows(self) -> int:
        return int(self.rows_per_shard.sum())

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    def rows_dev(self):
        """rows_per_shard as a device array sharded one-per-shard (the shape
        shard_map bodies see is [1])."""
        return jax.device_put(self.rows_per_shard.astype(np.int32),
                              NamedSharding(self.mesh, P(DATA_AXIS)))


def flatten_mesh(mb: MeshBatch) -> List:
    flat = []
    for c in mb.columns:
        flat.append(c.data)
        flat.append(c.validity)
        if c.lengths is not None:
            flat.append(c.lengths)
    return flat


def mesh_columns(schema: Schema, flat) -> Tuple[DeviceColumn, ...]:
    cols, i = [], 0
    for f in schema:
        if f.dtype is DType.STRING:
            cols.append(DeviceColumn(f.dtype, flat[i], flat[i + 1], flat[i + 2]))
            i += 3
        else:
            cols.append(DeviceColumn(f.dtype, flat[i], flat[i + 1]))
            i += 2
    return tuple(cols)


def staged_column_arrays(dtype: DType, col, string_max_bytes: int):
    """Chunk-normalize one arrow column and stage it to
    (data, validity, lengths) numpy arrays, validity defaulting to all-true
    — the single staging path for every host->mesh upload."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if isinstance(arr, pa.ChunkedArray):
        arr = (arr.chunk(0) if arr.num_chunks == 1
               else pa.concat_arrays(arr.chunks))
    data, validity, lengths = _arrow_to_staged(dtype, arr, string_max_bytes)
    if validity is None:
        validity = np.ones(len(arr), dtype=bool)
    return data, validity, lengths


def scatter_arrow(table: pa.Table, mesh: Mesh, string_max_bytes: int
                  ) -> MeshBatch:
    """Host arrow table -> mesh batch: rows split contiguously across shards
    (shard-major order preserves the table's row order end to end), each shard
    padded to a shared power-of-two local capacity, one sharded device_put per
    column buffer."""
    table = table.combine_chunks()
    schema = Schema.from_pa(table.schema)
    n = table.num_rows
    n_dev = int(mesh.devices.size)
    per = -(-n // n_dev) if n else 0
    local_cap = max(bucket_capacity(per), 1)
    total = n_dev * local_cap
    rows = np.zeros(n_dev, dtype=np.int32)
    for d in range(n_dev):
        rows[d] = max(0, min(per, n - d * per))

    sharding = NamedSharding(mesh, P(DATA_AXIS))
    cols: List[DeviceColumn] = []
    for i, f in enumerate(schema):
        data, validity, lengths = staged_column_arrays(f.dtype,
                                                       table.column(i),
                                                       string_max_bytes)
        gdata = np.zeros((total,) + data.shape[1:], dtype=data.dtype)
        gvalid = np.zeros(total, dtype=bool)
        glen = (np.zeros(total, dtype=np.int32) if lengths is not None
                else None)
        for d in range(n_dev):
            if rows[d] == 0:
                continue
            src = slice(d * per, d * per + rows[d])
            dst = slice(d * local_cap, d * local_cap + rows[d])
            gdata[dst] = data[src]
            gvalid[dst] = validity[src]
            if glen is not None:
                glen[dst] = lengths[src]
        up = jax.device_put(
            (gdata, gvalid) + ((glen,) if glen is not None else ()), sharding)
        cols.append(DeviceColumn(f.dtype, up[0], up[1],
                                 up[2] if glen is not None else None))
    return MeshBatch(schema, tuple(cols), rows, mesh)


def scatter_device_batch(db: DeviceBatch, mesh: Mesh) -> MeshBatch:
    """Single-device batch -> mesh batch: the EXPLICIT reshard (host
    staging; the entry path for small single-device intermediates joining a
    mesh pipeline). This is a deliberate host hop and counts as one —
    in-mesh exchanges must never route through here (host_hop_bytes == 0 on
    the all_to_all path is a CI assert)."""
    from spark_rapids_tpu.utils import metrics as um
    um.TRANSFER_METRICS[um.TRANSFER_HOST_HOP_BYTES].add(db.device_size_bytes)
    return scatter_arrow(db.to_arrow(), mesh, _string_width(db))


def _string_width(db: DeviceBatch) -> int:
    w = 8
    for c in db.columns:
        if c.lengths is not None:
            w = max(w, c.data.shape[-1])
    return w


def gather_mesh(mb: MeshBatch) -> DeviceBatch:
    """Mesh batch -> one compacted single-device batch, preserving shard-major
    row order (shard 0 rows first). The compaction runs as one XLA program
    over the sharded arrays (GSPMD all-gathers over ICI); the result lands on
    the default device."""
    n_dev, cap = mb.n_dev, mb.local_capacity
    total_rows = mb.num_rows
    out_cap = max(bucket_capacity(total_rows), 1)
    rows = mb.rows_dev()
    # n_dev is keyed explicitly: the traced gather reshapes over
    # n_dev * cap, so two meshes sharing (schema, cap, out_cap) but
    # differing in device count must not share a program (R016)
    key = ("mesh-gather", mb.mesh, mb.schema, cap, n_dev,
           tuple(c.data.shape[1:] for c in mb.columns), out_cap)

    from spark_rapids_tpu.execs.tpu_execs import _cached_jit

    def build(mesh=mb.mesh, n_dev=n_dev, cap=cap, out_cap=out_cap,
              schema=mb.schema):
        def fn(rows, *flat):
            live = (jnp.arange(cap, dtype=np.int32)[None, :]
                    < rows[:, None]).reshape(n_dev * cap)
            order = jnp.argsort(~live, stable=True)[:out_cap]
            outs = []
            for a in flat:
                g = jax.lax.with_sharding_constraint(
                    a[order], NamedSharding(mesh, P()))
                outs.append(g)
            return tuple(outs)
        return fn

    fn = _cached_jit(key, build)
    res = fn(rows, *flatten_mesh(mb))
    dev = jax.devices()[0]
    placed = jax.device_put(list(res), dev)
    cols = mesh_columns(mb.schema, placed)
    return DeviceBatch(mb.schema, cols, total_rows)


def replicate_device_batch(db: DeviceBatch, mesh: Mesh) -> DeviceBatch:
    """Replicate a single-device batch's arrays across the mesh (the
    all-gather role of GpuBroadcastExchangeExec's per-executor batch cache:
    XLA broadcasts the buffers over ICI)."""
    sharding = NamedSharding(mesh, P())
    cols = []
    for c in db.columns:
        data = jax.device_put(c.data, sharding)
        validity = jax.device_put(c.validity, sharding)
        lengths = (jax.device_put(c.lengths, sharding)
                   if c.lengths is not None else None)
        cols.append(DeviceColumn(c.dtype, data, validity, lengths))
    return DeviceBatch(db.schema, tuple(cols), db.num_rows)
