"""Device mesh management.

The TPU replacement for the reference's executor-per-GPU model
(GpuDeviceManager.scala: one GPU per executor process): one SPMD program over a
jax.sharding.Mesh, with batches partitioned along the data axis and collectives
riding ICI. Multi-host scaling is the same code — jax's global mesh spans hosts
with DCN between slices.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: Optional[int] = None, axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        devs = devs[:num_devices]
    from spark_rapids_tpu import shims
    return shims.get().make_mesh(devs, (axis_name,))


def batch_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Rows partitioned over the data axis (leading dim)."""
    return NamedSharding(mesh, P(axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
