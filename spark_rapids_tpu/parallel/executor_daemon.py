"""Cluster executor daemon: one OS process per executor.

Spawned by ProcessExecutor (parallel/cluster.py) with a control port; builds
a ShuffleEnv on the configured transport (TCP for cross-process topologies)
and serves tasks until told to stop. The control socket carries only task
specs and results — shuffle DATA moves executor-to-executor over the shuffle
transport's own sockets (the reference's metadata-via-driver / data-P2P
split, RapidsShuffleInternalManager.scala).

The executor-plugin-init analog (Plugin.scala RapidsExecutorPlugin): a fatal
init error exits the process, which the driver surfaces as a failed start.
"""
from __future__ import annotations

import argparse
import os
import socket
import sys
import tempfile
import threading
import traceback


def _cache_put(conf, cached_parts, tid: int, parts) -> None:
    """Register a shipped df.cache() entry's partitions in THIS executor's
    spillable catalog under the driver's BufferIds (the executor-side cache
    serving of HostColumnarToGpu.scala:222, re-targeted at the tiered
    store: the batches spill device->host->disk under pressure like any
    cached buffer)."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.memory.store import CACHE_BUFFER_PRIORITY

    _cache_remove(cached_parts, tid)      # stale generation, if any
    dm = DeviceManager.initialize(conf)
    smax = conf.string_max_bytes
    ids = []
    try:
        for i, ipc in enumerate(parts):
            with pa.ipc.open_stream(pa.BufferReader(ipc)) as r:
                table = r.read_all()
            bid = BufferId(tid, i)
            dm.device_store.add_batch(bid,
                                      DeviceBatch.from_arrow(table, smax),
                                      CACHE_BUFFER_PRIORITY)
            ids.append(bid)
    except Exception:
        # mid-loop failure must not orphan the partitions already
        # registered (mirrors CacheManager._materialize's rollback)
        for bid in ids:
            dm.catalog.remove(bid)
        raise
    cached_parts[tid] = ids


def _cache_remove(cached_parts, tid: int) -> None:
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    ids = cached_parts.pop(tid, None)
    if ids:
        dm = DeviceManager.peek()
        if dm is not None:
            for bid in ids:
                dm.catalog.remove(bid)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--executor-id", required=True)
    ap.add_argument("--control-port", type=int, required=True)
    args = ap.parse_args()

    # the TPU plugin's sitecustomize force-resets jax_platforms at interpreter
    # start, overriding JAX_PLATFORMS; pin the requested platform back before
    # any backend initializes (a busy chip tunnel would hang executor startup)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    sock = socket.create_connection(("127.0.0.1", args.control_port),
                                    timeout=60)
    sock.settimeout(None)  # connect bound only; serving blocks indefinitely
    from spark_rapids_tpu.parallel.cluster import (_recv_msg, _run_task,
                                                   _send_msg)
    from spark_rapids_tpu.shuffle.manager import ShuffleEnv
    from spark_rapids_tpu.utils import errors as uerr

    env = None
    cached_parts: dict = {}      # df.cache() table_id -> [BufferId...]
    spill_dir = tempfile.mkdtemp(prefix=f"spill-{args.executor_id}-")
    try:
        msg = _recv_msg(sock)
        assert msg["type"] == "init", msg
        conf = msg["conf"]
        env = ShuffleEnv(args.executor_id, conf, disk_dir=spill_dir)
        _send_msg(sock, {"type": "ready"})

        # responses interleave across concurrent task threads: serialize the
        # socket writes; the driver routes them back by id
        send_lock = threading.Lock()

        def send(obj) -> None:
            with send_lock:
                _send_msg(sock, obj)

        while True:
            msg = _recv_msg(sock)
            kind = msg["type"]
            rid = msg.get("id")
            if kind == "stop":
                return 0
            if kind == "ping":
                # liveness probe: the control socket can outlive a killed
                # shuffle transport (chaos kill_peer), so report both
                t = env.transport
                killed = bool(getattr(t, "killed", False)
                              or getattr(t, "_killed", False))
                send({"type": "pong", "killed": killed, "id": rid})
                continue
            if kind == "cleanup":
                env.shuffle_catalog.remove_shuffle(msg["shuffle_id"])
                send({"type": "ok", "id": rid})
                continue
            if kind == "cleanup_map":
                env.shuffle_catalog.remove_map_outputs(msg["shuffle_id"],
                                                       msg["map_id"])
                send({"type": "ok", "id": rid})
                continue
            if kind == "broadcast":
                from spark_rapids_tpu.parallel.broadcast import \
                    BroadcastManager
                BroadcastManager.put(msg["bid"], msg["blob"])
                send({"type": "ok", "id": rid})
                continue
            if kind == "cleanup_broadcast":
                from spark_rapids_tpu.parallel.broadcast import \
                    BroadcastManager
                BroadcastManager.remove(msg["bid"])
                send({"type": "ok", "id": rid})
                continue
            if kind == "cache_put":
                try:
                    _cache_put(conf, cached_parts, msg["tid"], msg["parts"])
                    send({"type": "ok", "id": rid})
                except Exception:
                    send({"type": "error", "id": rid,
                          "message": traceback.format_exc()})
                continue
            if kind == "cache_remove":
                _cache_remove(cached_parts, msg["tid"])
                send({"type": "ok", "id": rid})
                continue
            if kind == "task":
                # one thread per in-flight task (the driver bounds in-flight
                # tasks to taskSlots per executor; device entry inside the
                # task is gated by the admission semaphore)
                @uerr.wire_boundary
                def run(spec=msg["spec"], rid=rid) -> None:
                    from spark_rapids_tpu.shuffle.manager import \
                        ShuffleFetchFailedError
                    try:
                        blob = _run_task(env, spec)
                        send({"type": "done", "blob": blob, "id": rid})
                    except ShuffleFetchFailedError as e:
                        # structured codec (utils/errors.py): the scoped
                        # payload must survive the control socket — the
                        # driver's recompute loop keys off executor_id +
                        # blocks, which a flattened traceback would lose
                        send({"type": "error", "id": rid,
                              "error": uerr.encode_error(e),
                              "message": str(e)})
                    except Exception as e:
                        # unregistered types ship OPAQUE (non-retryable
                        # driver-side) with the traceback as message
                        send({"type": "error", "id": rid,
                              "error": uerr.encode_error(
                                  e, message=traceback.format_exc()),
                              "message": traceback.format_exc()})

                threading.Thread(target=run, daemon=True).start()
                continue
            send({"type": "error", "id": rid,
                  "message": f"unknown control message {kind!r}"})
    except (ConnectionError, EOFError):
        return 0
    finally:
        if env is not None:
            env.close()
        import shutil
        shutil.rmtree(spill_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
