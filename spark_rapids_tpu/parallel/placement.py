"""Placement: where a batch's buffers live, as a first-class plan property.

The reference binds an operator to a device implicitly (one GPU per executor
process, GpuDeviceManager.scala); this engine makes placement explicit and
carries it through planning as a ``jax.sharding.Sharding``:

- ``None``                      — the process default device (legacy behavior);
- ``SingleDeviceSharding(d)``   — a pinned single device (multi-device task
  scheduling, the PR 3 ``ExecContext.device`` role);
- ``NamedSharding(mesh, P('data'))`` — rows partitioned over the mesh data
  axis (mesh execution; exchanges are in-mesh collectives);
- ``NamedSharding(mesh, P())``  — replicated across the mesh (broadcast
  builds, range bounds).

``jax.device_put`` accepts any of these as its placement argument, so one
upload path (columnar/transfer.py, the PR 3 pipeline) serves every operator:
operators are placement-agnostic and the PLANNER (plan/mesh_rewrite.py)
decides where batches land.

The ICI-vs-DCN boundary also lives here: collective exchange (all_to_all,
all-gather) must ride the interconnect, so the planner clips its mesh to one
ICI domain (``ici_groups``); the PR 2 fault-tolerant TCP stack is reserved
for cross-slice (DCN) shuffle.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.sharding import SingleDeviceSharding


def as_placement(device_or_sharding: Any) -> Optional[jax.sharding.Sharding]:
    """Normalize the legacy ``device=`` argument into a placement: a raw
    ``jax.Device`` becomes a SingleDeviceSharding, a Sharding passes through,
    None (process default) stays None."""
    x = device_or_sharding
    if x is None or isinstance(x, jax.sharding.Sharding):
        return x
    return SingleDeviceSharding(x)


def placement_devices(p: Optional[jax.sharding.Sharding]) -> Tuple:
    """The devices a placement covers (empty tuple for the default)."""
    if p is None:
        return ()
    return tuple(p.device_set)


def placement_device(p: Optional[jax.sharding.Sharding]):
    """The single device of a one-device placement, else None (callers that
    genuinely need ONE device — e.g. a host-staged writer — must gather or
    reshard first; a multi-device placement has no canonical device)."""
    devs = placement_devices(p)
    return devs[0] if len(devs) == 1 else None


def is_sharded(p: Optional[jax.sharding.Sharding]) -> bool:
    """True when the placement partitions data over more than one device
    (a replicated multi-device sharding counts: its buffers live on every
    device and single-device code must not consume it blindly)."""
    return p is not None and len(p.device_set) > 1


def array_placement(arr: Any) -> Optional[jax.sharding.Sharding]:
    """The committed sharding of a jax array (None for host/numpy arrays)."""
    return getattr(arr, "sharding", None)


def batch_devices(batch) -> frozenset:
    """Every device holding any buffer of a DeviceBatch."""
    devs: set = set()
    for c in batch.columns:
        for arr in (c.data, c.validity, c.lengths):
            s = array_placement(arr)
            if s is not None:
                devs |= set(s.device_set)
    return frozenset(devs)


def assert_unsharded(batches: Sequence, op: str) -> None:
    """Refuse to silently gather mesh-sharded buffers onto one device.

    Single-device repack paths (``concat_device_batches`` and friends) would
    otherwise pull every shard of a NamedSharding array through XLA's implicit
    resharding — a hidden host-scale data movement. The explicit boundaries
    are ``MeshGatherExec`` / ``parallel.mesh_batch.gather_mesh`` (collective
    gather) and ``scatter_device_batch`` (reshard onto the mesh)."""
    for b in batches:
        devs = batch_devices(b)
        if len(devs) > 1:
            raise ValueError(
                f"{op} received a batch sharded over {len(devs)} devices; "
                "gather it explicitly (MeshGatherExec / gather_mesh) or "
                "reshard (scatter_device_batch) instead of silently "
                "collapsing the mesh onto one device")


def placement_label(p: Optional[jax.sharding.Sharding]) -> str:
    """Compact human label for plan display (tree_string)."""
    if p is None:
        return "default"
    devs = placement_devices(p)
    if len(devs) == 1:
        return f"device:{devs[0]}"
    if isinstance(p, NamedSharding):
        spec = tuple(p.spec)
        kind = "replicated" if not any(spec) else f"P{spec}"
        return f"mesh[{len(devs)}]:{kind}"
    return f"sharded[{len(devs)}]"


# ------------------------------------------------------------------ ICI / DCN
def _ici_key(d) -> Tuple:
    """Devices sharing this key are connected by ICI (one pod slice on one
    process group); differing keys can only reach each other over DCN."""
    return (getattr(d, "slice_index", None), d.process_index)


def ici_groups(devices: Sequence) -> List[List]:
    """Partition devices into ICI domains, preserving order within each.

    TPU runtimes expose ``slice_index`` per device (one pod slice = one ICI
    domain); backends without it fall back to process_index — devices owned
    by different hosts without a shared slice can only exchange over DCN."""
    groups: dict = {}
    for d in devices:
        groups.setdefault(_ici_key(d), []).append(d)
    return list(groups.values())


def largest_ici_group(devices: Sequence) -> List:
    """The biggest single-ICI-domain subset — the widest mesh whose
    collectives never touch DCN."""
    groups = ici_groups(devices)
    return max(groups, key=len) if groups else []


def spans_dcn(devices: Sequence) -> bool:
    """True when the device set crosses an ICI boundary: a collective over
    it would ride DCN, which belongs to the fault-tolerant TCP shuffle
    (shuffle/tcp.py), not to an in-mesh all_to_all."""
    return len(ici_groups(devices)) > 1
