"""Executor-side broadcast cache for the cluster path.

Reference analog: GpuBroadcastExchangeExec builds the broadcast batch ONCE
(driver side) and ships it through Spark's TorrentBroadcast; each executor
deserializes it ONE time and every task on that executor shares the device
copy (execution/GpuBroadcastExchangeExec.scala:47-66
SerializeConcatHostBuffersDeserializeBatch — the `@transient lazy val batch`
is the once-per-executor deserialize).

Here the driver executes the broadcast subtree locally, serializes the
result batch as arrow IPC, and pushes the bytes to every executor over the
control plane exactly once per (broadcast, executor). This process-global
registry holds the bytes; the first task that consumes the broadcast
deserializes to a device (or host) batch under a lock, and later tasks in
the same executor process reuse that batch.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional, Tuple

import pyarrow as pa

#: process-global broadcast-id namespace: schedulers from concurrent
#: sessions share one BroadcastManager registry, so ids must never collide
#: (distinct from the df.cache table_id namespace at 1 << 28)
BROADCAST_IDS = itertools.count(1 << 29)


class _Entry:
    __slots__ = ("ipc", "lock", "batches", "deserialize_count")

    def __init__(self, ipc: bytes):
        self.ipc = ipc
        self.lock = threading.Lock()
        #: (device, string_max_bytes) -> built batch; in practice one key,
        #: keyed defensively so a conf drift cannot serve a mis-sized batch
        self.batches: Dict[Tuple[bool, int], object] = {}
        #: observability for tests: how many times the IPC bytes were
        #: actually deserialized in this process (must be 1 per consumer
        #: shape, not once per task)
        self.deserialize_count = 0


class BroadcastManager:
    """Per-process registry (one per executor process; in-process executors
    share the driver's)."""

    _lock = threading.Lock()
    _entries: Dict[int, _Entry] = {}

    @classmethod
    def put(cls, broadcast_id: int, ipc: bytes) -> None:
        with cls._lock:
            cls._entries[broadcast_id] = _Entry(ipc)

    @classmethod
    def has(cls, broadcast_id: int) -> bool:
        with cls._lock:
            return broadcast_id in cls._entries

    @classmethod
    def get_batch(cls, broadcast_id: int, device: bool,
                  string_max_bytes: int):
        with cls._lock:
            e = cls._entries.get(broadcast_id)
        if e is None:
            raise KeyError(f"broadcast {broadcast_id} not registered in "
                           "this executor")
        key = (device, string_max_bytes)
        with e.lock:
            batch = e.batches.get(key)
            if batch is None:
                with pa.ipc.open_stream(pa.BufferReader(e.ipc)) as r:
                    table = r.read_all()
                e.deserialize_count += 1
                if device:
                    from spark_rapids_tpu.columnar.batch import DeviceBatch
                    batch = DeviceBatch.from_arrow(table, string_max_bytes)
                else:
                    from spark_rapids_tpu.columnar.host import HostBatch
                    batch = HostBatch.from_arrow(table, string_max_bytes)
                e.batches[key] = batch
        return batch

    @classmethod
    def deserialize_count(cls, broadcast_id: int) -> int:
        with cls._lock:
            e = cls._entries.get(broadcast_id)
        return e.deserialize_count if e is not None else 0

    @classmethod
    def remove(cls, broadcast_id: int) -> None:
        with cls._lock:
            cls._entries.pop(broadcast_id, None)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._entries.clear()
