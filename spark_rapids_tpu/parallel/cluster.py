"""Multi-executor query execution over the shuffle-manager stack.

The load-bearing path for the accelerated shuffle protocol: a physical plan
is split into shuffle stages at exchange boundaries (Spark's DAGScheduler
role), map tasks run across executors writing each reduce partition's device
batches through the CachingShuffleWriter into that executor's spillable
shuffle catalog (RapidsShuffleInternalManager.scala:194 getWriter ->
RapidsCachingWriter), and reduce-side reads serve local blocks from the
catalog and fetch remote blocks through the transport client
(RapidsCachingReader.scala + RapidsShuffleIterator) — in-process fabric or
real TCP sockets, including executors in separate OS processes.

Contrast with the mesh engine (execs/mesh_execs.py): there an exchange is an
XLA collective inside one SPMD program; here it is the reference's
pull-based, executor-to-executor protocol. Both produce identical results —
tests assert query equality across the two paths and the single-process
engine.

Range partitioning runs its map stage as ONE task (bounds need a global
sample; the reference pays a separate sampling job for the same reason —
SamplingUtils) — the reduce side still fans out across executors.
"""
from __future__ import annotations

import atexit
import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import threading
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar.dtypes import Schema
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.execs.base import ExecContext, LeafExec, PhysicalExec
from spark_rapids_tpu.shuffle.manager import (CachingShuffleReader,
                                              CachingShuffleWriter, MapStatus,
                                              MapOutputTracker, ShuffleEnv,
                                              ShuffleFetchFailedError)
from spark_rapids_tpu.utils import errors as uerr
from spark_rapids_tpu.utils import metrics as mt

_TCP_TRANSPORT = "spark_rapids_tpu.shuffle.tcp.TcpTransport"


# ------------------------------------------------------------------ plan split
class ClusterShuffleReadExec(LeafExec):
    """Reduce-side leaf standing in for an exchange: reads one partition of a
    parent stage's shuffle through the executor's caching reader (the
    ShuffledBatchRDD + RapidsCachingReader composition)."""

    is_device = True

    #: map-output sizes exist only at run time (MapStatus); the stage
    #: scheduler's AQE coalescing consumes them there, not at plan time
    size_estimate_none_reason = ("remote map-output sizes are known only "
                                 "at run time (MapStatus)")

    def __init__(self, stage_index: int, output: Schema, num_parts: int):
        super().__init__(output)
        self.stage_index = stage_index
        self.num_parts = num_parts
        self.shuffle_id: Optional[int] = None  # driver assigns pre-pickle
        #: AQE partition coalescing (GpuCustomShuffleReaderExec.scala:122
        #: role on the cluster path): when set, consumer partition i reads
        #: the contiguous exchange partitions ``specs[i]`` — built by the
        #: driver from OBSERVED MapStatus sizes after the map stage ran
        self.specs: Optional[Tuple[Tuple[int, ...], ...]] = None

    @property
    def num_partitions(self) -> int:
        return len(self.specs) if self.specs is not None else self.num_parts

    def execute(self, ctx: ExecContext):
        cs = getattr(ctx, "cluster_shuffle", None)
        assert cs is not None, "cluster shuffle read outside a cluster task"
        tracker = MapOutputTracker()
        tracker.register_shuffle(self.shuffle_id)
        for st in cs.statuses[self.shuffle_id]:
            tracker.register_map_output(self.shuffle_id, st)
        pids = (self.specs[ctx.partition_id] if self.specs is not None
                else (ctx.partition_id,))
        for pid in pids:
            reader = CachingShuffleReader(cs.env, tracker, self.shuffle_id,
                                          pid)
            for batch in reader.read():
                self.count_output(batch.num_rows)
                yield batch


@dataclass
class _Stage:
    index: int
    #: exchange exec (shuffle stages) or the final plan (result stage); its
    #: subtree may contain ClusterShuffleReadExec leaves for dep stages
    root: PhysicalExec
    is_result: bool
    deps: List[int] = field(default_factory=list)
    shuffle_id: Optional[int] = None
    num_tasks: int = 1
    statuses: List[MapStatus] = field(default_factory=list)
    #: result stage only: collected tables in partition order
    result_tables: List = field(default_factory=list)
    #: broadcast stages: driver-built, shipped once per executor
    is_broadcast: bool = False
    broadcast_id: Optional[int] = None


def split_stages(final: PhysicalExec) -> Optional[List[_Stage]]:
    """Cut the plan at device shuffle-exchange boundaries. Returns None when
    the plan has exchanges the cluster cannot stage (CPU exchanges), handing
    execution back to the single-process engine."""
    from spark_rapids_tpu.execs.exchange_execs import (
        BroadcastExchangeExecBase, CpuShuffleExchangeExec, RangePartitioning,
        TpuShuffleExchangeExec)
    stages: List[_Stage] = []

    def walk(node: PhysicalExec, deps: List[int]) -> PhysicalExec:
        if isinstance(node, CpuShuffleExchangeExec):
            raise _Unstageable()
        if getattr(node, "cluster_unstageable", False):
            # extension point: an exec whose state genuinely cannot ship to
            # executor processes opts out of staging here (cached scans USED
            # to — they now ship via _ship_cached_entries; no in-tree exec
            # sets the flag today)
            raise _Unstageable()
        if isinstance(node, BroadcastExchangeExecBase):
            child_deps: List[int] = []
            new_child = walk(node.children[0], child_deps)
            if any(not stages[d].is_broadcast for d in child_deps):
                # the build side reads dep shuffles (AQE dynamic broadcast
                # after an exchange): the driver cannot serve executor
                # catalogs, so the exchange stays inline in the parent
                # stage (rebuilt per task — the pre-cut behavior)
                deps.extend(child_deps)
                return (node if new_child is node.children[0]
                        else node.with_children([new_child]))
            exchange = (node if new_child is node.children[0]
                        else node.with_children([new_child]))
            idx = len(stages)
            stages.append(_Stage(idx, exchange, is_result=False,
                                 is_broadcast=True, deps=child_deps))
            deps.append(idx)
            return ClusterBroadcastReadExec(idx, exchange.output,
                                            exchange.is_device)
        if isinstance(node, TpuShuffleExchangeExec):
            child_deps: List[int] = []
            new_child = walk(node.children[0], child_deps)
            exchange = node.with_children([new_child])
            idx = len(stages)
            n_parts = exchange.partitioning.num_partitions
            single_task = isinstance(exchange.partitioning,
                                     RangePartitioning)
            stage = _Stage(idx, exchange, is_result=False, deps=child_deps,
                           num_tasks=(1 if single_task
                                      else max(1, new_child.num_partitions)))
            stages.append(stage)
            deps.append(idx)
            return ClusterShuffleReadExec(idx, exchange.output, n_parts)
        new_kids = [walk(c, deps) for c in node.children]
        if any(a is not b for a, b in zip(new_kids, node.children)):
            return node.with_children(new_kids)
        return node

    class _Unstageable(Exception):
        pass

    try:
        result_deps: List[int] = []
        new_final = walk(final, result_deps)
    except _Unstageable:
        return None
    result = _Stage(len(stages), new_final, is_result=True, deps=result_deps,
                    num_tasks=max(1, new_final.num_partitions))
    stages.append(result)
    return stages


# ------------------------------------------------------------------ tasks
class ClusterBroadcastReadExec(LeafExec):
    """Stand-in for a broadcast exchange on the cluster path: yields the
    driver-built broadcast batch from the executor's BroadcastManager cache
    (GpuBroadcastExchangeExec's once-per-executor deserialized batch,
    GpuBroadcastExchangeExec.scala:47-66). The driver assigns broadcast_id
    pre-pickle and ships the IPC bytes to every executor before any
    consuming task runs."""

    num_partitions = 1

    #: the broadcast batch is built by the driver mid-run; its size is a
    #: runtime property of another stage's output
    size_estimate_none_reason = ("broadcast stage output is materialized "
                                 "at run time by the driver")

    def __init__(self, stage_index: int, output: Schema, device: bool):
        super().__init__(output)
        self.stage_index = stage_index
        self.is_device = device
        self.broadcast_id: Optional[int] = None  # driver assigns pre-pickle

    def execute(self, ctx: ExecContext):
        from spark_rapids_tpu.parallel.broadcast import BroadcastManager
        batch = BroadcastManager.get_batch(self.broadcast_id, self.is_device,
                                           ctx.string_max_bytes)
        self.count_output(batch.num_rows)
        yield batch


@dataclass
class ClusterTaskContext:
    env: ShuffleEnv
    statuses: Dict[int, List[MapStatus]]


@dataclass
class _TaskSpec:
    kind: str                        # "map" | "result"
    plan_blob: bytes                 # pickled stage root
    partitions: Tuple[int, ...]      # partition ids this task runs
    num_source_parts: int
    shuffle_id: Optional[int]
    num_reduce_parts: int
    dep_statuses: Dict[int, List[MapStatus]]
    conf: TpuConf


@dataclass
class _StageLineage:
    """The deterministic replay record of one map stage (Spark's lineage,
    SURVEY.md §5): everything needed to re-execute ANY of the stage's map
    tasks after its outputs are lost — the resolved sub-plan snapshot (an
    immutable pickle: the driver's ``fix`` transform mutates shared tree
    nodes, so the blob is the only stable copy), the plan-signature replay
    key (program-cache machinery — a replayed task must run the exact plan
    the original ran), the input split assignment per map id, and the dep
    stage indices whose LIVE statuses feed the replay (so a replay whose
    own inputs were lost recomputes them first, recursively)."""
    stage_index: int
    plan_blob: bytes
    signature: str
    num_source_parts: int
    num_reduce_parts: int
    dep_stage_indices: Tuple[int, ...]
    #: map_id -> the source partitions its task maps (identity for hash
    #: partitioning; ``{0: (0,)}`` for range — the single task re-samples
    #: and maps every partition, exactly like the original run)
    task_partitions: Dict[int, Tuple[int, ...]]


def _run_task(env: ShuffleEnv, spec: _TaskSpec) -> bytes:
    """Execute one task against this executor's shuffle env. Returns pickled
    [MapStatus...] for map tasks or arrow-IPC table bytes for result tasks."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    root = pickle.loads(spec.plan_blob)
    dm = DeviceManager.initialize(spec.conf)
    cleanups: List = []
    cs = ClusterTaskContext(env, spec.dep_statuses)

    def make_ctx(p: int) -> ExecContext:
        ctx = ExecContext(spec.conf, partition_id=p,
                          num_partitions=spec.num_source_parts,
                          device_manager=dm, cleanups=cleanups)
        ctx.cluster_shuffle = cs
        return ctx

    try:
        if spec.kind == "map":
            statuses = [
                _map_one_partition(root, make_ctx(p), p, env,
                                   spec.shuffle_id, spec.num_reduce_parts)
                for p in spec.partitions]
            return pickle.dumps(statuses)
        # result tasks keep (partition_id, ipc bytes) so the driver can
        # reassemble global partition order (sorted output depends on it)
        out: List[Tuple[int, bytes]] = []
        schema = root.output.to_pa()
        for p in spec.partitions:
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, schema) as w:
                for b in root.execute(make_ctx(p)):
                    w.write_table(b.to_arrow().cast(schema))
            out.append((p, sink.getvalue().to_pybytes()))
        return pickle.dumps(out)
    finally:
        for fn in cleanups:
            fn()


def _map_one_partition(exchange, ctx: ExecContext, p: int, env: ShuffleEnv,
                       shuffle_id: int, n_reduce: int) -> MapStatus:
    """The map side of one source partition: the exchange's own map-piece
    protocol (iter_map_pieces — shared with the single-process engine),
    cached through the caching writer (RapidsCachingWriter.write). A
    range-partitioned stage runs as one task, so it maps EVERY source
    partition here (bounds need the global sample)."""
    from spark_rapids_tpu.execs.exchange_execs import RangePartitioning
    tracker = MapOutputTracker()  # local; the real one lives on the driver
    tracker.register_shuffle(shuffle_id)
    writer = CachingShuffleWriter(env, tracker, shuffle_id, map_id=p,
                                  num_partitions=n_reduce)
    wanted = (None if isinstance(exchange.partitioning, RangePartitioning)
              else (p,))
    return writer.write(
        (j, sub) for _, j, sub in exchange.iter_map_pieces(ctx, wanted))


# ------------------------------------------------------------------ executors
class InProcessExecutor:
    """One executor inside the driver process: its own shuffle env (stores,
    catalog, transport server); tasks run on the caller thread pool."""

    def __init__(self, executor_id: str, conf: TpuConf, disk_dir: str):
        self.executor_id = executor_id
        self.env = ShuffleEnv(executor_id, conf, disk_dir=disk_dir)

    def submit(self, spec: _TaskSpec) -> bytes:
        return _run_task(self.env, spec)

    def alive(self) -> bool:
        """Liveness for recompute scheduling: an executor whose transport
        was killed (chaos kill_peer / real peer death) serves no tasks and
        is excluded from replay targets."""
        t = self.env.transport
        return not (getattr(t, "killed", False) or getattr(t, "_killed",
                                                           False))

    def cleanup_shuffle(self, shuffle_id: int) -> None:
        self.env.shuffle_catalog.remove_shuffle(shuffle_id)

    def cleanup_map_outputs(self, shuffle_id: int, map_id: int) -> None:
        self.env.shuffle_catalog.remove_map_outputs(shuffle_id, map_id)

    def send_broadcast(self, broadcast_id: int, ipc: bytes) -> None:
        # in-process executors share the driver's BroadcastManager, which
        # the scheduler already registered — nothing to ship
        pass

    def cleanup_broadcast(self, broadcast_id: int) -> None:
        pass  # driver-local removal covers the shared registry

    def put_cache(self, table_id: int, generation: int,
                  parts: List[bytes]) -> None:
        pass  # shares the driver's DeviceManager catalog — already there

    def cleanup_cache(self, table_id: int) -> None:
        pass  # CacheManager._free already dropped the shared buffers

    def close(self) -> None:
        self.env.close()


def _send_msg(sock: socket.socket, obj) -> None:
    blob = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(blob)) + blob)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("executor control socket closed")
        hdr += chunk
    n = struct.unpack(">I", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("executor control socket closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class ProcessExecutor:
    """One executor in its own OS process: the daemon builds a ShuffleEnv on
    the TCP transport and serves tasks over a control socket. Shuffle DATA
    never touches the control plane — it rides the shuffle TCP sockets
    between executor processes (metadata-via-driver, data-P2P, the
    reference's split).

    The control protocol is ASYNC: every request carries an id, the daemon
    runs tasks on its own threads, and a reader thread here routes responses
    back by id — so N tasks can be in flight per executor at once (the
    reference's task model: many concurrent tasks per executor, device
    entry gated by GpuSemaphore, not by the dispatch channel)."""

    def __init__(self, executor_id: str, conf: TpuConf):
        self.executor_id = executor_id
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "spark_rapids_tpu.parallel.executor_daemon",
             "--executor-id", executor_id, "--control-port", str(port)],
            env=env)
        listener.settimeout(60)
        self.sock, _ = listener.accept()
        listener.close()
        self._send_lock = threading.Lock()
        self._pending_lock = threading.Lock()
        self._pending: Dict[int, list] = {}    # id -> [Event, response]
        self._dead = False                     # set when the reader exits
        self._ids = iter(range(1, 1 << 62))
        _send_msg(self.sock, {"type": "init", "conf": conf})
        resp = _recv_msg(self.sock)
        if resp.get("type") != "ready":
            raise RuntimeError(f"executor {executor_id} failed to start: "
                               f"{resp}")
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"{executor_id}-control-reader")
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                resp = _recv_msg(self.sock)
                with self._pending_lock:
                    slot = self._pending.pop(resp.get("id"), None)
                if slot is not None:
                    slot[1] = resp
                    slot[0].set()
        except (ConnectionError, OSError, EOFError):
            # executor died / socket closed: fail every in-flight request —
            # and every FUTURE one (the _dead flag; a send into a half-closed
            # socket can succeed, so waiting on a response would hang)
            with self._pending_lock:
                self._dead = True
                slots = list(self._pending.values())
                self._pending.clear()
            for slot in slots:
                slot[1] = self._lost_response()
                slot[0].set()

    def _lost_response(self) -> dict:
        return {"type": "error",
                "message": f"executor {self.executor_id} connection lost"}

    def _request(self, msg: dict) -> dict:
        rid = next(self._ids)
        slot = [threading.Event(), None]
        with self._pending_lock:
            if self._dead:
                return self._lost_response()
            self._pending[rid] = slot
        try:
            with self._send_lock:
                _send_msg(self.sock, {**msg, "id": rid})
        except (ConnectionError, OSError):
            with self._pending_lock:
                self._pending.pop(rid, None)
            return self._lost_response()
        slot[0].wait()
        return slot[1]

    def submit(self, spec: _TaskSpec) -> bytes:
        resp = self._request({"type": "task", "spec": spec})
        if resp["type"] == "error":
            payload = resp.get("error")
            decoded = (uerr.decode_error(payload) if payload is not None
                       else None)
            if isinstance(decoded, ShuffleFetchFailedError):
                # the daemon's scoped payload survived the control socket
                # via the wire codec (utils/errors.py): the recompute
                # driver keys off executor_id + blocks, which a flattened
                # traceback string would lose
                raise ShuffleFetchFailedError(
                    f"task failed on {self.executor_id}: {resp['message']}",
                    executor_id=decoded.executor_id,
                    blocks=decoded.blocks)
            # every other classified or OPAQUE error surfaces as a plain
            # driver-side failure (the recompute loop re-raises non-signals)
            raise RuntimeError(
                f"task failed on {self.executor_id}: {resp['message']}")
        return resp["blob"]

    def alive(self) -> bool:
        """Liveness probe over the control socket: a dead process (reader
        loop exited) or a daemon whose shuffle transport was killed counts
        as gone for recompute scheduling."""
        if self._dead:
            return False
        resp = self._request({"type": "ping"})
        return resp.get("type") == "pong" and not resp.get("killed", False)

    def cleanup_shuffle(self, shuffle_id: int) -> None:
        self._request({"type": "cleanup", "shuffle_id": shuffle_id})

    def cleanup_map_outputs(self, shuffle_id: int, map_id: int) -> None:
        self._request({"type": "cleanup_map", "shuffle_id": shuffle_id,
                       "map_id": map_id})

    def send_broadcast(self, broadcast_id: int, ipc: bytes) -> None:
        resp = self._request({"type": "broadcast", "bid": broadcast_id,
                              "blob": ipc})
        if resp.get("type") == "error":
            raise RuntimeError(f"broadcast push to {self.executor_id} "
                               f"failed: {resp['message']}")

    def cleanup_broadcast(self, broadcast_id: int) -> None:
        self._request({"type": "cleanup_broadcast", "bid": broadcast_id})

    def put_cache(self, table_id: int, generation: int,
                  parts: List[bytes]) -> None:
        resp = self._request({"type": "cache_put", "tid": table_id,
                              "gen": generation, "parts": parts})
        if resp.get("type") == "error":
            raise RuntimeError(f"cache push to {self.executor_id} failed: "
                               f"{resp['message']}")

    def cleanup_cache(self, table_id: int) -> None:
        self._request({"type": "cache_remove", "tid": table_id})

    def close(self) -> None:
        try:
            with self._send_lock:
                _send_msg(self.sock, {"type": "stop"})
            self.sock.close()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


class _Unpicklable(Exception):
    """A stage subtree cannot ship to executors (e.g. a lambda UDF)."""


# ------------------------------------------------------------------ scheduler
class ClusterScheduler:
    """Stage-by-stage driver (the DAGScheduler role): map stages fan tasks
    across executors and register MapStatus with the driver tracker; the
    result stage's arrow output returns to the caller."""

    def __init__(self, conf: TpuConf):
        self._owned_dirs: List[str] = []
        self.conf = self._prepare_conf(conf)
        self.n = conf.get(cfg.CLUSTER_EXECUTORS)
        self._tmp = tempfile.mkdtemp(prefix="spark-rapids-tpu-cluster-")
        self._owned_dirs.append(self._tmp)
        if conf.get(cfg.CLUSTER_PROCESS_EXECUTORS):
            self.executors = [ProcessExecutor(f"exec-{i}", self.conf)
                              for i in range(self.n)]
        else:
            self.executors = [
                InProcessExecutor(f"exec-{i}", self.conf,
                                  os.path.join(self._tmp, f"exec-{i}"))
                for i in range(self.n)]
        self._next_shuffle = 0
        #: shuffle_id -> replay record, written when a map stage's tasks
        #: are built and consulted when a reduce-side fetch failure scopes
        #: lost map outputs back to this shuffle
        self._lineage: Dict[int, _StageLineage] = {}
        #: (executor identity, cache table_id) -> shipped generation
        self._shipped_caches: Dict[Tuple[int, int], int] = {}
        atexit.register(self.close)

    def _prepare_conf(self, conf: TpuConf) -> TpuConf:
        extra = {}
        if conf.get(cfg.CLUSTER_PROCESS_EXECUTORS):
            if not conf.get_raw("spark.rapids.tpu.shuffle.transport.class"):
                extra["spark.rapids.tpu.shuffle.transport.class"] = \
                    _TCP_TRANSPORT
            if not conf.shuffle_tcp_registry:
                reg = tempfile.mkdtemp(prefix="spark-rapids-tpu-registry-")
                self._owned_dirs.append(reg)
                extra["spark.rapids.tpu.shuffle.tcp.registryDir"] = reg
        return conf.with_overrides(extra) if extra else conf

    def _widen_scans(self, plan: PhysicalExec) -> PhysicalExec:
        """File scans default to one scan task; spread multi-file scans
        across the executors (FilePartition planning)."""
        import copy

        def fix(node: PhysicalExec) -> PhysicalExec:
            files = getattr(node, "files", None)
            if getattr(node, "is_file_scan", False) and files:
                n = min(len(files), 2 * len(self.executors))
                if n > 1 and node.scan_partitions == 1:
                    node = copy.copy(node)
                    node.scan_partitions = n
            return node
        return plan.transform_up(fix)

    def run(self, final: PhysicalExec) -> Optional[List[pa.Table]]:
        """Execute the plan across the cluster; None = plan not stageable
        (caller falls back to the single-process engine)."""
        final = self._widen_scans(final)
        stages = split_stages(final)
        if stages is None:
            return None
        self.last_stages = stages  # introspection for tests/explain
        self._ship_cached_entries(stages)
        shuffle_ids: List[int] = []
        broadcast_ids: List[int] = []
        try:
            for stage in stages:
                if stage.is_broadcast:
                    # the id list tracks the bid the moment it registers so
                    # a failed executor push still reaches cleanup
                    self._run_broadcast_stage(stage, stages, broadcast_ids)
                    continue
                if not stage.is_result:
                    stage.shuffle_id = self._next_shuffle
                    self._next_shuffle += 1
                    shuffle_ids.append(stage.shuffle_id)
                self._run_stage(stage, stages)
            result = stages[-1]
            return result.result_tables
        except _Unpicklable:
            # an unpicklable plan (e.g. lambda UDFs) cannot ship to
            # executors: fall back to the single-process engine
            return None
        finally:
            from spark_rapids_tpu.parallel.broadcast import BroadcastManager
            for sid in shuffle_ids:
                self._lineage.pop(sid, None)
                for ex in self.executors:
                    try:
                        ex.cleanup_shuffle(sid)
                    except Exception:
                        pass
            for bid in broadcast_ids:
                BroadcastManager.remove(bid)      # driver-local registry
                for ex in self.executors:
                    try:
                        ex.cleanup_broadcast(bid)
                    except Exception:
                        pass

    def _coalesce_stage_reads(self, stage: _Stage, stages: List[_Stage],
                              leaves: List[ClusterShuffleReadExec],
                              root: PhysicalExec) -> None:
        """AQE partition coalescing on the cluster path: group contiguous
        small reduce partitions of the stage's dep shuffles into single
        reduce tasks using the OBSERVED per-partition MapStatus sizes
        (GpuCustomShuffleReaderExec.scala:122 + coalesceShufflePartitions).
        All read leaves of one stage get IDENTICAL specs — a co-partitioned
        join's sides stay aligned, and contiguous grouping preserves
        range-partition order."""
        if not leaves or not self.conf.get(cfg.ADAPTIVE_ENABLED):
            return
        n = leaves[0].num_parts
        if n <= 1 or any(lf.num_parts != n for lf in leaves):
            return
        sizes = [0] * n
        for lf in leaves:
            dep = stages[lf.stage_index]
            if not dep.statuses:
                return
            for st in dep.statuses:
                for j, s in enumerate(st.partition_sizes):
                    sizes[j] += s
        from spark_rapids_tpu.plan.adaptive import coalesce_specs
        specs = coalesce_specs(
            sizes, self.conf.get(cfg.ADAPTIVE_ADVISORY_PARTITION_BYTES))
        if len(specs) >= n:
            return
        for lf in leaves:
            lf.specs = specs
        # a sibling source with MORE partitions than the coalesced reads
        # (e.g. a widened file scan under a union) would make the stage fan
        # past len(specs) and index out of range — coalescing only applies
        # when the reads govern the stage's partitioning
        src = root if stage.is_result else root.children[0]
        if src.num_partitions != len(specs):
            for lf in leaves:
                lf.specs = None

    def _ship_cached_entries(self, stages: List[_Stage]) -> None:
        """df.cache() on the cluster (round-4 VERDICT item 6): every cached
        entry scanned by this plan ships ONCE per executor process —
        generation-tracked, so re-materialized entries re-ship and repeat
        actions don't (the second-run-faster property). Executors register
        the partitions in their own spillable catalogs under the same
        BufferIds the scan execs resolve (HostColumnarToGpu.scala:222
        executor-side cache serving, re-targeted at the tiered store)."""
        from spark_rapids_tpu.execs.cache_execs import _CachedScanBase

        def walk(n: PhysicalExec):
            yield n
            for c in n.children:
                yield from walk(c)

        entries = {}
        for st in stages:
            for n in walk(st.root):
                if isinstance(n, _CachedScanBase):
                    entries[n.entry.table_id] = n.entry
        for e in entries.values():
            if e.buffer_ids is None:
                raise RuntimeError("cached plan reached the cluster "
                                   "scheduler unmaterialized")
            parts: Optional[List[bytes]] = None   # serialized lazily, once
            for ex in self.executors:
                key = (id(ex), e.table_id)
                if self._shipped_caches.get(key) == e.generation:
                    continue
                if parts is None:
                    parts = self._serialize_cached(e)
                ex.put_cache(e.table_id, e.generation, parts)
                self._shipped_caches[key] = e.generation

    def _serialize_cached(self, e) -> List[bytes]:
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        catalog = DeviceManager.get().catalog
        parts: List[bytes] = []
        for bid in e.buffer_ids:
            buf = catalog.acquire(bid)
            if buf is None:
                raise RuntimeError(f"cached buffer {bid} vanished while "
                                   "shipping to executors")
            try:
                table = buf.get_host_batch().to_arrow()
            finally:
                buf.close()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, table.schema) as w:
                w.write_table(table)
            parts.append(sink.getvalue().to_pybytes())
        return parts

    def cleanup_cache(self, table_id: int) -> None:
        """unpersist() propagation: drop shipped copies everywhere."""
        for ex in self.executors:
            self._shipped_caches.pop((id(ex), table_id), None)
            try:
                ex.cleanup_cache(table_id)
            except Exception:
                pass

    def _run_broadcast_stage(self, stage: _Stage, stages: List[_Stage],
                             broadcast_ids: List[int]) -> None:
        """Build the broadcast batch ONCE on the driver and ship the
        serialized bytes to every executor (GpuBroadcastExchangeExec's
        driver-side build + TorrentBroadcast distribution,
        GpuBroadcastExchangeExec.scala:140-165). Tasks consume it through
        ClusterBroadcastReadExec -> BroadcastManager (one deserialize per
        executor process, not one per task)."""
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        from spark_rapids_tpu.parallel.broadcast import BroadcastManager

        # nested broadcasts in the build side read the driver-local registry
        root = stage.root.transform_up(lambda n: self._resolve_broadcast(
            n, stages))
        dm = DeviceManager.initialize(self.conf)
        cleanups: List = []
        ctx = ExecContext(self.conf, partition_id=0, num_partitions=1,
                          device_manager=dm, cleanups=cleanups)
        try:
            batch = next(iter(root.execute(ctx)))
            schema = root.output.to_pa()
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, schema) as w:
                w.write_table(batch.to_arrow().cast(schema))
            ipc = sink.getvalue().to_pybytes()
        finally:
            for fn in cleanups:
                fn()
        from spark_rapids_tpu.parallel.broadcast import BROADCAST_IDS
        stage.broadcast_id = next(BROADCAST_IDS)
        # track for cleanup BEFORE any push: a failed executor push must
        # not leak the driver entry or the blobs already pushed
        broadcast_ids.append(stage.broadcast_id)
        # driver-local registration first (serves in-process executors and
        # nested driver-side builds), then one push per process executor
        BroadcastManager.put(stage.broadcast_id, ipc)
        for ex in self.executors:
            ex.send_broadcast(stage.broadcast_id, ipc)

    @staticmethod
    def _resolve_broadcast(node: PhysicalExec,
                           stages: List[_Stage]) -> PhysicalExec:
        if isinstance(node, ClusterBroadcastReadExec):
            node.broadcast_id = stages[node.stage_index].broadcast_id
        return node

    def _run_stage(self, stage: _Stage, stages: List[_Stage]) -> None:
        from spark_rapids_tpu.execs.exchange_execs import RangePartitioning
        # resolve dep shuffle ids into the read leaves, then pickle
        dep_statuses: Dict[int, List[MapStatus]] = {}
        leaves: List[ClusterShuffleReadExec] = []

        def fix(node: PhysicalExec) -> PhysicalExec:
            if isinstance(node, ClusterShuffleReadExec):
                dep = stages[node.stage_index]
                node.shuffle_id = dep.shuffle_id
                dep_statuses[dep.shuffle_id] = dep.statuses
                leaves.append(node)
            return self._resolve_broadcast(node, stages)

        root = stage.root.transform_up(fix)
        self._coalesce_stage_reads(stage, stages, leaves, root)
        # task count reflects post-coalesce partitioning (a dep's observed
        # sizes may have shrunk this stage's input partition count)
        if stage.is_result:
            stage.num_tasks = max(1, root.num_partitions)
            num_source = stage.num_tasks
        else:
            num_source = max(1, root.children[0].num_partitions)
            single_task = isinstance(root.partitioning, RangePartitioning)
            stage.num_tasks = 1 if single_task else num_source
        try:
            blob = pickle.dumps(root)
        except Exception as e:  # lambda UDFs etc.: hand back to local engine
            raise _Unpicklable(str(e)) from e

        tasks = [_TaskSpec(
            kind="result" if stage.is_result else "map",
            plan_blob=blob, partitions=(p,),
            num_source_parts=num_source,
            shuffle_id=stage.shuffle_id,
            num_reduce_parts=(0 if stage.is_result else
                              stage.root.partitioning.num_partitions),
            dep_statuses=dep_statuses, conf=self.conf)
            for p in range(stage.num_tasks)]

        if not stage.is_result:
            # lineage capture: the blob is the immutable sub-plan snapshot
            # (fix mutates shared nodes, so re-pickling later would drift),
            # and the program-cache signature is the stable replay key a
            # re-execution is checked against
            from spark_rapids_tpu.serving.program_cache import plan_key
            self._lineage[stage.shuffle_id] = _StageLineage(
                stage_index=stage.index, plan_blob=blob,
                signature=plan_key(root, self.conf),
                num_source_parts=num_source,
                num_reduce_parts=stage.root.partitioning.num_partitions,
                dep_stage_indices=tuple(stage.deps),
                task_partitions={p: t.partitions
                                 for t in tasks for p in t.partitions})

        results = self._run_recomputing(tasks, stages, stage.deps, [0])

        if stage.is_result:
            per_part: List[Tuple[int, bytes]] = []
            for blob_out in results:
                if blob_out:
                    per_part.extend(pickle.loads(blob_out))
            tables: List[pa.Table] = []
            for _, ipc in sorted(per_part, key=lambda x: x[0]):
                with pa.ipc.open_stream(pa.BufferReader(ipc)) as r:
                    tables.append(r.read_all())
            stage.result_tables = tables
        else:
            statuses: List[MapStatus] = []
            for blob_out in results:
                statuses.extend(pickle.loads(blob_out))
            stage.statuses = statuses

    # -------------------------------------------------------- lineage recompute
    def _executor_alive(self, ex) -> bool:
        try:
            return bool(ex.alive())
        except Exception:
            return False

    @staticmethod
    def _dep_statuses(stages: List[_Stage],
                      dep_indices: Sequence[int]
                      ) -> Dict[int, List[MapStatus]]:
        """LIVE dep map statuses (broadcast deps have no shuffle): read at
        (re)dispatch time so a replay observes replacements a recompute
        round just made."""
        return {stages[d].shuffle_id: stages[d].statuses
                for d in dep_indices if stages[d].shuffle_id is not None}

    # rung 2 of the failure ladder: the lineage-recompute triage loop
    @uerr.triage_boundary
    def _run_recomputing(self, tasks: List[_TaskSpec], stages: List[_Stage],
                         dep_indices: Sequence[int], budget: List[int],
                         exclude: Set[str] = frozenset()
                         ) -> List[Optional[bytes]]:
        """Drive ``tasks`` to completion through the lineage-recompute loop
        (the stage half of Spark's "task retry IS stage re-execution"):

        - a task failing because the executor it ran ON died is merely LOST
          work — requeued on the survivors (its fetch error, if any, names
          whichever remote it happened to be reading and must not steer a
          recompute);
        - a ``ShuffleFetchFailedError`` from a live executor is the scoped
          recompute signal: the named peer's lost map tasks are re-executed
          from lineage on surviving peers, dep statuses refresh, and ONLY
          the unfinished tasks re-dispatch;
        - anything else is a real failure and surfaces unchanged.

        ``budget`` is the stage-attempt counter (one mutable cell shared
        with nested replays so a flapping fault cannot recurse forever);
        past ``shuffle.recompute.maxStageAttempts`` the fetch error
        re-surfaces and the serving failover path owns recovery."""
        results: List[Optional[bytes]] = [None] * len(tasks)
        work = list(enumerate(tasks))
        while True:
            live = [ex for ex in self.executors if self._executor_alive(ex)]
            targets = ([ex for ex in live if ex.executor_id not in exclude]
                       or live)
            if not targets:
                raise RuntimeError("no live executors remain to run stage "
                                   "tasks")
            errors = self._run_tasks(work, results, targets)
            if not errors:
                return results
            recompute: List[ShuffleFetchFailedError] = []
            only_lost = True
            for ex, e in errors:
                if not self._executor_alive(ex):
                    continue                  # lost work, not a signal
                only_lost = False
                if not isinstance(e, ShuffleFetchFailedError):
                    raise e
                recompute.append(e)
            if not only_lost:
                budget[0] += 1
                max_attempts = self.conf.get(
                    cfg.SHUFFLE_RECOMPUTE_MAX_STAGE_ATTEMPTS)
                if budget[0] > max_attempts:
                    mt.RECOMPUTE_METRICS[
                        mt.SHUFFLE_RECOMPUTE_ESCALATIONS].add(1)
                    raise recompute[0]
                for err in recompute:
                    self._recompute_lost_maps(err, stages, dep_indices,
                                              budget)
            refreshed = self._dep_statuses(stages, dep_indices)
            work = [(i, _dc_replace(tasks[i], dep_statuses=refreshed))
                    for i in range(len(tasks)) if results[i] is None]

    def _recompute_lost_maps(self, err: ShuffleFetchFailedError,
                             stages: List[_Stage],
                             dep_indices: Sequence[int],
                             budget: List[int]) -> None:
        """Scope one fetch failure to the map tasks that must replay. The
        error's blocks are the per-shuffle scope; a DEAD peer additionally
        widens to every map id it owned in the dep shuffles, because
        zero-row blocks never register in the catalog — the block list a
        single reduce partition observed can under-count a dead peer's map
        tasks whose pieces for THAT partition were empty."""
        by_shuffle: Dict[int, Set[int]] = {}
        for b in err.blocks:
            by_shuffle.setdefault(b.shuffle_id, set()).add(b.map_id)
        peer = err.executor_id
        peer_ex = next((ex for ex in self.executors
                        if ex.executor_id == peer), None)
        peer_dead = peer_ex is None or not self._executor_alive(peer_ex)
        if peer_dead:
            for d in dep_indices:
                sid = stages[d].shuffle_id
                if sid is None:
                    continue
                owned = {st.map_id for st in stages[d].statuses
                         if st.executor_id == peer}
                if owned:
                    by_shuffle.setdefault(sid, set()).update(owned)
        for sid in sorted(by_shuffle):
            self._replay_map_tasks(sid, sorted(by_shuffle[sid]), {peer},
                                   stages, budget)

    def _replay_map_tasks(self, shuffle_id: int, map_ids: List[int],
                          exclude: Set[str], stages: List[_Stage],
                          budget: List[int]) -> None:
        """Re-execute the lost map tasks of one shuffle from lineage on
        surviving peers and REPLACE their outputs exactly-once: stale
        catalog entries drop first on every live executor (a replay landing
        where the originals still live must not double rows for a later
        reader), then the fresh MapStatus entries replace the lost ones
        by map id in the owning stage's statuses."""
        lin = self._lineage.get(shuffle_id)
        if lin is None:
            raise RuntimeError(
                f"no lineage recorded for shuffle {shuffle_id}; cannot "
                f"recompute map tasks {map_ids}")
        from spark_rapids_tpu.serving.program_cache import plan_key
        root = pickle.loads(lin.plan_blob)
        sig = plan_key(root, self.conf)
        if sig != lin.signature:
            raise RuntimeError(
                f"lineage replay key mismatch for shuffle {shuffle_id}: "
                f"{sig} != {lin.signature} — replay would not be "
                f"deterministic, escalating")
        mt.RECOMPUTE_METRICS[mt.SHUFFLE_RECOMPUTES].add(1)
        mt.RECOMPUTE_METRICS[mt.SHUFFLE_RECOMPUTED_MAP_TASKS].add(
            len(map_ids))
        for ex in self.executors:
            if not self._executor_alive(ex):
                continue
            for m in map_ids:
                try:
                    ex.cleanup_map_outputs(shuffle_id, m)
                except Exception:
                    pass          # best-effort: a dying executor's catalog
        specs = [_TaskSpec(
            kind="map", plan_blob=lin.plan_blob,
            partitions=lin.task_partitions[m],
            num_source_parts=lin.num_source_parts,
            shuffle_id=shuffle_id, num_reduce_parts=lin.num_reduce_parts,
            dep_statuses=self._dep_statuses(stages, lin.dep_stage_indices),
            conf=self.conf)
            for m in map_ids]
        # the shared attempt budget rides into the nested run: a replay
        # whose own dep shuffle was lost recomputes it recursively, bounded
        # by the same maxStageAttempts cell
        blobs = self._run_recomputing(specs, stages, lin.dep_stage_indices,
                                      budget, exclude=exclude)
        fresh: List[MapStatus] = []
        for blob in blobs:
            fresh.extend(pickle.loads(blob))
        owner = stages[lin.stage_index]
        replaced = set(map_ids)
        # in-place: every dep_statuses dict built earlier references THIS
        # list object, so readers of the next dispatch see the replacement
        owner.statuses[:] = [st for st in owner.statuses
                             if st.map_id not in replaced] + fresh

    def _run_tasks(self, work: List[Tuple[int, _TaskSpec]],
                   results: List[Optional[bytes]],
                   executors: List) -> List[Tuple[object, Exception]]:
        """Run one round of (index, spec) work items across ``executors``:
        a work queue per executor drained by ``taskSlots`` worker threads,
        so up to executors * taskSlots tasks are in flight and stage
        wall-clock scales with partitions, not executors. Errors stop the
        round fast (remaining queued items are abandoned) and return as
        (executor, error) pairs for the recompute loop to triage — stage
        re-execution via lineage, SURVEY.md §5."""
        import collections
        # tasks pin to executors round-robin (Spark's locality preference:
        # an executor's map outputs stay in ITS shuffle catalog, so spreading
        # map tasks keeps reduce reads mostly local); each executor drains
        # its queue with `taskSlots` concurrent workers
        n_ex = len(executors)
        queues = [collections.deque() for _ in range(n_ex)]
        for k, item in enumerate(work):
            queues[k % n_ex].append(item)
        qlock = threading.Lock()
        errors: List[Tuple[object, Exception]] = []
        slots = max(1, self.conf.get(cfg.CLUSTER_TASK_SLOTS))

        # the collection point of the recompute triage: every task failure
        # (the scoped ShuffleFetchFailedError signal above all) lands in
        # the errors ledger for _run_recomputing to route — never dropped
        @uerr.triage_boundary
        def worker(home: int, ex) -> None:
            while not errors:
                with qlock:
                    if not queues[home]:
                        return
                    idx, spec = queues[home].popleft()
                try:
                    results[idx] = ex.submit(spec)
                except Exception as e:       # triaged after join
                    errors.append((ex, e))
                    return

        threads = [threading.Thread(target=worker, args=(i, ex),
                                    name=f"task-slot-{i}-{s}")
                   for i, ex in enumerate(executors)
                   for s in range(min(slots, len(queues[i])))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return errors

    def close(self) -> None:
        import shutil
        for ex in self.executors:
            try:
                ex.close()
            except Exception:
                pass
        self.executors = []
        for d in self._owned_dirs:
            shutil.rmtree(d, ignore_errors=True)
        self._owned_dirs = []


def cluster_scheduler_for(session) -> ClusterScheduler:
    """One scheduler (and executor set) per session, created lazily."""
    sched = getattr(session, "_cluster_scheduler", None)
    if sched is None or sched.n != session.conf.get(cfg.CLUSTER_EXECUTORS) \
            or not sched.executors:
        if sched is not None:
            sched.close()
        sched = ClusterScheduler(session.conf)
        session._cluster_scheduler = sched
    return sched
