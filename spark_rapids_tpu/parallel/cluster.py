"""Multi-executor query execution over the shuffle-manager stack.

The load-bearing path for the accelerated shuffle protocol: a physical plan
is split into shuffle stages at exchange boundaries (Spark's DAGScheduler
role), map tasks run across executors writing each reduce partition's device
batches through the CachingShuffleWriter into that executor's spillable
shuffle catalog (RapidsShuffleInternalManager.scala:194 getWriter ->
RapidsCachingWriter), and reduce-side reads serve local blocks from the
catalog and fetch remote blocks through the transport client
(RapidsCachingReader.scala + RapidsShuffleIterator) — in-process fabric or
real TCP sockets, including executors in separate OS processes.

Contrast with the mesh engine (execs/mesh_execs.py): there an exchange is an
XLA collective inside one SPMD program; here it is the reference's
pull-based, executor-to-executor protocol. Both produce identical results —
tests assert query equality across the two paths and the single-process
engine.

Range partitioning runs its map stage as ONE task (bounds need a global
sample; the reference pays a separate sampling job for the same reason —
SamplingUtils) — the reduce side still fans out across executors.
"""
from __future__ import annotations

import atexit
import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar.dtypes import Schema
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.execs.base import ExecContext, LeafExec, PhysicalExec
from spark_rapids_tpu.shuffle.manager import (CachingShuffleReader,
                                              CachingShuffleWriter, MapStatus,
                                              MapOutputTracker, ShuffleEnv)

_TCP_TRANSPORT = "spark_rapids_tpu.shuffle.tcp.TcpTransport"


# ------------------------------------------------------------------ plan split
class ClusterShuffleReadExec(LeafExec):
    """Reduce-side leaf standing in for an exchange: reads one partition of a
    parent stage's shuffle through the executor's caching reader (the
    ShuffledBatchRDD + RapidsCachingReader composition)."""

    is_device = True

    def __init__(self, stage_index: int, output: Schema, num_parts: int):
        super().__init__(output)
        self.stage_index = stage_index
        self.num_parts = num_parts
        self.shuffle_id: Optional[int] = None  # driver assigns pre-pickle

    @property
    def num_partitions(self) -> int:
        return self.num_parts

    def execute(self, ctx: ExecContext):
        cs = getattr(ctx, "cluster_shuffle", None)
        assert cs is not None, "cluster shuffle read outside a cluster task"
        tracker = MapOutputTracker()
        tracker.register_shuffle(self.shuffle_id)
        for st in cs.statuses[self.shuffle_id]:
            tracker.register_map_output(self.shuffle_id, st)
        reader = CachingShuffleReader(cs.env, tracker, self.shuffle_id,
                                      ctx.partition_id)
        for batch in reader.read():
            self.count_output(batch.num_rows)
            yield batch


@dataclass
class _Stage:
    index: int
    #: exchange exec (shuffle stages) or the final plan (result stage); its
    #: subtree may contain ClusterShuffleReadExec leaves for dep stages
    root: PhysicalExec
    is_result: bool
    deps: List[int] = field(default_factory=list)
    shuffle_id: Optional[int] = None
    num_tasks: int = 1
    statuses: List[MapStatus] = field(default_factory=list)
    #: result stage only: collected tables in partition order
    result_tables: List = field(default_factory=list)


def split_stages(final: PhysicalExec) -> Optional[List[_Stage]]:
    """Cut the plan at device shuffle-exchange boundaries. Returns None when
    the plan has exchanges the cluster cannot stage (CPU exchanges), handing
    execution back to the single-process engine."""
    from spark_rapids_tpu.execs.exchange_execs import (
        CpuShuffleExchangeExec, RangePartitioning, TpuShuffleExchangeExec)
    stages: List[_Stage] = []

    def walk(node: PhysicalExec, deps: List[int]) -> PhysicalExec:
        if isinstance(node, CpuShuffleExchangeExec):
            raise _Unstageable()
        if isinstance(node, TpuShuffleExchangeExec):
            child_deps: List[int] = []
            new_child = walk(node.children[0], child_deps)
            exchange = node.with_children([new_child])
            idx = len(stages)
            n_parts = exchange.partitioning.num_partitions
            single_task = isinstance(exchange.partitioning,
                                     RangePartitioning)
            stage = _Stage(idx, exchange, is_result=False, deps=child_deps,
                           num_tasks=(1 if single_task
                                      else max(1, new_child.num_partitions)))
            stages.append(stage)
            deps.append(idx)
            return ClusterShuffleReadExec(idx, exchange.output, n_parts)
        new_kids = [walk(c, deps) for c in node.children]
        if any(a is not b for a, b in zip(new_kids, node.children)):
            return node.with_children(new_kids)
        return node

    class _Unstageable(Exception):
        pass

    try:
        result_deps: List[int] = []
        new_final = walk(final, result_deps)
    except _Unstageable:
        return None
    result = _Stage(len(stages), new_final, is_result=True, deps=result_deps,
                    num_tasks=max(1, new_final.num_partitions))
    stages.append(result)
    return stages


# ------------------------------------------------------------------ tasks
@dataclass
class ClusterTaskContext:
    env: ShuffleEnv
    statuses: Dict[int, List[MapStatus]]


@dataclass
class _TaskSpec:
    kind: str                        # "map" | "result"
    plan_blob: bytes                 # pickled stage root
    partitions: Tuple[int, ...]      # partition ids this task runs
    num_source_parts: int
    shuffle_id: Optional[int]
    num_reduce_parts: int
    dep_statuses: Dict[int, List[MapStatus]]
    conf: TpuConf


def _run_task(env: ShuffleEnv, spec: _TaskSpec) -> bytes:
    """Execute one task against this executor's shuffle env. Returns pickled
    [MapStatus...] for map tasks or arrow-IPC table bytes for result tasks."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    root = pickle.loads(spec.plan_blob)
    dm = DeviceManager.initialize(spec.conf)
    cleanups: List = []
    cs = ClusterTaskContext(env, spec.dep_statuses)

    def make_ctx(p: int) -> ExecContext:
        ctx = ExecContext(spec.conf, partition_id=p,
                          num_partitions=spec.num_source_parts,
                          device_manager=dm, cleanups=cleanups)
        ctx.cluster_shuffle = cs
        return ctx

    try:
        if spec.kind == "map":
            statuses = [
                _map_one_partition(root, make_ctx(p), p, env,
                                   spec.shuffle_id, spec.num_reduce_parts)
                for p in spec.partitions]
            return pickle.dumps(statuses)
        # result tasks keep (partition_id, ipc bytes) so the driver can
        # reassemble global partition order (sorted output depends on it)
        out: List[Tuple[int, bytes]] = []
        schema = root.output.to_pa()
        for p in spec.partitions:
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, schema) as w:
                for b in root.execute(make_ctx(p)):
                    w.write_table(b.to_arrow().cast(schema))
            out.append((p, sink.getvalue().to_pybytes()))
        return pickle.dumps(out)
    finally:
        for fn in cleanups:
            fn()


def _map_one_partition(exchange, ctx: ExecContext, p: int, env: ShuffleEnv,
                       shuffle_id: int, n_reduce: int) -> MapStatus:
    """The map side of one source partition: the exchange's own map-piece
    protocol (iter_map_pieces — shared with the single-process engine),
    cached through the caching writer (RapidsCachingWriter.write). A
    range-partitioned stage runs as one task, so it maps EVERY source
    partition here (bounds need the global sample)."""
    from spark_rapids_tpu.execs.exchange_execs import RangePartitioning
    tracker = MapOutputTracker()  # local; the real one lives on the driver
    tracker.register_shuffle(shuffle_id)
    writer = CachingShuffleWriter(env, tracker, shuffle_id, map_id=p,
                                  num_partitions=n_reduce)
    wanted = (None if isinstance(exchange.partitioning, RangePartitioning)
              else (p,))
    return writer.write(
        (j, sub) for _, j, sub in exchange.iter_map_pieces(ctx, wanted))


# ------------------------------------------------------------------ executors
class InProcessExecutor:
    """One executor inside the driver process: its own shuffle env (stores,
    catalog, transport server); tasks run on the caller thread pool."""

    def __init__(self, executor_id: str, conf: TpuConf, disk_dir: str):
        self.executor_id = executor_id
        self.env = ShuffleEnv(executor_id, conf, disk_dir=disk_dir)

    def submit(self, spec: _TaskSpec) -> bytes:
        return _run_task(self.env, spec)

    def cleanup_shuffle(self, shuffle_id: int) -> None:
        self.env.shuffle_catalog.remove_shuffle(shuffle_id)

    def close(self) -> None:
        self.env.close()


def _send_msg(sock: socket.socket, obj) -> None:
    blob = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(blob)) + blob)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("executor control socket closed")
        hdr += chunk
    n = struct.unpack(">I", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("executor control socket closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class ProcessExecutor:
    """One executor in its own OS process: the daemon builds a ShuffleEnv on
    the TCP transport and serves tasks over a control socket. Shuffle DATA
    never touches the control plane — it rides the shuffle TCP sockets
    between executor processes (metadata-via-driver, data-P2P, the
    reference's split)."""

    def __init__(self, executor_id: str, conf: TpuConf):
        self.executor_id = executor_id
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "spark_rapids_tpu.parallel.executor_daemon",
             "--executor-id", executor_id, "--control-port", str(port)],
            env=env)
        listener.settimeout(60)
        self.sock, _ = listener.accept()
        listener.close()
        self._lock = threading.Lock()
        _send_msg(self.sock, {"type": "init", "conf": conf})
        resp = _recv_msg(self.sock)
        if resp.get("type") != "ready":
            raise RuntimeError(f"executor {executor_id} failed to start: "
                               f"{resp}")

    def submit(self, spec: _TaskSpec) -> bytes:
        with self._lock:
            _send_msg(self.sock, {"type": "task", "spec": spec})
            resp = _recv_msg(self.sock)
        if resp["type"] == "error":
            raise RuntimeError(
                f"task failed on {self.executor_id}: {resp['message']}")
        return resp["blob"]

    def cleanup_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            _send_msg(self.sock, {"type": "cleanup",
                                  "shuffle_id": shuffle_id})
            _recv_msg(self.sock)

    def close(self) -> None:
        try:
            with self._lock:
                _send_msg(self.sock, {"type": "stop"})
            self.sock.close()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


class _Unpicklable(Exception):
    """A stage subtree cannot ship to executors (e.g. a lambda UDF)."""


# ------------------------------------------------------------------ scheduler
class ClusterScheduler:
    """Stage-by-stage driver (the DAGScheduler role): map stages fan tasks
    across executors and register MapStatus with the driver tracker; the
    result stage's arrow output returns to the caller."""

    def __init__(self, conf: TpuConf):
        self._owned_dirs: List[str] = []
        self.conf = self._prepare_conf(conf)
        self.n = conf.get(cfg.CLUSTER_EXECUTORS)
        self._tmp = tempfile.mkdtemp(prefix="spark-rapids-tpu-cluster-")
        self._owned_dirs.append(self._tmp)
        if conf.get(cfg.CLUSTER_PROCESS_EXECUTORS):
            self.executors = [ProcessExecutor(f"exec-{i}", self.conf)
                              for i in range(self.n)]
        else:
            self.executors = [
                InProcessExecutor(f"exec-{i}", self.conf,
                                  os.path.join(self._tmp, f"exec-{i}"))
                for i in range(self.n)]
        self._next_shuffle = 0
        atexit.register(self.close)

    def _prepare_conf(self, conf: TpuConf) -> TpuConf:
        extra = {}
        if conf.get(cfg.CLUSTER_PROCESS_EXECUTORS):
            if not conf.get_raw("spark.rapids.tpu.shuffle.transport.class"):
                extra["spark.rapids.tpu.shuffle.transport.class"] = \
                    _TCP_TRANSPORT
            if not conf.shuffle_tcp_registry:
                reg = tempfile.mkdtemp(prefix="spark-rapids-tpu-registry-")
                self._owned_dirs.append(reg)
                extra["spark.rapids.tpu.shuffle.tcp.registryDir"] = reg
        return conf.with_overrides(extra) if extra else conf

    def _widen_scans(self, plan: PhysicalExec) -> PhysicalExec:
        """File scans default to one scan task; spread multi-file scans
        across the executors (FilePartition planning)."""
        import copy

        def fix(node: PhysicalExec) -> PhysicalExec:
            files = getattr(node, "files", None)
            if getattr(node, "is_file_scan", False) and files:
                n = min(len(files), 2 * len(self.executors))
                if n > 1 and node.scan_partitions == 1:
                    node = copy.copy(node)
                    node.scan_partitions = n
            return node
        return plan.transform_up(fix)

    def run(self, final: PhysicalExec) -> Optional[List[pa.Table]]:
        """Execute the plan across the cluster; None = plan not stageable
        (caller falls back to the single-process engine)."""
        final = self._widen_scans(final)
        stages = split_stages(final)
        if stages is None:
            return None
        self.last_stages = stages  # introspection for tests/explain
        shuffle_ids: List[int] = []
        try:
            for stage in stages:
                if not stage.is_result:
                    stage.shuffle_id = self._next_shuffle
                    self._next_shuffle += 1
                    shuffle_ids.append(stage.shuffle_id)
                self._run_stage(stage, stages)
            result = stages[-1]
            return result.result_tables
        except _Unpicklable:
            # an unpicklable plan (e.g. lambda UDFs) cannot ship to
            # executors: fall back to the single-process engine
            return None
        finally:
            for sid in shuffle_ids:
                for ex in self.executors:
                    try:
                        ex.cleanup_shuffle(sid)
                    except Exception:
                        pass

    def _run_stage(self, stage: _Stage, stages: List[_Stage]) -> None:
        # resolve dep shuffle ids into the read leaves, then pickle
        dep_statuses: Dict[int, List[MapStatus]] = {}

        def fix(node: PhysicalExec) -> PhysicalExec:
            if isinstance(node, ClusterShuffleReadExec):
                dep = stages[node.stage_index]
                node.shuffle_id = dep.shuffle_id
                dep_statuses[dep.shuffle_id] = dep.statuses
            return node

        root = stage.root.transform_up(fix)
        try:
            blob = pickle.dumps(root)
        except Exception as e:  # lambda UDFs etc.: hand back to local engine
            raise _Unpicklable(str(e)) from e
        if stage.is_result:
            num_source = stage.num_tasks
        else:
            num_source = max(1, root.children[0].num_partitions)
        assignments: List[Tuple[int, List[int]]] = []
        for i, ex in enumerate(self.executors):
            parts = list(range(i, stage.num_tasks, len(self.executors)))
            if parts:
                assignments.append((i, parts))

        specs = []
        for i, parts in assignments:
            specs.append((i, _TaskSpec(
                kind="result" if stage.is_result else "map",
                plan_blob=blob, partitions=tuple(parts),
                num_source_parts=num_source,
                shuffle_id=stage.shuffle_id,
                num_reduce_parts=(0 if stage.is_result else
                                  stage.root.partitioning.num_partitions),
                dep_statuses=dep_statuses, conf=self.conf)))

        results: List[Optional[bytes]] = [None] * len(specs)
        errors: List[Exception] = []

        def run(slot: int, exec_idx: int, spec: _TaskSpec):
            try:
                results[slot] = self.executors[exec_idx].submit(spec)
            except Exception as e:  # surfaced after join
                errors.append(e)

        threads = [threading.Thread(target=run, args=(s, i, spec))
                   for s, (i, spec) in enumerate(specs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

        if stage.is_result:
            per_part: List[Tuple[int, bytes]] = []
            for blob_out in results:
                if blob_out:
                    per_part.extend(pickle.loads(blob_out))
            tables: List[pa.Table] = []
            for _, ipc in sorted(per_part, key=lambda x: x[0]):
                with pa.ipc.open_stream(pa.BufferReader(ipc)) as r:
                    tables.append(r.read_all())
            stage.result_tables = tables
        else:
            statuses: List[MapStatus] = []
            for blob_out in results:
                statuses.extend(pickle.loads(blob_out))
            stage.statuses = statuses

    def close(self) -> None:
        import shutil
        for ex in self.executors:
            try:
                ex.close()
            except Exception:
                pass
        self.executors = []
        for d in self._owned_dirs:
            shutil.rmtree(d, ignore_errors=True)
        self._owned_dirs = []


def cluster_scheduler_for(session) -> ClusterScheduler:
    """One scheduler (and executor set) per session, created lazily."""
    sched = getattr(session, "_cluster_scheduler", None)
    if sched is None or sched.n != session.conf.get(cfg.CLUSTER_EXECUTORS) \
            or not sched.executors:
        if sched is not None:
            sched.close()
        sched = ClusterScheduler(session.conf)
        session._cluster_scheduler = sched
    return sched
