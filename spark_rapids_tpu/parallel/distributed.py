"""Distributed query execution over a device mesh.

Replaces the reference's multi-process task parallelism + UCX shuffle for the
*aggregation* exchange pattern: instead of hash-partitioning batches and moving
them peer-to-peer (RapidsShuffleClient/Server), a distributed aggregate runs as
ONE SPMD program under shard_map:

  phase 1 (local): each device partially aggregates its row shard
          (group_aggregate evaluate=False — the Partial mode);
  phase 2 (ICI):   partial keys+buffers all-gather across the data axis —
          a single XLA collective on the interconnect, no host round-trip;
  phase 3 (merge): every device merges the gathered partials identically
          (merge_aggregate — the Final mode), yielding replicated results.

For large group cardinalities the all-gather is replaced by a hash-partitioned
all-to-all (see shuffle/), but the program structure is identical.
"""
from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DType, Schema
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression
from spark_rapids_tpu.ops.aggregate import group_aggregate, merge_aggregate


from spark_rapids_tpu.exprs.core import (flatten_colvs as _flatten_colvs,
                                         unflatten_colvs as _unflatten_colvs)

def build_distributed_aggregate(mesh: Mesh, schema: Schema,
                                key_exprs: Tuple[Expression, ...],
                                agg_fns: Tuple,
                                local_capacity: int,
                                string_max_bytes: int = 256,
                                axis: str = "data"):
    """Build (or fetch the cached) jitted SPMD aggregate step.

    Cached through the engine's keyed program cache (_cached_jit): a fresh
    jit(shard_map(closure)) per call would re-trace and recompile the whole
    aggregate every time (R001 recompile hazard — the q4 compile-wall class
    of bug).

    Returns fn(num_rows_local [n_dev] int32, *flat sharded arrays) ->
    (flat merged outputs..., num_groups) with outputs replicated.
    """
    n_dev = mesh.devices.size

    def local_step(num_rows_local, *flat_local):
        # shard_map body: arrays are the per-device shard [local_capacity, ...]
        colvs = _unflatten_colvs(schema, flat_local)
        ectx = EvalCtx(jnp, colvs, local_capacity, string_max_bytes)
        my_rows = num_rows_local[0]
        key_cols, buf_cols, num_groups = group_aggregate(
            jnp, ectx, key_exprs, agg_fns, my_rows, local_capacity,
            evaluate=False)

        # phase 2: gather partials over ICI
        gathered_alive = jax.lax.all_gather(
            jnp.arange(local_capacity, dtype=np.int32) < num_groups,
            axis, tiled=True)
        gath_keys = [_gather_colv(k, axis) for k in key_cols]
        gath_bufs = [_gather_colv(b, axis) for b in buf_cols]

        # phase 3: identical merge on every device -> replicated outputs
        out_keys, out_res, total_groups = merge_aggregate(
            jnp, gath_keys, gath_bufs, agg_fns, gathered_alive,
            local_capacity * n_dev)
        return tuple(_flatten_colvs(list(out_keys) + list(out_res))) + (
            total_groups,)

    in_specs = (P(axis),) + tuple(
        P(axis) for _ in range(_flat_len(schema)))
    out_specs = _out_specs(key_exprs, agg_fns) + (P(),)

    from spark_rapids_tpu import shims
    from spark_rapids_tpu.execs.tpu_execs import _cached_jit
    # shim resolved here, once: its identity is part of the key, so a
    # provider swap can never serve the old backend's program (R016)
    shim = shims.get()
    key = ("dist-agg", type(shim).__name__, mesh, schema, tuple(key_exprs),
           tuple(agg_fns), local_capacity, string_max_bytes, axis)
    return _cached_jit(key, lambda: shim.shard_map(
        local_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False))


def _gather_colv(v: ColV, axis: str) -> ColV:
    data = jax.lax.all_gather(v.data, axis, tiled=True)
    validity = jax.lax.all_gather(v.validity, axis, tiled=True)
    lengths = (jax.lax.all_gather(v.lengths, axis, tiled=True)
               if v.lengths is not None else None)
    return ColV(v.dtype, data, validity, lengths)


from spark_rapids_tpu.exprs.core import flat_len as _flat_len


def _out_specs(key_exprs, agg_fns) -> Tuple:
    n_out = 0
    for e in key_exprs:
        n_out += 3 if e.dtype() is DType.STRING else 2
    for fn in agg_fns:
        dt = fn.dtype()
        n_out += 3 if dt is DType.STRING else 2
    return tuple(P() for _ in range(n_out))
