"""Deterministic dsdgen-alike for the full TPC-DS table set: the store,
catalog and web sales channels, their returns, inventory, and every dimension
the query suite touches.

Reference analog: TpcdsLikeSpark.scala's table setup (the reference converts
real dsdgen output; this generator synthesizes the same shapes) with the
structural properties the queries depend on: ticket/order-level consistency
(all lines of one ticket or order share customer/store/date — the per-order
count-distinct queries group on that), returns sampled from their sales facts
(same order/item link), a catalog-channel replay of store returns (the
bought/returned/bought-again chains), planted price/brand bands where random
draws would qualify ~0 rows at small scales, ~4% null foreign keys like
dsdgen emits, a real calendar for date_dim, and cross-product demographics
dimensions. Doubles stand in for decimals (v0 has no decimal support).
"""
from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

_EPOCH = datetime.date(1970, 1, 1)
_D0 = datetime.date(1998, 1, 1)
_DAYS = (datetime.date(2003, 12, 31) - _D0).days + 1
#: dsdgen's julian-style first date key
_SK0 = 2450815

CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Men",
              "Music", "Shoes", "Sports", "Women", "Children"]
CLASSES = ["accent", "bedding", "classical", "dresses", "mens watch",
           "pants", "football", "romance", "fiction", "shirts", "athletic",
           "computers", "stereo", "portable", "reference", "personal",
           "self-help", "fragrances", "accessories"]

#: planted (category, class, brand) combos matching the brand-list predicates
#: of q53/q63 — random draws over the three independent pools would qualify
#: ~0 items at small scales
_BRAND_COMBOS = [
    ("Books", "personal", "scholaramalgamalg #14"),
    ("Children", "portable", "scholaramalgamalg #7"),
    ("Electronics", "reference", "exportiunivamalg #9"),
    ("Books", "self-help", "scholaramalgamalg #9"),
    ("Women", "accessories", "amalgimporto #1"),
    ("Music", "classical", "edu packscholar #1"),
    ("Men", "fragrances", "exportiimporto #1"),
    ("Women", "pants", "importoamalg #1"),
]
CITIES = ["Midway", "Fairview", "Oakland", "Riverside", "Five Points",
          "Centerville", "Oak Grove", "Pleasant Hill", "Bethel", "Clinton",
          "Antioch", "Marion", "Greenville", "Union", "Salem", "Spring Hill",
          "Shiloh", "Liberty", "Wilson", "Glendale"]
COUNTIES = ["Williamson County", "Walker County", "Ziebach County",
            "Daviess County", "Barrow County", "Franklin Parish",
            "Luce County", "Richland County"]
STATES = ["TN", "GA", "SD", "IN", "LA", "MI", "SC", "OH", "TX", "CA"]
FIRST_NAMES = ["James", "Mary", "John", "Linda", "Robert", "Susan", "Ana",
               "David", "Carlos", "Laura", "Kevin", "Grace", "Amy", "Paul"]
LAST_NAMES = ["Smith", "Jones", "Brown", "Davis", "Miller", "Moore",
              "Garcia", "Lopez", "Lee", "Walker", "Hall", "Young"]
SALUTATIONS = ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir", "Miss"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
MARITAL = ["M", "S", "D", "W", "U"]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]
COLORS = ["powder", "khaki", "brown", "honeydew", "floral", "deep", "light",
          "cornflower", "midnight", "snow", "cyan", "papaya", "orange",
          "frosted", "forest", "ghost", "red", "blue", "green", "white"]
UNITS = ["Ounce", "Oz", "Bunch", "Ton", "N/A", "Dozen", "Box", "Pound",
         "Pallet", "Gross", "Cup", "Dram", "Each", "Tbl", "Lb", "Bundle"]
SIZES = ["medium", "extra large", "N/A", "small", "petite", "large",
         "economy"]


def n_item(scale): return max(int(18_000 * scale), 100)
def n_customer(scale): return max(int(100_000 * scale), 300)
def n_address(scale): return max(int(50_000 * scale), 120)
def n_store(scale): return max(int(12 * scale), 6)
def n_promo(scale): return max(int(300 * scale), 12)
def n_tickets(scale): return max(int(240_000 * scale), 600)


def gen_date_dim() -> pa.Table:
    days = [_D0 + datetime.timedelta(days=i) for i in range(_DAYS)]
    week0 = _D0.isocalendar()[1]
    return pa.table({
        "d_date_sk": pa.array(np.arange(_SK0, _SK0 + _DAYS, dtype=np.int64)),
        "d_date": pa.array([(d - _EPOCH).days for d in days], type=pa.date32()),
        "d_year": pa.array(np.array([d.year for d in days], np.int32)),
        "d_moy": pa.array(np.array([d.month for d in days], np.int32)),
        "d_dom": pa.array(np.array([d.day for d in days], np.int32)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in days],
                                   np.int32)),
        "d_quarter_name": pa.array(
            [f"{d.year}Q{(d.month - 1) // 3 + 1}" for d in days]),
        "d_dow": pa.array(np.array([d.weekday() for d in days], np.int32)),
        "d_day_name": pa.array([DAY_NAMES[d.weekday()] for d in days]),
        # sequential week/month counters like dsdgen's *_seq surrogates
        "d_week_seq": pa.array(np.array(
            [(d - _D0).days // 7 + 1 for d in days], np.int32)),
        "d_month_seq": pa.array(np.array(
            [(d.year - _D0.year) * 12 + d.month - 1 + 1189 for d in days],
            np.int32)),
    })


def gen_time_dim() -> pa.Table:
    sk = np.arange(1440, dtype=np.int64)  # one row per minute of day
    hour = (sk // 60).astype(np.int32)
    meal = np.where((hour >= 6) & (hour < 9), "breakfast",
                    np.where((hour >= 11) & (hour < 13), "lunch",
                             np.where((hour >= 17) & (hour < 20), "dinner",
                                      "")))
    return pa.table({
        "t_time_sk": pa.array(sk),
        "t_hour": pa.array(hour),
        "t_minute": pa.array((sk % 60).astype(np.int32)),
        "t_meal_time": pa.array(meal, mask=(meal == "")),
    })


def gen_item(scale: float, seed: int) -> pa.Table:
    n = n_item(scale)
    rng = np.random.default_rng(seed + 11)
    sk = np.arange(1, n + 1, dtype=np.int64)
    brand_id = (rng.integers(1, 11, n) * 1000000
                + rng.integers(1, 11, n) * 1000 + rng.integers(1, 11, n))
    cat_id = rng.integers(1, len(CATEGORIES) + 1, n).astype(np.int32)
    # dsdgen-style syllable brand names with a small number suffix, so the
    # brand-list predicates (q53/q63 style) have real values to match
    brand_bases = np.array(["amalgimporto #", "edu packscholar #",
                            "exportiimporto #", "importoamalg #",
                            "scholaramalgamalg #", "exportiunivamalg #",
                            "corpamalgamalg #", "amalgamalg #"])
    brand = np.char.add(brand_bases[rng.integers(0, len(brand_bases), n)],
                        rng.integers(1, 16, n).astype(str))
    cls = np.array(CLASSES)[rng.integers(0, len(CLASSES), n)]
    color = np.array(COLORS)[rng.integers(0, len(COLORS), n)]
    units = np.array(UNITS)[rng.integers(0, len(UNITS), n)]
    size = np.array(SIZES)[rng.integers(0, len(SIZES), n)]
    # plant q41-style variant combos (category/color/units/size quadruples
    # its predicate matches) on every 25th-offset-11 item
    variant = [("Women", "powder", "Ounce", "medium"),
               ("Women", "brown", "Bunch", "small"),
               ("Men", "floral", "Dozen", "petite"),
               ("Men", "light", "Box", "medium"),
               ("Women", "midnight", "Pallet", "extra large"),
               ("Men", "orange", "Each", "large")]
    vplant = np.flatnonzero((sk - 1) % 25 == 11)
    vwhich = np.arange(vplant.shape[0]) % len(variant)
    vcat = np.array([v[0] for v in variant])[vwhich]
    cat_id[vplant] = np.array([CATEGORIES.index(c) + 1 for c in vcat],
                              np.int32)
    color[vplant] = np.array([v[1] for v in variant])[vwhich]
    units[vplant] = np.array([v[2] for v in variant])[vwhich]
    size[vplant] = np.array([v[3] for v in variant])[vwhich]
    # plant every 10th item on a qualifying (category, class, brand) combo
    planted = np.flatnonzero((sk - 1) % 10 == 5)
    combo = [np.array([c[j] for c in _BRAND_COMBOS])
             for j in range(3)]
    which = np.arange(planted.shape[0]) % len(_BRAND_COMBOS)
    cat_id[planted] = np.array(
        [CATEGORIES.index(c) + 1 for c in combo[0]], np.int32)[which]
    cls[planted] = combo[1][which]
    brand[planted] = combo[2][which]
    return pa.table({
        "i_item_sk": pa.array(sk),
        "i_item_id": pa.array(np.char.add("AAAAAAAA",
                                          np.char.zfill(sk.astype(str), 8))),
        "i_item_desc": pa.array(np.char.add("item desc ", sk.astype(str))),
        "i_product_name": pa.array(np.char.add("product ", sk.astype(str))),
        "i_brand_id": pa.array(brand_id.astype(np.int32)),
        "i_brand": pa.array(brand),
        "i_class": pa.array(cls),
        "i_category_id": pa.array(cat_id),
        "i_category": pa.array(np.array(CATEGORIES)[cat_id - 1]),
        # cycle so the specific ids queries filter on (manufact 128, manager
        # 1/8/28) exist at any generated item count
        "i_manufact_id": pa.array(((sk - 1) % 1000 + 1).astype(np.int32)),
        "i_manufact": pa.array(np.char.add(
            "manufact#", ((sk - 1) % 50 + 1).astype(str))),
        "i_color": pa.array(color),
        "i_units": pa.array(units),
        "i_size": pa.array(size),
        "i_wholesale_cost": pa.array(np.round(rng.uniform(0.05, 70.0, n), 2)),
        "i_manager_id": pa.array(((sk - 1) % 100 + 1).astype(np.int32)),
        # planted price bands (uniform prices would leave these windows nearly
        # empty at small scales): every 25th item at ~1.00-1.49 (q21/q40/q82's
        # cheap-item window) and every 25th-offset-7 at 68-98 (q37's mid-price
        # window, paired with steady inventory in gen_inventory)
        "i_current_price": pa.array(np.where(
            (sk - 1) % 25 == 3,
            np.round(rng.uniform(1.0, 1.45, n), 2),
            np.where((sk - 1) % 25 == 7,
                     np.round(rng.uniform(68.0, 98.0, n), 2),
                     np.round(rng.uniform(0.09, 99.99, n), 2)))),
    })


def gen_customer(scale: float, seed: int) -> pa.Table:
    n = n_customer(scale)
    rng = np.random.default_rng(seed + 12)
    sk = np.arange(1, n + 1, dtype=np.int64)
    cd_n = 2 * len(MARITAL) * len(EDUCATION) * len(CREDIT)
    hd_n = len(BUY_POTENTIAL) * 10 * 5
    return pa.table({
        "c_customer_sk": pa.array(sk),
        "c_customer_id": pa.array(np.char.add("AAAAAAAA",
                                              np.char.zfill(sk.astype(str), 8))),
        "c_current_addr_sk": pa.array(
            rng.integers(1, n_address(scale) + 1, n).astype(np.int64)),
        "c_current_cdemo_sk": pa.array(rng.integers(1, cd_n + 1, n).astype(np.int64)),
        "c_current_hdemo_sk": pa.array(rng.integers(1, hd_n + 1, n).astype(np.int64)),
        "c_first_name": pa.array(np.array(FIRST_NAMES)[rng.integers(0, len(FIRST_NAMES), n)]),
        "c_last_name": pa.array(np.array(LAST_NAMES)[rng.integers(0, len(LAST_NAMES), n)]),
        "c_salutation": pa.array(np.array(SALUTATIONS)[rng.integers(0, len(SALUTATIONS), n)]),
        "c_preferred_cust_flag": pa.array(np.where(rng.random(n) < 0.5, "Y", "N")),
        "c_birth_country": pa.array(np.where(rng.random(n) < 0.8,
                                             "UNITED STATES", "CANADA")),
        "c_birth_year": pa.array(rng.integers(1924, 1993, n).astype(np.int32)),
        "c_birth_month": pa.array(rng.integers(1, 13, n).astype(np.int32)),
        "c_birth_day": pa.array(rng.integers(1, 29, n).astype(np.int32)),
    })


def gen_customer_address(scale: float, seed: int) -> pa.Table:
    n = n_address(scale)
    rng = np.random.default_rng(seed + 13)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "ca_address_sk": pa.array(sk),
        "ca_city": pa.array(np.array(CITIES)[rng.integers(0, len(CITIES), n)]),
        "ca_county": pa.array(np.array(COUNTIES)[rng.integers(0, len(COUNTIES), n)]),
        "ca_state": pa.array(np.array(STATES)[rng.integers(0, len(STATES), n)]),
        "ca_zip": pa.array(np.char.zfill(
            rng.integers(10000, 99999, n).astype(str), 5)),
        "ca_country": pa.array(np.full(n, "United States")),
        "ca_gmt_offset": pa.array(rng.integers(-8, -4, n).astype(np.float64)),
    })


def gen_customer_demographics() -> pa.Table:
    rows = [(g, m, e, c)
            for g in ("M", "F") for m in MARITAL for e in EDUCATION
            for c in CREDIT]
    n = len(rows)
    return pa.table({
        "cd_demo_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "cd_gender": pa.array([r[0] for r in rows]),
        "cd_marital_status": pa.array([r[1] for r in rows]),
        "cd_education_status": pa.array([r[2] for r in rows]),
        "cd_credit_rating": pa.array([r[3] for r in rows]),
        "cd_purchase_estimate": pa.array(
            np.array([500 + (i % 10) * 500 for i in range(n)], np.int32)),
        "cd_dep_count": pa.array(np.array([i % 7 for i in range(n)], np.int32)),
    })


def gen_household_demographics() -> pa.Table:
    rows = [(b, d, v) for b in BUY_POTENTIAL for d in range(10)
            for v in range(5)]
    n = len(rows)
    return pa.table({
        "hd_demo_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "hd_buy_potential": pa.array([r[0] for r in rows]),
        "hd_dep_count": pa.array(np.array([r[1] for r in rows], np.int32)),
        "hd_vehicle_count": pa.array(np.array([r[2] for r in rows], np.int32)),
    })


#: dsdgen's syllable name pool — shared with the TPCx-BB review generator so
#: store mentions in review content stay joinable against s_store_name
STORE_NAMES = ("ought", "able", "pri", "ese", "anti", "cally", "ation",
               "eing")


def gen_store(scale: float, seed: int) -> pa.Table:
    n = n_store(scale)
    rng = np.random.default_rng(seed + 14)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "s_store_sk": pa.array(sk),
        "s_store_id": pa.array(np.char.add("AAAAAAAA",
                                           np.char.zfill(sk.astype(str), 8))),
        "s_store_name": pa.array(np.array(STORE_NAMES)[(sk - 1) % 8]),
        "s_number_employees": pa.array(rng.integers(200, 301, n).astype(np.int32)),
        # cycle the value pools so every city/county/offset the queries filter
        # on exists even with a handful of stores
        "s_city": pa.array(np.array(CITIES)[(sk - 1) % len(CITIES)]),
        "s_county": pa.array(np.array(COUNTIES)[(sk - 1) % len(COUNTIES)]),
        "s_state": pa.array(np.array(STATES)[(sk - 1) % len(STATES)]),
        "s_company_name": pa.array(np.full(n, "Unknown")),
        "s_company_id": pa.array(((sk - 1) % 6 + 1).astype(np.int32)),
        "s_street_number": pa.array(rng.integers(1, 1000, n).astype(str)),
        "s_street_name": pa.array(np.array(
            ["Main", "Oak", "Park", "First", "Elm"])[(sk - 1) % 5]),
        "s_street_type": pa.array(np.array(
            ["St", "Ave", "Blvd", "Ln"])[(sk - 1) % 4]),
        "s_suite_number": pa.array(np.char.add(
            "Suite ", ((sk - 1) % 20 * 10).astype(str))),
        "s_zip": pa.array(np.char.zfill(
            rng.integers(10000, 99999, n).astype(str), 5)),
        "s_gmt_offset": pa.array((-5.0 - ((sk - 1) % 4)).astype(np.float64)),
    })


def gen_promotion(scale: float, seed: int) -> pa.Table:
    n = n_promo(scale)
    rng = np.random.default_rng(seed + 15)
    yn = lambda p: np.where(rng.random(n) < p, "Y", "N")  # noqa: E731
    return pa.table({
        "p_promo_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "p_channel_dmail": pa.array(yn(0.5)),
        "p_channel_email": pa.array(yn(0.5)),
        "p_channel_tv": pa.array(yn(0.5)),
        "p_channel_event": pa.array(yn(0.5)),
    })


def _null_some(rng, arr: np.ndarray, frac: float) -> pa.Array:
    mask = rng.random(arr.shape[0]) < frac
    return pa.array(arr, mask=mask)


def _price_lines(rng, n: int):
    """Per-line pricing derivation shared by the sales fact generators:
    quantity, wholesale/list/sales prices and the ext_* amounts."""
    qty = rng.integers(1, 101, n).astype(np.int32)
    wholesale = np.round(rng.uniform(1.0, 100.0, n), 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, n), 2)
    disc = np.round(rng.uniform(0.0, 1.0, n), 2)
    sales_price = np.round(list_price * (1 - disc), 2)
    return {
        "qty": qty, "wholesale": wholesale, "list_price": list_price,
        "sales_price": sales_price,
        "ext_sales": np.round(qty * sales_price, 2),
        "ext_wholesale": np.round(qty * wholesale, 2),
        "ext_list": np.round(qty * list_price, 2),
        "ext_discount": np.round(qty * (list_price - sales_price), 2),
    }


def gen_store_sales(scale: float, seed: int) -> pa.Table:
    tickets = n_tickets(scale)
    rng = np.random.default_rng(seed + 16)
    # dsdgen tickets run long; counts up to ~24 items keep the
    # count-between-15-and-20 queries (q34) satisfiable
    lines_per = rng.integers(1, 25, tickets)
    n = int(lines_per.sum())
    tick = np.repeat(np.arange(1, tickets + 1, dtype=np.int64), lines_per)
    # ticket-level attributes (shared by every line of the ticket)
    t_cust = rng.integers(1, n_customer(scale) + 1, tickets).astype(np.int64)
    cd_n = 2 * len(MARITAL) * len(EDUCATION) * len(CREDIT)
    hd_n = len(BUY_POTENTIAL) * 10 * 5
    t_cdemo = rng.integers(1, cd_n + 1, tickets).astype(np.int64)
    t_hdemo = rng.integers(1, hd_n + 1, tickets).astype(np.int64)
    t_addr = rng.integers(1, n_address(scale) + 1, tickets).astype(np.int64)
    t_store = rng.integers(1, n_store(scale) + 1, tickets).astype(np.int64)
    t_date = (rng.integers(0, _DAYS, tickets) + _SK0).astype(np.int64)
    t_time = rng.integers(0, 1440, tickets).astype(np.int64)
    rep = lambda a: a[tick - 1]  # noqa: E731

    p = _price_lines(rng, n)
    qty, wholesale, list_price, sales_price = (
        p["qty"], p["wholesale"], p["list_price"], p["sales_price"])
    ext_sales, ext_wholesale, ext_list, ext_discount = (
        p["ext_sales"], p["ext_wholesale"], p["ext_list"], p["ext_discount"])
    coupon = np.where(rng.random(n) < 0.1,
                      np.round(ext_sales * rng.uniform(0, 0.5, n), 2), 0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    tax = np.round(net_paid * 0.08, 2)
    return pa.table({
        "ss_sold_date_sk": _null_some(rng, rep(t_date), 0.04),
        "ss_sold_time_sk": _null_some(rng, rep(t_time), 0.04),
        "ss_item_sk": pa.array(rng.integers(1, n_item(scale) + 1, n).astype(np.int64)),
        "ss_customer_sk": _null_some(rng, rep(t_cust), 0.04),
        "ss_cdemo_sk": _null_some(rng, rep(t_cdemo), 0.04),
        "ss_hdemo_sk": _null_some(rng, rep(t_hdemo), 0.04),
        "ss_addr_sk": _null_some(rng, rep(t_addr), 0.04),
        "ss_store_sk": _null_some(rng, rep(t_store), 0.04),
        "ss_promo_sk": _null_some(rng,
                                  rng.integers(1, n_promo(scale) + 1,
                                               n).astype(np.int64), 0.04),
        "ss_ticket_number": pa.array(tick),
        "ss_quantity": pa.array(qty),
        "ss_wholesale_cost": pa.array(wholesale),
        "ss_list_price": pa.array(list_price),
        "ss_sales_price": pa.array(sales_price),
        "ss_ext_discount_amt": pa.array(ext_discount),
        "ss_ext_sales_price": pa.array(ext_sales),
        "ss_ext_wholesale_cost": pa.array(ext_wholesale),
        "ss_ext_list_price": pa.array(ext_list),
        "ss_ext_tax": pa.array(tax),
        "ss_coupon_amt": pa.array(coupon),
        "ss_net_paid": pa.array(net_paid),
        "ss_net_paid_inc_tax": pa.array(np.round(net_paid + tax, 2)),
        "ss_net_profit": pa.array(np.round(net_paid - ext_wholesale, 2)),
    })


def n_warehouse(scale): return max(int(10 * scale), 5)
def n_web_site(scale): return max(int(8 * scale), 4)
def n_web_page(scale): return max(int(120 * scale), 30)
def n_call_center(scale): return max(int(8 * scale), 4)
def n_catalog_page(scale): return max(int(200 * scale), 40)
def n_orders(scale): return max(int(100_000 * scale), 500)

SHIP_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"]
SHIP_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
                 "LATVIAN", "ALLIANCE", "GREAT EASTERN", "DIAMOND", "RUPEKSA"]


def gen_warehouse(scale: float, seed: int) -> pa.Table:
    n = n_warehouse(scale)
    rng = np.random.default_rng(seed + 21)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "w_warehouse_sk": pa.array(sk),
        "w_warehouse_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "w_warehouse_name": pa.array(np.char.add("Warehouse no ",
                                                 sk.astype(str))),
        "w_warehouse_sq_ft": pa.array(
            rng.integers(50_000, 1_000_000, n).astype(np.int32)),
        "w_city": pa.array(np.array(CITIES)[(sk - 1) % len(CITIES)]),
        "w_county": pa.array(np.array(COUNTIES)[(sk - 1) % len(COUNTIES)]),
        "w_state": pa.array(np.array(STATES)[(sk - 1) % len(STATES)]),
        "w_country": pa.array(np.full(n, "United States")),
        "w_gmt_offset": pa.array((-5.0 - ((sk - 1) % 4)).astype(np.float64)),
    })


def gen_web_site(scale: float, seed: int) -> pa.Table:
    n = n_web_site(scale)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "web_site_sk": pa.array(sk),
        "web_site_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "web_name": pa.array(np.char.add("site_", ((sk - 1) % 4).astype(str))),
        "web_company_name": pa.array(np.array(
            ["pri", "able", "ought", "ese", "anti", "cally"])[(sk - 1) % 6]),
        "web_state": pa.array(np.array(STATES)[(sk - 1) % len(STATES)]),
        "web_gmt_offset": pa.array((-5.0 - ((sk - 1) % 4)).astype(np.float64)),
    })


def gen_web_page(scale: float, seed: int) -> pa.Table:
    n = n_web_page(scale)
    rng = np.random.default_rng(seed + 22)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "wp_web_page_sk": pa.array(sk),
        "wp_web_page_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "wp_char_count": pa.array(rng.integers(3000, 9000, n).astype(np.int32)),
        "wp_link_count": pa.array(rng.integers(2, 25, n).astype(np.int32)),
    })


def gen_call_center(scale: float, seed: int) -> pa.Table:
    n = n_call_center(scale)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "cc_call_center_sk": pa.array(sk),
        "cc_call_center_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "cc_name": pa.array(np.array(["NY Metro", "Mid Atlantic",
                                      "North Midwest", "Pacific NW"])[
            (sk - 1) % 4]),
        "cc_manager": pa.array(np.array(FIRST_NAMES)[(sk - 1)
                                                     % len(FIRST_NAMES)]),
        "cc_county": pa.array(np.array(COUNTIES)[(sk - 1) % len(COUNTIES)]),
    })


def gen_catalog_page(scale: float, seed: int) -> pa.Table:
    n = n_catalog_page(scale)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "cp_catalog_page_sk": pa.array(sk),
        "cp_catalog_page_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "cp_catalog_number": pa.array(((sk - 1) // 100 + 1).astype(np.int32)),
        "cp_catalog_page_number": pa.array(((sk - 1) % 100 + 1)
                                           .astype(np.int32)),
    })


def gen_ship_mode() -> pa.Table:
    rows = [(t, c) for t in SHIP_TYPES for c in SHIP_CARRIERS[:4]]
    n = len(rows)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "sm_ship_mode_sk": pa.array(sk),
        "sm_ship_mode_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "sm_type": pa.array([r[0] for r in rows]),
        "sm_code": pa.array(np.array(["AIR", "SURFACE", "SEA"])[(sk - 1) % 3]),
        "sm_carrier": pa.array([r[1] for r in rows]),
    })


def gen_reason() -> pa.Table:
    reasons = ["Package was damaged", "Stopped working", "Did not get it on time",
               "Not the product that was ordred", "Parts missing",
               "Does not work with a product that I have",
               "Gift exchange", "Did not like the color",
               "Did not like the model", "Did not fit"]
    n = len(reasons)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "r_reason_sk": pa.array(sk),
        "r_reason_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "r_reason_desc": pa.array(reasons),
    })


def gen_income_band() -> pa.Table:
    n = 20
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "ib_income_band_sk": pa.array(sk),
        "ib_lower_bound": pa.array(((sk - 1) * 10000).astype(np.int32)),
        "ib_upper_bound": pa.array((sk * 10000 - 1).astype(np.int32)),
    })


def _gen_channel_sales(scale: float, seed: int, prefix: str,
                       extra: Dict[str, int],
                       replay=None) -> pa.Table:
    """Order-structured sales fact for the catalog/web channels: all lines of
    an order share customer/date/addr/etc (dsdgen's order consistency, which
    the order-level count-distinct queries group on); the warehouse varies
    per line (q16/q94 probe orders shipping from multiple warehouses).
    ``extra`` maps extra per-order dim columns to their key-space size.
    ``replay`` is an optional (customer_sk, item_sk, date_sk) triple of
    equal-length arrays appended as single-line orders — the
    bought/returned/bought-again chains q25/q29-style queries join on."""
    rng = np.random.default_rng(seed + sum(prefix.encode()))
    orders = n_orders(scale)
    lines_per = rng.integers(1, 9, orders)
    n = int(lines_per.sum())
    order_no = np.repeat(np.arange(1, orders + 1, dtype=np.int64), lines_per)

    cd_n = 2 * len(MARITAL) * len(EDUCATION) * len(CREDIT)
    hd_n = len(BUY_POTENTIAL) * 10 * 5
    per_order = {
        "sold_date_sk": (rng.integers(0, _DAYS, orders) + _SK0),
        "sold_time_sk": rng.integers(0, 1440, orders),
        "bill_customer_sk": rng.integers(1, n_customer(scale) + 1, orders),
        "bill_cdemo_sk": rng.integers(1, cd_n + 1, orders),
        "bill_hdemo_sk": rng.integers(1, hd_n + 1, orders),
        "bill_addr_sk": rng.integers(1, n_address(scale) + 1, orders),
        "ship_customer_sk": rng.integers(1, n_customer(scale) + 1, orders),
        "ship_cdemo_sk": rng.integers(1, cd_n + 1, orders),
        "ship_hdemo_sk": rng.integers(1, hd_n + 1, orders),
        "ship_addr_sk": rng.integers(1, n_address(scale) + 1, orders),
        "ship_mode_sk": rng.integers(1, len(SHIP_TYPES) * 4 + 1, orders),
    }
    for name, size in extra.items():
        per_order[name] = rng.integers(1, size + 1, orders)

    replay_items = None
    if replay is not None:
        r_cust, r_item, r_date = (np.asarray(a, dtype=np.int64)
                                  for a in replay)
        m = r_cust.shape[0]
        per_order = {k: np.concatenate([v, rng.integers(1, int(v.max()) + 1, m)])
                     for k, v in per_order.items()}
        per_order["sold_date_sk"][-m:] = np.minimum(
            r_date + rng.integers(1, 90, m), _SK0 + _DAYS - 1)
        per_order["bill_customer_sk"][-m:] = r_cust
        order_no = np.concatenate(
            [order_no, np.arange(orders + 1, orders + m + 1, dtype=np.int64)])
        replay_items = r_item
        orders += m
        n += m
    per_order["ship_date_sk"] = np.minimum(
        per_order["sold_date_sk"] + rng.integers(1, 121, orders),
        _SK0 + _DAYS - 1)
    rep = lambda a: a[order_no - 1]  # noqa: E731

    p = _price_lines(rng, n)
    ship_cost = np.round(p["qty"] * rng.uniform(0.5, 10.0, n), 2)
    coupon = np.where(rng.random(n) < 0.1,
                      np.round(p["ext_sales"] * rng.uniform(0, 0.5, n), 2),
                      0.0)
    net_paid = np.round(p["ext_sales"] - coupon, 2)
    tax = np.round(net_paid * 0.08, 2)
    cols = {}
    for name, arr in per_order.items():
        cols[f"{prefix}_{name}"] = _null_some(
            rng, rep(arr.astype(np.int64)), 0.04)
    item_sk = rng.integers(1, n_item(scale) + 1, n).astype(np.int64)
    if replay_items is not None:
        item_sk[-replay_items.shape[0]:] = replay_items
    cols[f"{prefix}_item_sk"] = pa.array(item_sk)
    cols[f"{prefix}_warehouse_sk"] = _null_some(
        rng, rng.integers(1, n_warehouse(scale) + 1, n).astype(np.int64),
        0.04)
    cols[f"{prefix}_promo_sk"] = _null_some(
        rng, rng.integers(1, n_promo(scale) + 1, n).astype(np.int64), 0.04)
    cols[f"{prefix}_order_number"] = pa.array(order_no)
    cols[f"{prefix}_quantity"] = pa.array(p["qty"])
    cols[f"{prefix}_wholesale_cost"] = pa.array(p["wholesale"])
    cols[f"{prefix}_list_price"] = pa.array(p["list_price"])
    cols[f"{prefix}_sales_price"] = pa.array(p["sales_price"])
    cols[f"{prefix}_ext_discount_amt"] = pa.array(p["ext_discount"])
    cols[f"{prefix}_ext_sales_price"] = pa.array(p["ext_sales"])
    cols[f"{prefix}_ext_wholesale_cost"] = pa.array(p["ext_wholesale"])
    cols[f"{prefix}_ext_list_price"] = pa.array(p["ext_list"])
    cols[f"{prefix}_ext_tax"] = pa.array(tax)
    cols[f"{prefix}_coupon_amt"] = pa.array(coupon)
    cols[f"{prefix}_ext_ship_cost"] = pa.array(ship_cost)
    cols[f"{prefix}_net_paid"] = pa.array(net_paid)
    cols[f"{prefix}_net_paid_inc_tax"] = pa.array(np.round(net_paid + tax, 2))
    cols[f"{prefix}_net_paid_inc_ship"] = pa.array(
        np.round(net_paid + ship_cost, 2))
    cols[f"{prefix}_net_profit"] = pa.array(
        np.round(net_paid - p["ext_wholesale"], 2))
    return pa.table(cols)


def _gen_channel_returns(scale: float, seed: int, sales: pa.Table,
                         sp: str, rp: str, carry: Dict[str, str],
                         frac: float = 0.08) -> pa.Table:
    """Returns fact sampled from sales lines (same order/item link dsdgen
    uses), returned 1-60 days after the sale."""
    rng = np.random.default_rng(seed + sum(rp.encode()))
    n_s = sales.num_rows
    take = np.flatnonzero(rng.random(n_s) < frac)
    k = take.shape[0]
    get = lambda c: sales.column(c).to_numpy(zero_copy_only=False)[take]  # noqa: E731

    sold = get(f"{sp}_sold_date_sk")
    ret_date = np.minimum(np.nan_to_num(sold, nan=_SK0) + rng.integers(1, 61, k),
                          _SK0 + _DAYS - 1)
    qty = get(f"{sp}_quantity")
    net = np.nan_to_num(get(f"{sp}_net_paid"))
    ret_qty = np.minimum(rng.integers(1, 101, k), qty).astype(np.int32)
    frac_q = ret_qty / np.maximum(qty, 1)
    amt = np.round(net * frac_q, 2)
    fee = np.round(rng.uniform(0.5, 100.0, k), 2)
    cols = {
        f"{rp}_returned_date_sk": pa.array(
            np.where(np.isnan(sold), 0, ret_date).astype(np.int64),
            mask=np.isnan(sold)),
        f"{rp}_returned_time_sk": pa.array(
            rng.integers(0, 1440, k).astype(np.int64)),
    }
    for src, dst in carry.items():
        v = sales.column(src).to_numpy(zero_copy_only=False)[take]
        if v.dtype.kind == "f":
            cols[dst] = pa.array(np.where(np.isnan(v), 0, v).astype(np.int64),
                                 mask=np.isnan(v))
        else:
            cols[dst] = pa.array(v.astype(np.int64))
    cols[f"{rp}_reason_sk"] = _null_some(
        rng, rng.integers(1, 11, k).astype(np.int64), 0.04)
    cols[f"{rp}_return_quantity"] = pa.array(ret_qty)
    amt_name = "return_amount" if rp == "cr" else "return_amt"
    cols[f"{rp}_{amt_name}"] = pa.array(amt)
    cols[f"{rp}_return_tax"] = pa.array(np.round(amt * 0.08, 2))
    cols[f"{rp}_return_amt_inc_tax"] = pa.array(np.round(amt * 1.08, 2))
    cols[f"{rp}_fee"] = pa.array(fee)
    cols[f"{rp}_return_ship_cost"] = pa.array(
        np.round(rng.uniform(0.5, 50.0, k) * ret_qty, 2))
    cols[f"{rp}_refunded_cash"] = pa.array(
        np.round(amt * rng.uniform(0.3, 1.0, k), 2))
    cols[f"{rp}_net_loss"] = pa.array(np.round(fee + amt * 0.1, 2))
    return pa.table(cols)


def gen_catalog_sales(scale: float, seed: int, replay=None) -> pa.Table:
    return _gen_channel_sales(scale, seed, "cs", {
        "call_center_sk": n_call_center(scale),
        "catalog_page_sk": n_catalog_page(scale)},
        replay=replay)


def gen_web_sales_ds(scale: float, seed: int) -> pa.Table:
    return _gen_channel_sales(scale, seed, "ws", {
        "web_page_sk": n_web_page(scale), "web_site_sk": n_web_site(scale)})


def gen_catalog_returns(scale: float, seed: int, cs: pa.Table) -> pa.Table:
    return _gen_channel_returns(scale, seed, cs, "cs", "cr", {
        "cs_item_sk": "cr_item_sk",
        "cs_order_number": "cr_order_number",
        "cs_bill_customer_sk": "cr_refunded_customer_sk",
        "cs_ship_customer_sk": "cr_returning_customer_sk",
        "cs_bill_cdemo_sk": "cr_refunded_cdemo_sk",
        "cs_bill_addr_sk": "cr_returning_addr_sk",
        "cs_call_center_sk": "cr_call_center_sk",
        "cs_catalog_page_sk": "cr_catalog_page_sk",
        "cs_warehouse_sk": "cr_warehouse_sk",
    })


def gen_web_returns_ds(scale: float, seed: int, ws: pa.Table) -> pa.Table:
    return _gen_channel_returns(scale, seed, ws, "ws", "wr", {
        "ws_item_sk": "wr_item_sk",
        "ws_order_number": "wr_order_number",
        "ws_bill_customer_sk": "wr_refunded_customer_sk",
        "ws_bill_cdemo_sk": "wr_refunded_cdemo_sk",
        "ws_bill_addr_sk": "wr_refunded_addr_sk",
        "ws_ship_customer_sk": "wr_returning_customer_sk",
        "ws_web_page_sk": "wr_web_page_sk",
    })


def gen_store_returns(scale: float, seed: int, ss: pa.Table) -> pa.Table:
    rng = np.random.default_rng(seed + 23)
    n_s = ss.num_rows
    take = np.flatnonzero(rng.random(n_s) < 0.08)
    k = take.shape[0]
    get = lambda c: ss.column(c).to_numpy(zero_copy_only=False)[take]  # noqa: E731
    sold = get("ss_sold_date_sk")
    ret_date = np.minimum(np.nan_to_num(sold, nan=_SK0) + rng.integers(1, 61, k),
                          _SK0 + _DAYS - 1)
    qty = get("ss_quantity")
    net = np.nan_to_num(get("ss_net_paid"))
    ret_qty = np.minimum(rng.integers(1, 101, k), qty).astype(np.int32)
    amt = np.round(net * (ret_qty / np.maximum(qty, 1)), 2)
    fee = np.round(rng.uniform(0.5, 100.0, k), 2)

    def carry(c):
        v = get(c)
        if v.dtype.kind == "f":
            return pa.array(np.where(np.isnan(v), 0, v).astype(np.int64),
                            mask=np.isnan(v))
        return pa.array(v.astype(np.int64))

    return pa.table({
        "sr_returned_date_sk": pa.array(
            np.where(np.isnan(sold), 0, ret_date).astype(np.int64),
            mask=np.isnan(sold)),
        "sr_return_time_sk": pa.array(
            rng.integers(0, 1440, k).astype(np.int64)),
        "sr_item_sk": carry("ss_item_sk"),
        "sr_customer_sk": carry("ss_customer_sk"),
        "sr_cdemo_sk": carry("ss_cdemo_sk"),
        "sr_hdemo_sk": carry("ss_hdemo_sk"),
        "sr_addr_sk": carry("ss_addr_sk"),
        "sr_store_sk": carry("ss_store_sk"),
        "sr_reason_sk": _null_some(
            rng, rng.integers(1, 11, k).astype(np.int64), 0.04),
        "sr_ticket_number": carry("ss_ticket_number"),
        "sr_return_quantity": pa.array(ret_qty),
        "sr_return_amt": pa.array(amt),
        "sr_return_tax": pa.array(np.round(amt * 0.08, 2)),
        "sr_return_amt_inc_tax": pa.array(np.round(amt * 1.08, 2)),
        "sr_fee": pa.array(fee),
        "sr_refunded_cash": pa.array(
            np.round(amt * rng.uniform(0.3, 1.0, k), 2)),
        "sr_net_loss": pa.array(np.round(fee + amt * 0.1, 2)),
    })


def gen_inventory(scale: float, seed: int) -> pa.Table:
    """Weekly per-item/warehouse snapshots over the whole calendar,
    zero-inflated Poisson per-item rates (high-variance items matter for the
    coefficient-of-variation and stock-window queries)."""
    rng = np.random.default_rng(seed + 24)
    items = min(n_item(scale), 300)
    warehouses = n_warehouse(scale)
    week_starts = np.arange(_SK0, _SK0 + _DAYS, 7, dtype=np.int64)
    ii, ww, dd = np.meshgrid(np.arange(1, items + 1, dtype=np.int64),
                             np.arange(1, warehouses + 1, dtype=np.int64),
                             week_starts, indexing="ij")
    lam = np.exp(rng.uniform(np.log(0.3), np.log(300.0), items))
    # the mid-price plant (gen_item's %25==7 band) keeps steady three-digit
    # stock so q37/q82's 100-500 on-hand window is populated
    lam[np.arange(items) % 25 == 7] = 150.0
    qty = rng.poisson(lam[ii.ravel() - 1]).astype(np.int32)
    return pa.table({
        "inv_date_sk": pa.array(dd.ravel()),
        "inv_item_sk": pa.array(ii.ravel()),
        "inv_warehouse_sk": pa.array(ww.ravel()),
        "inv_quantity_on_hand": _null_some(rng, qty, 0.02),
    })


def gen_all(scale: float = 0.002, seed: int = 0) -> Dict[str, pa.Table]:
    store_sales = gen_store_sales(scale, seed)
    store_returns = gen_store_returns(scale, seed, store_sales)
    # every 3rd store return re-buys the item from the catalog afterwards
    # (the bought/returned/bought-again chains q25/q29 join on)
    cust = store_returns.column("sr_customer_sk").to_numpy(
        zero_copy_only=False)
    rdate = store_returns.column("sr_returned_date_sk").to_numpy(
        zero_copy_only=False)
    item = store_returns.column("sr_item_sk").to_numpy(zero_copy_only=False)
    ok = np.flatnonzero(~np.isnan(cust) & ~np.isnan(rdate))[::3]
    catalog_sales = gen_catalog_sales(
        scale, seed,
        replay=(cust[ok], item[ok], rdate[ok]))
    web_sales = gen_web_sales_ds(scale, seed)
    return {
        "date_dim": gen_date_dim(),
        "time_dim": gen_time_dim(),
        "item": gen_item(scale, seed),
        "customer": gen_customer(scale, seed),
        "customer_address": gen_customer_address(scale, seed),
        "customer_demographics": gen_customer_demographics(),
        "household_demographics": gen_household_demographics(),
        "store": gen_store(scale, seed),
        "promotion": gen_promotion(scale, seed),
        "warehouse": gen_warehouse(scale, seed),
        "web_site": gen_web_site(scale, seed),
        "web_page": gen_web_page(scale, seed),
        "call_center": gen_call_center(scale, seed),
        "catalog_page": gen_catalog_page(scale, seed),
        "ship_mode": gen_ship_mode(),
        "reason": gen_reason(),
        "income_band": gen_income_band(),
        "store_sales": store_sales,
        "store_returns": store_returns,
        "catalog_sales": catalog_sales,
        "catalog_returns": gen_catalog_returns(scale, seed, catalog_sales),
        "web_sales": web_sales,
        "web_returns": gen_web_returns_ds(scale, seed, web_sales),
        "inventory": gen_inventory(scale, seed),
    }
