"""Deterministic dsdgen-alike for the TPC-DS store channel.

Reference analog: TpcdsLikeSpark.scala's table setup (the reference converts
real dsdgen output; this generator synthesizes the same shapes). Covers
store_sales plus every dimension the store-channel query subset touches, with
the structural properties those queries depend on: ticket-level consistency
(all lines of one ss_ticket_number share customer/store/date/hdemo — the
count-items-per-ticket queries group on that), ~4% null foreign keys like
dsdgen emits, a real calendar for date_dim, and cross-product demographics
dimensions. Doubles stand in for decimals (v0 has no decimal support).
"""
from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

_EPOCH = datetime.date(1970, 1, 1)
_D0 = datetime.date(1998, 1, 1)
_DAYS = (datetime.date(2003, 12, 31) - _D0).days + 1
#: dsdgen's julian-style first date key
_SK0 = 2450815

CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Men",
              "Music", "Shoes", "Sports", "Women", "Children"]
CLASSES = ["accent", "bedding", "classical", "dresses", "mens watch",
           "pants", "football", "romance", "fiction", "shirts", "athletic",
           "computers", "stereo", "portable", "reference"]
CITIES = ["Midway", "Fairview", "Oakland", "Riverside", "Five Points",
          "Centerville", "Oak Grove", "Pleasant Hill", "Bethel", "Clinton",
          "Antioch", "Marion", "Greenville", "Union", "Salem", "Spring Hill",
          "Shiloh", "Liberty", "Wilson", "Glendale"]
COUNTIES = ["Williamson County", "Walker County", "Ziebach County",
            "Daviess County", "Barrow County", "Franklin Parish",
            "Luce County", "Richland County"]
STATES = ["TN", "GA", "SD", "IN", "LA", "MI", "SC", "OH", "TX", "CA"]
FIRST_NAMES = ["James", "Mary", "John", "Linda", "Robert", "Susan", "Ana",
               "David", "Carlos", "Laura", "Kevin", "Grace", "Amy", "Paul"]
LAST_NAMES = ["Smith", "Jones", "Brown", "Davis", "Miller", "Moore",
              "Garcia", "Lopez", "Lee", "Walker", "Hall", "Young"]
SALUTATIONS = ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir", "Miss"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
MARITAL = ["M", "S", "D", "W", "U"]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday", "Sunday"]


def n_item(scale): return max(int(18_000 * scale), 100)
def n_customer(scale): return max(int(100_000 * scale), 300)
def n_address(scale): return max(int(50_000 * scale), 120)
def n_store(scale): return max(int(12 * scale), 6)
def n_promo(scale): return max(int(300 * scale), 12)
def n_tickets(scale): return max(int(240_000 * scale), 600)


def gen_date_dim() -> pa.Table:
    days = [_D0 + datetime.timedelta(days=i) for i in range(_DAYS)]
    week0 = _D0.isocalendar()[1]
    return pa.table({
        "d_date_sk": pa.array(np.arange(_SK0, _SK0 + _DAYS, dtype=np.int64)),
        "d_date": pa.array([(d - _EPOCH).days for d in days], type=pa.date32()),
        "d_year": pa.array(np.array([d.year for d in days], np.int32)),
        "d_moy": pa.array(np.array([d.month for d in days], np.int32)),
        "d_dom": pa.array(np.array([d.day for d in days], np.int32)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in days],
                                   np.int32)),
        "d_dow": pa.array(np.array([d.weekday() for d in days], np.int32)),
        "d_day_name": pa.array([DAY_NAMES[d.weekday()] for d in days]),
        # sequential week/month counters like dsdgen's *_seq surrogates
        "d_week_seq": pa.array(np.array(
            [(d - _D0).days // 7 + 1 for d in days], np.int32)),
        "d_month_seq": pa.array(np.array(
            [(d.year - _D0.year) * 12 + d.month - 1 + 1189 for d in days],
            np.int32)),
    })


def gen_time_dim() -> pa.Table:
    sk = np.arange(1440, dtype=np.int64)  # one row per minute of day
    return pa.table({
        "t_time_sk": pa.array(sk),
        "t_hour": pa.array((sk // 60).astype(np.int32)),
        "t_minute": pa.array((sk % 60).astype(np.int32)),
    })


def gen_item(scale: float, seed: int) -> pa.Table:
    n = n_item(scale)
    rng = np.random.default_rng(seed + 11)
    sk = np.arange(1, n + 1, dtype=np.int64)
    brand_id = (rng.integers(1, 11, n) * 1000000
                + rng.integers(1, 11, n) * 1000 + rng.integers(1, 11, n))
    cat_id = rng.integers(1, len(CATEGORIES) + 1, n).astype(np.int32)
    return pa.table({
        "i_item_sk": pa.array(sk),
        "i_item_id": pa.array(np.char.add("AAAAAAAA",
                                          np.char.zfill(sk.astype(str), 8))),
        "i_item_desc": pa.array(np.char.add("item desc ", sk.astype(str))),
        "i_brand_id": pa.array(brand_id.astype(np.int32)),
        "i_brand": pa.array(np.char.add("corpbrand #", brand_id.astype(str))),
        "i_class": pa.array(np.array(CLASSES)[rng.integers(0, len(CLASSES), n)]),
        "i_category_id": pa.array(cat_id),
        "i_category": pa.array(np.array(CATEGORIES)[cat_id - 1]),
        # cycle so the specific ids queries filter on (manufact 128, manager
        # 1/8/28) exist at any generated item count
        "i_manufact_id": pa.array(((sk - 1) % 1000 + 1).astype(np.int32)),
        "i_manufact": pa.array(np.char.add("manufact#",
                                           rng.integers(1, 1001, n).astype(str))),
        "i_wholesale_cost": pa.array(np.round(rng.uniform(0.05, 70.0, n), 2)),
        "i_manager_id": pa.array(((sk - 1) % 100 + 1).astype(np.int32)),
        "i_current_price": pa.array(np.round(rng.uniform(0.09, 99.99, n), 2)),
    })


def gen_customer(scale: float, seed: int) -> pa.Table:
    n = n_customer(scale)
    rng = np.random.default_rng(seed + 12)
    sk = np.arange(1, n + 1, dtype=np.int64)
    cd_n = 2 * len(MARITAL) * len(EDUCATION) * len(CREDIT)
    hd_n = len(BUY_POTENTIAL) * 10 * 5
    return pa.table({
        "c_customer_sk": pa.array(sk),
        "c_customer_id": pa.array(np.char.add("AAAAAAAA",
                                              np.char.zfill(sk.astype(str), 8))),
        "c_current_addr_sk": pa.array(
            rng.integers(1, n_address(scale) + 1, n).astype(np.int64)),
        "c_current_cdemo_sk": pa.array(rng.integers(1, cd_n + 1, n).astype(np.int64)),
        "c_current_hdemo_sk": pa.array(rng.integers(1, hd_n + 1, n).astype(np.int64)),
        "c_first_name": pa.array(np.array(FIRST_NAMES)[rng.integers(0, len(FIRST_NAMES), n)]),
        "c_last_name": pa.array(np.array(LAST_NAMES)[rng.integers(0, len(LAST_NAMES), n)]),
        "c_salutation": pa.array(np.array(SALUTATIONS)[rng.integers(0, len(SALUTATIONS), n)]),
        "c_preferred_cust_flag": pa.array(np.where(rng.random(n) < 0.5, "Y", "N")),
        "c_birth_country": pa.array(np.where(rng.random(n) < 0.8,
                                             "UNITED STATES", "CANADA")),
    })


def gen_customer_address(scale: float, seed: int) -> pa.Table:
    n = n_address(scale)
    rng = np.random.default_rng(seed + 13)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "ca_address_sk": pa.array(sk),
        "ca_city": pa.array(np.array(CITIES)[rng.integers(0, len(CITIES), n)]),
        "ca_county": pa.array(np.array(COUNTIES)[rng.integers(0, len(COUNTIES), n)]),
        "ca_state": pa.array(np.array(STATES)[rng.integers(0, len(STATES), n)]),
        "ca_zip": pa.array(np.char.zfill(
            rng.integers(10000, 99999, n).astype(str), 5)),
        "ca_country": pa.array(np.full(n, "United States")),
        "ca_gmt_offset": pa.array(rng.integers(-8, -4, n).astype(np.float64)),
    })


def gen_customer_demographics() -> pa.Table:
    rows = [(g, m, e, c)
            for g in ("M", "F") for m in MARITAL for e in EDUCATION
            for c in CREDIT]
    n = len(rows)
    return pa.table({
        "cd_demo_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "cd_gender": pa.array([r[0] for r in rows]),
        "cd_marital_status": pa.array([r[1] for r in rows]),
        "cd_education_status": pa.array([r[2] for r in rows]),
        "cd_credit_rating": pa.array([r[3] for r in rows]),
        "cd_purchase_estimate": pa.array(
            np.array([500 + (i % 10) * 500 for i in range(n)], np.int32)),
        "cd_dep_count": pa.array(np.array([i % 7 for i in range(n)], np.int32)),
    })


def gen_household_demographics() -> pa.Table:
    rows = [(b, d, v) for b in BUY_POTENTIAL for d in range(10)
            for v in range(5)]
    n = len(rows)
    return pa.table({
        "hd_demo_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "hd_buy_potential": pa.array([r[0] for r in rows]),
        "hd_dep_count": pa.array(np.array([r[1] for r in rows], np.int32)),
        "hd_vehicle_count": pa.array(np.array([r[2] for r in rows], np.int32)),
    })


def gen_store(scale: float, seed: int) -> pa.Table:
    n = n_store(scale)
    rng = np.random.default_rng(seed + 14)
    sk = np.arange(1, n + 1, dtype=np.int64)
    return pa.table({
        "s_store_sk": pa.array(sk),
        "s_store_id": pa.array(np.char.add("AAAAAAAA",
                                           np.char.zfill(sk.astype(str), 8))),
        "s_store_name": pa.array(np.array(
            ["ought", "able", "pri", "ese", "anti", "cally", "ation", "eing"]
        )[(sk - 1) % 8]),
        "s_number_employees": pa.array(rng.integers(200, 301, n).astype(np.int32)),
        # cycle the value pools so every city/county/offset the queries filter
        # on exists even with a handful of stores
        "s_city": pa.array(np.array(CITIES)[(sk - 1) % len(CITIES)]),
        "s_county": pa.array(np.array(COUNTIES)[(sk - 1) % len(COUNTIES)]),
        "s_state": pa.array(np.array(STATES)[(sk - 1) % len(STATES)]),
        "s_company_name": pa.array(np.full(n, "Unknown")),
        "s_zip": pa.array(np.char.zfill(
            rng.integers(10000, 99999, n).astype(str), 5)),
        "s_gmt_offset": pa.array((-5.0 - ((sk - 1) % 4)).astype(np.float64)),
    })


def gen_promotion(scale: float, seed: int) -> pa.Table:
    n = n_promo(scale)
    rng = np.random.default_rng(seed + 15)
    yn = lambda p: np.where(rng.random(n) < p, "Y", "N")  # noqa: E731
    return pa.table({
        "p_promo_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "p_channel_dmail": pa.array(yn(0.5)),
        "p_channel_email": pa.array(yn(0.5)),
        "p_channel_tv": pa.array(yn(0.5)),
        "p_channel_event": pa.array(yn(0.5)),
    })


def _null_some(rng, arr: np.ndarray, frac: float) -> pa.Array:
    mask = rng.random(arr.shape[0]) < frac
    return pa.array(arr, mask=mask)


def _price_lines(rng, n: int):
    """Per-line pricing derivation shared by the sales fact generators:
    quantity, wholesale/list/sales prices and the ext_* amounts."""
    qty = rng.integers(1, 101, n).astype(np.int32)
    wholesale = np.round(rng.uniform(1.0, 100.0, n), 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, n), 2)
    disc = np.round(rng.uniform(0.0, 1.0, n), 2)
    sales_price = np.round(list_price * (1 - disc), 2)
    return {
        "qty": qty, "wholesale": wholesale, "list_price": list_price,
        "sales_price": sales_price,
        "ext_sales": np.round(qty * sales_price, 2),
        "ext_wholesale": np.round(qty * wholesale, 2),
        "ext_list": np.round(qty * list_price, 2),
        "ext_discount": np.round(qty * (list_price - sales_price), 2),
    }


def gen_store_sales(scale: float, seed: int) -> pa.Table:
    tickets = n_tickets(scale)
    rng = np.random.default_rng(seed + 16)
    # dsdgen tickets run long; counts up to ~24 items keep the
    # count-between-15-and-20 queries (q34) satisfiable
    lines_per = rng.integers(1, 25, tickets)
    n = int(lines_per.sum())
    tick = np.repeat(np.arange(1, tickets + 1, dtype=np.int64), lines_per)
    # ticket-level attributes (shared by every line of the ticket)
    t_cust = rng.integers(1, n_customer(scale) + 1, tickets).astype(np.int64)
    cd_n = 2 * len(MARITAL) * len(EDUCATION) * len(CREDIT)
    hd_n = len(BUY_POTENTIAL) * 10 * 5
    t_cdemo = rng.integers(1, cd_n + 1, tickets).astype(np.int64)
    t_hdemo = rng.integers(1, hd_n + 1, tickets).astype(np.int64)
    t_addr = rng.integers(1, n_address(scale) + 1, tickets).astype(np.int64)
    t_store = rng.integers(1, n_store(scale) + 1, tickets).astype(np.int64)
    t_date = (rng.integers(0, _DAYS, tickets) + _SK0).astype(np.int64)
    t_time = rng.integers(0, 1440, tickets).astype(np.int64)
    rep = lambda a: a[tick - 1]  # noqa: E731

    p = _price_lines(rng, n)
    qty, wholesale, list_price, sales_price = (
        p["qty"], p["wholesale"], p["list_price"], p["sales_price"])
    ext_sales, ext_wholesale, ext_list, ext_discount = (
        p["ext_sales"], p["ext_wholesale"], p["ext_list"], p["ext_discount"])
    coupon = np.where(rng.random(n) < 0.1,
                      np.round(ext_sales * rng.uniform(0, 0.5, n), 2), 0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    tax = np.round(net_paid * 0.08, 2)
    return pa.table({
        "ss_sold_date_sk": _null_some(rng, rep(t_date), 0.04),
        "ss_sold_time_sk": _null_some(rng, rep(t_time), 0.04),
        "ss_item_sk": pa.array(rng.integers(1, n_item(scale) + 1, n).astype(np.int64)),
        "ss_customer_sk": _null_some(rng, rep(t_cust), 0.04),
        "ss_cdemo_sk": _null_some(rng, rep(t_cdemo), 0.04),
        "ss_hdemo_sk": _null_some(rng, rep(t_hdemo), 0.04),
        "ss_addr_sk": _null_some(rng, rep(t_addr), 0.04),
        "ss_store_sk": _null_some(rng, rep(t_store), 0.04),
        "ss_promo_sk": _null_some(rng,
                                  rng.integers(1, n_promo(scale) + 1,
                                               n).astype(np.int64), 0.04),
        "ss_ticket_number": pa.array(tick),
        "ss_quantity": pa.array(qty),
        "ss_wholesale_cost": pa.array(wholesale),
        "ss_list_price": pa.array(list_price),
        "ss_sales_price": pa.array(sales_price),
        "ss_ext_discount_amt": pa.array(ext_discount),
        "ss_ext_sales_price": pa.array(ext_sales),
        "ss_ext_wholesale_cost": pa.array(ext_wholesale),
        "ss_ext_list_price": pa.array(ext_list),
        "ss_ext_tax": pa.array(tax),
        "ss_coupon_amt": pa.array(coupon),
        "ss_net_paid": pa.array(net_paid),
        "ss_net_paid_inc_tax": pa.array(np.round(net_paid + tax, 2)),
        "ss_net_profit": pa.array(np.round(net_paid - ext_wholesale, 2)),
    })


def gen_all(scale: float = 0.002, seed: int = 0) -> Dict[str, pa.Table]:
    return {
        "date_dim": gen_date_dim(),
        "time_dim": gen_time_dim(),
        "item": gen_item(scale, seed),
        "customer": gen_customer(scale, seed),
        "customer_address": gen_customer_address(scale, seed),
        "customer_demographics": gen_customer_demographics(),
        "household_demographics": gen_household_demographics(),
        "store": gen_store(scale, seed),
        "promotion": gen_promotion(scale, seed),
        "store_sales": gen_store_sales(scale, seed),
    }
