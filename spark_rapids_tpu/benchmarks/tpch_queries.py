"""All 22 TPC-H queries over the DataFrame API (reference:
integration_tests/src/main/scala/.../tpch/TpchLikeSpark.scala Q1Like-Q22Like).

The reference runs the spec SQL through Spark's Catalyst; this engine has no
SQL frontend, so each query is the standard DataFrame translation of the same
spec text, with correlated/scalar subqueries rewritten the way Catalyst
decorrelates them: EXISTS -> left-semi join, NOT EXISTS -> left-anti join,
scalar subquery -> single-row aggregate cross-joined (or equi-joined on the
correlation key). Results are the spec's columns in the spec's order.
"""
from __future__ import annotations

import datetime
from typing import Dict

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.dataframe import DataFrame

col, lit, when = F.col, F.lit, F.when
_d = datetime.date


def _revenue():
    return col("l_extendedprice") * (1 - col("l_discount"))


def q1(t) -> DataFrame:
    charge = _revenue() * (1 + col("l_tax"))
    return (t["lineitem"]
            .filter(col("l_shipdate") <= lit(_d(1998, 9, 2)))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(_revenue()).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count().alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q2(t) -> DataFrame:
    eu_supp = (t["supplier"]
               .join(t["nation"], [("s_nationkey", "n_nationkey")])
               .join(t["region"].filter(col("r_name") == "EUROPE"),
                     [("n_regionkey", "r_regionkey")]))
    joined = (t["part"]
              .filter((col("p_size") == 15) & col("p_type").like("%BRASS"))
              .join(t["partsupp"], [("p_partkey", "ps_partkey")])
              .join(eu_supp, [("ps_suppkey", "s_suppkey")]))
    min_cost = (joined.groupBy("p_partkey")
                .agg(F.min("ps_supplycost").alias("min_cost"))
                .withColumnRenamed("p_partkey", "mc_partkey"))
    return (joined.join(min_cost, [("p_partkey", "mc_partkey")])
            .filter(col("ps_supplycost") == col("min_cost"))
            .select("s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
                    "s_address", "s_phone", "s_comment")
            .sort(col("s_acctbal").desc(), "n_name", "s_name", "p_partkey")
            .limit(100))


def q3(t) -> DataFrame:
    cutoff = lit(_d(1995, 3, 15))
    return (t["customer"].filter(col("c_mktsegment") == "BUILDING")
            .join(t["orders"].filter(col("o_orderdate") < cutoff),
                  [("c_custkey", "o_custkey")])
            .join(t["lineitem"].filter(col("l_shipdate") > cutoff),
                  [("o_orderkey", "l_orderkey")])
            .groupBy("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(_revenue()).alias("revenue"))
            .select("l_orderkey", "revenue", "o_orderdate", "o_shippriority")
            .sort(col("revenue").desc(), "o_orderdate")
            .limit(10))


def q4(t) -> DataFrame:
    late = t["lineitem"].filter(col("l_commitdate") < col("l_receiptdate"))
    return (t["orders"]
            .filter((col("o_orderdate") >= lit(_d(1993, 7, 1)))
                    & (col("o_orderdate") < lit(_d(1993, 10, 1))))
            .join(late, [("o_orderkey", "l_orderkey")], "left_semi")
            .groupBy("o_orderpriority")
            .agg(F.count().alias("order_count"))
            .sort("o_orderpriority"))


def q5(t) -> DataFrame:
    return (t["customer"]
            .join(t["orders"]
                  .filter((col("o_orderdate") >= lit(_d(1994, 1, 1)))
                          & (col("o_orderdate") < lit(_d(1995, 1, 1)))),
                  [("c_custkey", "o_custkey")])
            .join(t["lineitem"], [("o_orderkey", "l_orderkey")])
            .join(t["supplier"], [("l_suppkey", "s_suppkey"),
                                  ("c_nationkey", "s_nationkey")])
            .join(t["nation"], [("s_nationkey", "n_nationkey")])
            .join(t["region"].filter(col("r_name") == "ASIA"),
                  [("n_regionkey", "r_regionkey")])
            .groupBy("n_name")
            .agg(F.sum(_revenue()).alias("revenue"))
            .sort(col("revenue").desc()))


def q6(t) -> DataFrame:
    return (t["lineitem"]
            .filter((col("l_shipdate") >= lit(_d(1994, 1, 1)))
                    & (col("l_shipdate") < lit(_d(1995, 1, 1)))
                    & (col("l_discount") >= 0.05)
                    & (col("l_discount") <= 0.07)
                    & (col("l_quantity") < 24))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q7(t) -> DataFrame:
    n1 = t["nation"].select(col("n_nationkey").alias("sn_key"),
                            col("n_name").alias("supp_nation"))
    n2 = t["nation"].select(col("n_nationkey").alias("cn_key"),
                            col("n_name").alias("cust_nation"))
    pair = (((col("supp_nation") == "FRANCE") & (col("cust_nation") == "GERMANY"))
            | ((col("supp_nation") == "GERMANY") & (col("cust_nation") == "FRANCE")))
    return (t["lineitem"]
            .filter((col("l_shipdate") >= lit(_d(1995, 1, 1)))
                    & (col("l_shipdate") <= lit(_d(1996, 12, 31))))
            .join(t["supplier"], [("l_suppkey", "s_suppkey")])
            .join(t["orders"], [("l_orderkey", "o_orderkey")])
            .join(t["customer"], [("o_custkey", "c_custkey")])
            .join(n1, [("s_nationkey", "sn_key")])
            .join(n2, [("c_nationkey", "cn_key")])
            .filter(pair)
            .select("supp_nation", "cust_nation",
                    F.year("l_shipdate").alias("l_year"),
                    _revenue().alias("volume"))
            .groupBy("supp_nation", "cust_nation", "l_year")
            .agg(F.sum("volume").alias("revenue"))
            .sort("supp_nation", "cust_nation", "l_year"))


def q8(t) -> DataFrame:
    n2 = t["nation"].select(col("n_nationkey").alias("sn_key"),
                            col("n_name").alias("supp_nation"))
    base = (t["part"].filter(col("p_type") == "ECONOMY ANODIZED STEEL")
            .join(t["lineitem"], [("p_partkey", "l_partkey")])
            .join(t["supplier"], [("l_suppkey", "s_suppkey")])
            .join(t["orders"]
                  .filter((col("o_orderdate") >= lit(_d(1995, 1, 1)))
                          & (col("o_orderdate") <= lit(_d(1996, 12, 31)))),
                  [("l_orderkey", "o_orderkey")])
            .join(t["customer"], [("o_custkey", "c_custkey")])
            .join(t["nation"], [("c_nationkey", "n_nationkey")])
            .join(t["region"].filter(col("r_name") == "AMERICA"),
                  [("n_regionkey", "r_regionkey")])
            .join(n2, [("s_nationkey", "sn_key")])
            .select(F.year("o_orderdate").alias("o_year"),
                    _revenue().alias("volume"), "supp_nation"))
    return (base.groupBy("o_year")
            .agg(F.sum(when(col("supp_nation") == "BRAZIL", col("volume"))
                       .otherwise(0.0)).alias("brazil_volume"),
                 F.sum("volume").alias("total_volume"))
            .select("o_year", (col("brazil_volume")
                               / col("total_volume")).alias("mkt_share"))
            .sort("o_year"))


def q9(t) -> DataFrame:
    amount = (_revenue() - col("ps_supplycost") * col("l_quantity"))
    return (t["part"].filter(col("p_name").contains("green"))
            .join(t["lineitem"], [("p_partkey", "l_partkey")])
            .join(t["supplier"], [("l_suppkey", "s_suppkey")])
            .join(t["partsupp"], [("l_suppkey", "ps_suppkey"),
                                  ("l_partkey", "ps_partkey")])
            .join(t["orders"], [("l_orderkey", "o_orderkey")])
            .join(t["nation"], [("s_nationkey", "n_nationkey")])
            .select(col("n_name").alias("nation"),
                    F.year("o_orderdate").alias("o_year"),
                    amount.alias("amount"))
            .groupBy("nation", "o_year")
            .agg(F.sum("amount").alias("sum_profit"))
            .sort("nation", col("o_year").desc()))


def q10(t) -> DataFrame:
    return (t["customer"]
            .join(t["orders"]
                  .filter((col("o_orderdate") >= lit(_d(1993, 10, 1)))
                          & (col("o_orderdate") < lit(_d(1994, 1, 1)))),
                  [("c_custkey", "o_custkey")])
            .join(t["lineitem"].filter(col("l_returnflag") == "R"),
                  [("o_orderkey", "l_orderkey")])
            .join(t["nation"], [("c_nationkey", "n_nationkey")])
            .groupBy("c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                     "c_address", "c_comment")
            .agg(F.sum(_revenue()).alias("revenue"))
            .select("c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
                    "c_address", "c_phone", "c_comment")
            .sort(col("revenue").desc())
            .limit(20))


def q11(t) -> DataFrame:
    de = (t["partsupp"]
          .join(t["supplier"], [("ps_suppkey", "s_suppkey")])
          .join(t["nation"].filter(col("n_name") == "GERMANY"),
                [("s_nationkey", "n_nationkey")])
          .select("ps_partkey",
                  (col("ps_supplycost") * col("ps_availqty")).alias("v")))
    grouped = de.groupBy("ps_partkey").agg(F.sum("v").alias("value"))
    total = de.agg(F.sum("v").alias("total"))
    return (grouped.crossJoin(total)
            .filter(col("value") > col("total") * 0.0001)
            .select("ps_partkey", "value")
            .sort(col("value").desc()))


def q12(t) -> DataFrame:
    high = col("o_orderpriority").isin("1-URGENT", "2-HIGH")
    return (t["lineitem"]
            .filter(col("l_shipmode").isin("MAIL", "SHIP")
                    & (col("l_commitdate") < col("l_receiptdate"))
                    & (col("l_shipdate") < col("l_commitdate"))
                    & (col("l_receiptdate") >= lit(_d(1994, 1, 1)))
                    & (col("l_receiptdate") < lit(_d(1995, 1, 1))))
            .join(t["orders"], [("l_orderkey", "o_orderkey")])
            .groupBy("l_shipmode")
            .agg(F.sum(when(high, 1).otherwise(0)).alias("high_line_count"),
                 F.sum(when(high, 0).otherwise(1)).alias("low_line_count"))
            .sort("l_shipmode"))


def q13(t) -> DataFrame:
    ords = t["orders"].filter(~col("o_comment").like("%special%requests%"))
    return (t["customer"]
            .join(ords, [("c_custkey", "o_custkey")], "left")
            .groupBy("c_custkey")
            .agg(F.count("o_orderkey").alias("c_count"))
            .groupBy("c_count")
            .agg(F.count().alias("custdist"))
            .sort(col("custdist").desc(), col("c_count").desc()))


def q14(t) -> DataFrame:
    promo = when(col("p_type").like("PROMO%"), _revenue()).otherwise(0.0)
    return (t["lineitem"]
            .filter((col("l_shipdate") >= lit(_d(1995, 9, 1)))
                    & (col("l_shipdate") < lit(_d(1995, 10, 1))))
            .join(t["part"], [("l_partkey", "p_partkey")])
            .agg(F.sum(promo).alias("promo"), F.sum(_revenue()).alias("total"))
            .select((col("promo") * 100.0 / col("total"))
                    .alias("promo_revenue")))


def q15(t) -> DataFrame:
    revenue = (t["lineitem"]
               .filter((col("l_shipdate") >= lit(_d(1996, 1, 1)))
                       & (col("l_shipdate") < lit(_d(1996, 4, 1))))
               .groupBy(col("l_suppkey").alias("supplier_no"))
               .agg(F.sum(_revenue()).alias("total_revenue")))
    max_rev = revenue.agg(F.max("total_revenue").alias("max_revenue"))
    return (t["supplier"]
            .join(revenue, [("s_suppkey", "supplier_no")])
            .crossJoin(max_rev)
            .filter(col("total_revenue") == col("max_revenue"))
            .select("s_suppkey", "s_name", "s_address", "s_phone",
                    "total_revenue")
            .sort("s_suppkey"))


def q16(t) -> DataFrame:
    complaints = (t["supplier"]
                  .filter(col("s_comment").like("%Customer%Complaints%"))
                  .select("s_suppkey"))
    ps = t["partsupp"].join(complaints, [("ps_suppkey", "s_suppkey")],
                            "left_anti")
    return (t["part"]
            .filter((col("p_brand") != "Brand#45")
                    & ~col("p_type").like("MEDIUM POLISHED%")
                    & col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9))
            .join(ps, [("p_partkey", "ps_partkey")])
            .select("p_brand", "p_type", "p_size", "ps_suppkey")
            .distinct()
            .groupBy("p_brand", "p_type", "p_size")
            .agg(F.count().alias("supplier_cnt"))
            .sort(col("supplier_cnt").desc(), "p_brand", "p_type", "p_size"))


def q17(t) -> DataFrame:
    parts = t["part"].filter((col("p_brand") == "Brand#23")
                             & (col("p_container") == "MED BOX"))
    avg_qty = (t["lineitem"].groupBy(col("l_partkey").alias("aq_partkey"))
               .agg(F.avg("l_quantity").alias("aq")))
    return (t["lineitem"]
            .join(parts, [("l_partkey", "p_partkey")])
            .join(avg_qty, [("l_partkey", "aq_partkey")])
            .filter(col("l_quantity") < col("aq") * 0.2)
            .agg(F.sum("l_extendedprice").alias("s"))
            .select((col("s") / 7.0).alias("avg_yearly")))


def q18(t) -> DataFrame:
    big = (t["lineitem"].groupBy(col("l_orderkey").alias("big_orderkey"))
           .agg(F.sum("l_quantity").alias("big_qty"))
           .filter(col("big_qty") > 300))
    return (t["customer"]
            .join(t["orders"], [("c_custkey", "o_custkey")])
            .join(big, [("o_orderkey", "big_orderkey")], "left_semi")
            .join(t["lineitem"], [("o_orderkey", "l_orderkey")])
            .groupBy("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                     "o_totalprice")
            .agg(F.sum("l_quantity").alias("sum_qty"))
            .sort(col("o_totalprice").desc(), "o_orderdate")
            .limit(100))


def q19(t) -> DataFrame:
    qty, size = col("l_quantity"), col("p_size")
    c1 = ((col("p_brand") == "Brand#12")
          & col("p_container").isin("SM CASE", "SM BOX", "SM PACK", "SM PKG")
          & (qty >= 1) & (qty <= 11) & (size >= 1) & (size <= 5))
    c2 = ((col("p_brand") == "Brand#23")
          & col("p_container").isin("MED BAG", "MED BOX", "MED PKG", "MED PACK")
          & (qty >= 10) & (qty <= 20) & (size >= 1) & (size <= 10))
    c3 = ((col("p_brand") == "Brand#34")
          & col("p_container").isin("LG CASE", "LG BOX", "LG PACK", "LG PKG")
          & (qty >= 20) & (qty <= 30) & (size >= 1) & (size <= 15))
    return (t["lineitem"]
            .filter(col("l_shipmode").isin("AIR", "REG AIR")
                    & (col("l_shipinstruct") == "DELIVER IN PERSON"))
            .join(t["part"], [("l_partkey", "p_partkey")])
            .filter(c1 | c2 | c3)
            .agg(F.sum(_revenue()).alias("revenue")))


def q20(t) -> DataFrame:
    forest = t["part"].filter(col("p_name").like("forest%")).select("p_partkey")
    qty = (t["lineitem"]
           .filter((col("l_shipdate") >= lit(_d(1994, 1, 1)))
                   & (col("l_shipdate") < lit(_d(1995, 1, 1))))
           .groupBy(col("l_partkey").alias("q_partkey"),
                    col("l_suppkey").alias("q_suppkey"))
           .agg(F.sum("l_quantity").alias("qty_sum")))
    supps = (t["partsupp"]
             .join(forest, [("ps_partkey", "p_partkey")], "left_semi")
             .join(qty, [("ps_partkey", "q_partkey"),
                         ("ps_suppkey", "q_suppkey")])
             .filter(col("ps_availqty") > col("qty_sum") * 0.5)
             .select("ps_suppkey").distinct())
    return (t["supplier"]
            .join(supps, [("s_suppkey", "ps_suppkey")], "left_semi")
            .join(t["nation"].filter(col("n_name") == "CANADA"),
                  [("s_nationkey", "n_nationkey")])
            .select("s_name", "s_address")
            .sort("s_name"))


def q21(t) -> DataFrame:
    # EXISTS(other supplier on the order) / NOT EXISTS(other LATE supplier):
    # since the probe row is itself late, they reduce to per-order distinct
    # supplier counts — all_cnt > 1 and late_cnt == 1 (Catalyst decorrelates
    # to the same aggregate-join shape)
    late = t["lineitem"].filter(col("l_receiptdate") > col("l_commitdate"))
    late_cnt = (late.select("l_orderkey", "l_suppkey").distinct()
                .groupBy(col("l_orderkey").alias("lc_orderkey"))
                .agg(F.count().alias("late_cnt")))
    all_cnt = (t["lineitem"].select("l_orderkey", "l_suppkey").distinct()
               .groupBy(col("l_orderkey").alias("ac_orderkey"))
               .agg(F.count().alias("all_cnt")))
    return (late
            .join(t["orders"].filter(col("o_orderstatus") == "F"),
                  [("l_orderkey", "o_orderkey")])
            .join(t["supplier"], [("l_suppkey", "s_suppkey")])
            .join(t["nation"].filter(col("n_name") == "SAUDI ARABIA"),
                  [("s_nationkey", "n_nationkey")])
            .join(late_cnt, [("l_orderkey", "lc_orderkey")])
            .join(all_cnt, [("l_orderkey", "ac_orderkey")])
            .filter((col("late_cnt") == 1) & (col("all_cnt") > 1))
            .groupBy("s_name")
            .agg(F.count().alias("numwait"))
            .sort(col("numwait").desc(), "s_name")
            .limit(100))


def q22(t) -> DataFrame:
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cust = (t["customer"]
            .select(F.substring("c_phone", 1, 2).alias("cntrycode"),
                    "c_acctbal", "c_custkey")
            .filter(col("cntrycode").isin(*codes)))
    avg_bal = (cust.filter(col("c_acctbal") > 0.0)
               .agg(F.avg("c_acctbal").alias("avg_bal")))
    return (cust
            .join(t["orders"], [("c_custkey", "o_custkey")], "left_anti")
            .crossJoin(avg_bal)
            .filter(col("c_acctbal") > col("avg_bal"))
            .groupBy("cntrycode")
            .agg(F.count().alias("numcust"),
                 F.sum("c_acctbal").alias("totacctbal"))
            .sort("cntrycode"))


QUERIES: Dict[int, object] = {i: globals()[f"q{i}"] for i in range(1, 23)}


def run_query(n: int, dataframes) -> DataFrame:
    return QUERIES[n](dataframes)
