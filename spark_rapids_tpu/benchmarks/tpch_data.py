"""Deterministic vectorized dbgen-alike for all 8 TPC-H tables.

Reference analog: integration_tests' TPC-H setup (CSV/Parquet conversion of
dbgen output, TpchLikeSpark.scala setupAllCSV/Parquet). Doubles stand in for
decimals exactly like the reference's TpchLike schema (v0 has no decimal
support). Value domains follow the TPC-H spec closely enough that every query
qualifies rows: real region/nation names, brand/type/container vocabularies,
date ranges 1992-1998, comment streams salted with the phrases the queries
grep for ('special ... requests', 'Customer ... Complaints', green/forest part
names). scale=1.0 ~ the spec's SF1 row counts.
"""
from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

_EPOCH = datetime.date(1970, 1, 1)
_D = lambda y, m, d: (datetime.date(y, m, d) - _EPOCH).days  # noqa: E731

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [  # (name, regionkey) — the spec's 25 nations
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                "TAKE BACK RETURN"]
TYPE_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
          "cream", "cyan", "dark", "deep", "dim", "dodger", "drab",
          "firebrick", "floral", "forest", "frosted", "gainsboro", "ghost",
          "goldenrod", "green", "grey", "honeydew", "hot", "indian", "ivory",
          "khaki", "lace", "lavender", "lawn", "lemon", "light", "lime",
          "linen", "magenta", "maroon", "medium", "metallic", "midnight"]
_WORDS = ["carefully", "furiously", "quickly", "ironic", "final", "bold",
          "pending", "regular", "express", "silent", "even", "blithely",
          "deposits", "packages", "accounts", "theodolites", "instructions",
          "foxes", "pinto", "beans", "dependencies", "platelets"]

N_SUPP_PER_PART = 4


# row-count floors keep tiny test scales dense enough that every query's
# predicates qualify rows (25 nations need >~100 suppliers for nation-pair
# queries like Q7/Q21 to produce output)
def n_supplier(scale: float) -> int:
    return max(int(10_000 * scale), 100)


def n_customer(scale: float) -> int:
    return max(int(150_000 * scale), 300)


def n_part(scale: float) -> int:
    return max(int(200_000 * scale), 200)


def n_orders(scale: float) -> int:
    return max(int(1_500_000 * scale), 3000)


def _orderdates(scale: float, seed: int) -> "np.ndarray":
    """Order dates drawn from a dedicated stream so gen_orders and
    gen_lineitem_full (ship/commit/receipt = orderdate + offsets) stay
    consistent without materializing each other's tables."""
    rng = np.random.default_rng((seed + 5) * 1_000_003 + 17)
    return rng.integers(_D(1992, 1, 1), _D(1998, 8, 3),
                        n_orders(scale)).astype(np.int32)


def _comment(rng, n, salt_phrase=None, salt_frac=0.02):
    """Random word-soup comments; salt_frac of rows get the two salt words
    embedded in order (with a word between, so only multi-segment LIKEs hit)."""
    w = np.array(_WORDS)
    c = np.char.add(np.char.add(w[rng.integers(0, len(w), n)], " "),
                    np.char.add(w[rng.integers(0, len(w), n)],
                                np.char.add(" ", w[rng.integers(0, len(w), n)])))
    if salt_phrase is not None:
        a, b = salt_phrase
        hit = rng.random(n) < salt_frac
        mid = w[rng.integers(0, len(w), n)]
        salted = np.char.add(np.char.add(np.char.add(np.char.add(a, " "), mid),
                                         f" {b} "),
                             w[rng.integers(0, len(w), n)])
        c = np.where(hit, salted, c)
    return c


def _phone(nationkey):
    code = (10 + nationkey).astype(np.int64)
    return np.char.add(code.astype(str),
                       "-" + np.char.zfill(
                           (nationkey * 7919 % 10_000_000).astype(str), 7))


def gen_region() -> pa.Table:
    return pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
        "r_name": pa.array(REGIONS),
        "r_comment": pa.array([f"{r.lower()} region" for r in REGIONS]),
    })


def gen_nation() -> pa.Table:
    return pa.table({
        "n_nationkey": pa.array(np.arange(25, dtype=np.int64)),
        "n_name": pa.array([n for n, _ in NATIONS]),
        "n_regionkey": pa.array(np.array([r for _, r in NATIONS], np.int64)),
        "n_comment": pa.array([f"{n.lower()} nation" for n, _ in NATIONS]),
    })


def gen_supplier(scale: float, seed: int) -> pa.Table:
    n = n_supplier(scale)
    rng = np.random.default_rng(seed + 1)
    keys = np.arange(1, n + 1, dtype=np.int64)
    nationkey = rng.integers(0, 25, n).astype(np.int64)
    return pa.table({
        "s_suppkey": pa.array(keys),
        "s_name": pa.array(np.char.add("Supplier#", np.char.zfill(keys.astype(str), 9))),
        "s_address": pa.array(np.char.add("addr ", keys.astype(str))),
        "s_nationkey": pa.array(nationkey),
        "s_phone": pa.array(_phone(nationkey)),
        "s_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, n), 2)),
        "s_comment": pa.array(_comment(rng, n, ("Customer", "Complaints"), 0.05)),
    })


def gen_customer(scale: float, seed: int) -> pa.Table:
    n = n_customer(scale)
    rng = np.random.default_rng(seed + 2)
    keys = np.arange(1, n + 1, dtype=np.int64)
    nationkey = rng.integers(0, 25, n).astype(np.int64)
    seg = np.array(SEGMENTS)
    return pa.table({
        "c_custkey": pa.array(keys),
        "c_name": pa.array(np.char.add("Customer#", np.char.zfill(keys.astype(str), 9))),
        "c_address": pa.array(np.char.add("caddr ", keys.astype(str))),
        "c_nationkey": pa.array(nationkey),
        "c_phone": pa.array(_phone(nationkey)),
        "c_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, n), 2)),
        "c_mktsegment": pa.array(seg[rng.integers(0, 5, n)]),
        "c_comment": pa.array(_comment(rng, n)),
    })


def gen_part(scale: float, seed: int) -> pa.Table:
    n = n_part(scale)
    rng = np.random.default_rng(seed + 3)
    keys = np.arange(1, n + 1, dtype=np.int64)
    colors = np.array(COLORS)
    name = np.char.add(np.char.add(colors[rng.integers(0, len(colors), n)], " "),
                       colors[rng.integers(0, len(colors), n)])
    t1 = np.array(TYPE_1)[rng.integers(0, len(TYPE_1), n)]
    t2 = np.array(TYPE_2)[rng.integers(0, len(TYPE_2), n)]
    t3 = np.array(TYPE_3)[rng.integers(0, len(TYPE_3), n)]
    ptype = np.char.add(np.char.add(np.char.add(t1, " "), np.char.add(t2, " ")), t3)
    cont = np.char.add(
        np.char.add(np.array(CONTAINER_1)[rng.integers(0, 5, n)], " "),
        np.array(CONTAINER_2)[rng.integers(0, 8, n)])
    brand = np.char.add("Brand#", (rng.integers(1, 6, n) * 10
                                   + rng.integers(1, 6, n)).astype(str))
    return pa.table({
        "p_partkey": pa.array(keys),
        "p_name": pa.array(name),
        "p_mfgr": pa.array(np.char.add("Manufacturer#", rng.integers(1, 6, n).astype(str))),
        "p_brand": pa.array(brand),
        "p_type": pa.array(ptype),
        "p_size": pa.array(rng.integers(1, 51, n).astype(np.int32)),
        "p_container": pa.array(cont),
        "p_retailprice": pa.array(np.round(900 + (keys % 1000) * 100 / 1000.0
                                           + 100 * (keys % 10), 2)),
        "p_comment": pa.array(_comment(rng, n)),
    })


def _ps_suppkey(partkey, i, n_supp):
    """Deterministic part->supplier map shared by partsupp and lineitem so the
    (l_partkey, l_suppkey) FK into partsupp always holds (dbgen does the same
    with its supplier-distribution formula)."""
    return ((partkey + i * (n_supp // N_SUPP_PER_PART + 1)) % n_supp) + 1


def gen_partsupp(scale: float, seed: int) -> pa.Table:
    np_ = n_part(scale)
    n_supp = n_supplier(scale)
    rng = np.random.default_rng(seed + 4)
    partkey = np.repeat(np.arange(1, np_ + 1, dtype=np.int64), N_SUPP_PER_PART)
    i = np.tile(np.arange(N_SUPP_PER_PART, dtype=np.int64), np_)
    n = partkey.shape[0]
    return pa.table({
        "ps_partkey": pa.array(partkey),
        "ps_suppkey": pa.array(_ps_suppkey(partkey, i, n_supp)),
        "ps_availqty": pa.array(rng.integers(1, 10_000, n).astype(np.int32)),
        "ps_supplycost": pa.array(np.round(rng.uniform(1.0, 1000.0, n), 2)),
        "ps_comment": pa.array(_comment(rng, n)),
    })


def gen_orders(scale: float, seed: int) -> pa.Table:
    n = n_orders(scale)
    n_cust = n_customer(scale)
    rng = np.random.default_rng(seed + 5)
    keys = np.arange(1, n + 1, dtype=np.int64)
    # dbgen gives orders to only 2/3 of customers (custkey % 3 != 0): Q13/Q22
    # depend on orderless customers existing
    cust_pool = np.arange(1, n_cust + 1, dtype=np.int64)
    cust_pool = cust_pool[cust_pool % 3 != 0]
    orderdate = _orderdates(scale, seed)
    # status correlates with age like dbgen output: old orders are fulfilled
    status = np.where(orderdate < _D(1995, 6, 17), "F",
                      np.where(rng.random(n) < 0.05, "P", "O"))
    return pa.table({
        "o_orderkey": pa.array(keys),
        "o_custkey": pa.array(cust_pool[rng.integers(0, cust_pool.shape[0], n)]),
        "o_orderstatus": pa.array(status),
        "o_totalprice": pa.array(np.round(rng.uniform(850.0, 560_000.0, n), 2)),
        "o_orderdate": pa.array(orderdate, type=pa.date32()),
        "o_orderpriority": pa.array(np.array(PRIORITIES)[rng.integers(0, 5, n)]),
        "o_clerk": pa.array(np.char.add("Clerk#", np.char.zfill(
            rng.integers(1, max(n // 1000, 2), n).astype(str), 9))),
        "o_shippriority": pa.array(np.zeros(n, np.int32)),
        "o_comment": pa.array(_comment(rng, n, ("special", "requests"), 0.03)),
    })


def gen_lineitem_full(scale: float, seed: int) -> pa.Table:
    n_ord = n_orders(scale)
    np_ = n_part(scale)
    n_supp = n_supplier(scale)
    rng = np.random.default_rng(seed + 6)
    lines_per = rng.integers(1, 8, n_ord)
    orderkey = np.repeat(np.arange(1, n_ord + 1, dtype=np.int64), lines_per)
    n = orderkey.shape[0]
    linenumber = (np.arange(n, dtype=np.int64)
                  - np.repeat(np.cumsum(lines_per) - lines_per, lines_per) + 1)
    odate = _orderdates(scale, seed)[orderkey - 1]
    shipdate = odate + rng.integers(1, 122, n).astype(np.int32)
    commitdate = odate + rng.integers(30, 91, n).astype(np.int32)
    receiptdate = shipdate + rng.integers(1, 31, n).astype(np.int32)
    partkey = rng.integers(1, np_ + 1, n).astype(np.int64)
    suppkey = _ps_suppkey(partkey, rng.integers(0, N_SUPP_PER_PART, n), n_supp)
    quantity = rng.integers(1, 51, n).astype(np.float64)
    extendedprice = np.round(quantity * rng.uniform(900, 2100, n), 2)
    flags = np.where(receiptdate <= _D(1995, 6, 17),
                     np.where(rng.random(n) < 0.5, "R", "A"), "N")
    return pa.table({
        "l_orderkey": pa.array(orderkey),
        "l_partkey": pa.array(partkey),
        "l_suppkey": pa.array(suppkey),
        "l_linenumber": pa.array(linenumber.astype(np.int32)),
        "l_quantity": pa.array(quantity),
        "l_extendedprice": pa.array(extendedprice),
        "l_discount": pa.array(np.round(rng.uniform(0.0, 0.1, n), 2)),
        "l_tax": pa.array(np.round(rng.uniform(0.0, 0.08, n), 2)),
        "l_returnflag": pa.array(flags),
        "l_linestatus": pa.array(np.where(shipdate > _D(1995, 6, 17), "O", "F")),
        "l_shipdate": pa.array(shipdate, type=pa.date32()),
        "l_commitdate": pa.array(commitdate, type=pa.date32()),
        "l_receiptdate": pa.array(receiptdate, type=pa.date32()),
        "l_shipinstruct": pa.array(np.array(SHIPINSTRUCT)[rng.integers(0, 4, n)]),
        "l_shipmode": pa.array(np.array(SHIPMODES)[rng.integers(0, 7, n)]),
        "l_comment": pa.array(_comment(rng, n)),
    })


def gen_all(scale: float = 0.001, seed: int = 0) -> Dict[str, pa.Table]:
    return {
        "region": gen_region(),
        "nation": gen_nation(),
        "supplier": gen_supplier(scale, seed),
        "customer": gen_customer(scale, seed),
        "part": gen_part(scale, seed),
        "partsupp": gen_partsupp(scale, seed),
        "orders": gen_orders(scale, seed),
        "lineitem": gen_lineitem_full(scale, seed),
    }
