"""TPC-H-like benchmark data + queries (reference:
integration_tests/src/main/scala/.../tpch/ — "Like" queries over generated data;
doubles instead of decimals, exactly like the reference's TpchLike schema since
v0 has no decimal support).

The generator is a deterministic, vectorized dbgen-alike for the lineitem table
(the table Q1/Q6 need); scale factor 1.0 ~ 6M rows.
"""
from __future__ import annotations

import datetime

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.api.dataframe import DataFrame

_FLAGS = np.array(["A", "N", "R"])
_STATUS = np.array(["F", "O"])
_EPOCH_1992 = (datetime.date(1992, 1, 1) - datetime.date(1970, 1, 1)).days


def gen_lineitem(scale: float = 0.01, seed: int = 0) -> pa.Table:
    n = int(6_000_000 * scale)
    rng = np.random.default_rng(seed)
    quantity = rng.integers(1, 51, n).astype(np.float64)
    extendedprice = np.round(rng.uniform(900, 105000, n), 2)
    discount = np.round(rng.uniform(0.0, 0.1, n), 2)
    tax = np.round(rng.uniform(0.0, 0.08, n), 2)
    flag_idx = rng.integers(0, 3, n)
    status_idx = rng.integers(0, 2, n)
    shipdate = (_EPOCH_1992 + rng.integers(0, 2526, n)).astype(np.int32)
    orderkey = rng.integers(1, max(int(n / 4), 2), n).astype(np.int64)
    return pa.table({
        "l_orderkey": pa.array(orderkey),
        "l_quantity": pa.array(quantity),
        "l_extendedprice": pa.array(extendedprice),
        "l_discount": pa.array(discount),
        "l_tax": pa.array(tax),
        "l_returnflag": pa.array(_FLAGS[flag_idx]),
        "l_linestatus": pa.array(_STATUS[status_idx]),
        "l_shipdate": pa.array(shipdate, type=pa.date32()),
    })


def q1(lineitem: DataFrame) -> DataFrame:
    """TPC-H Q1: pricing summary report."""
    cutoff = datetime.date(1998, 9, 2)
    disc_price = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    charge = disc_price * (1 + F.col("l_tax"))
    return (lineitem
            .filter(F.col("l_shipdate") <= F.lit(cutoff))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count().alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q6(lineitem: DataFrame) -> DataFrame:
    """TPC-H Q6: forecasting revenue change."""
    lo = datetime.date(1994, 1, 1)
    hi = datetime.date(1995, 1, 1)
    return (lineitem
            .filter((F.col("l_shipdate") >= F.lit(lo))
                    & (F.col("l_shipdate") < F.lit(hi))
                    & (F.col("l_discount") >= 0.05)
                    & (F.col("l_discount") <= 0.07)
                    & (F.col("l_quantity") < 24))
            .agg(F.sum(F.col("l_extendedprice") * F.col("l_discount"))
                 .alias("revenue")))


BENCH_CONF = {
    # float sums are required by TPC-H aggregates (same switch the reference
    # flips for benchmarks: spark.rapids.sql.variableFloatAgg.enabled)
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.tpu.sql.incompatibleOps.enabled": "true",
    # v5e has 16 GB HBM; the 2 GiB default thrashes at SF >= 1 (store_sales
    # alone exceeds it device-side, so every query re-uploaded it — 5.4 s
    # per query measured; the reference's tuning guide similarly sizes the
    # device pool to the data)
    "spark.rapids.tpu.sql.scanCache.maxBytes": str(12 << 30),
}
