"""TPC-DS queries as raw SQL text through the SQL frontend.

Reference analog: TpcdsLikeSpark.scala runs every TPC-DS query as SQL text
through Catalyst (TpcdsLikeSpark.scala:761 onward). This module carries the
same queries as SQL for THIS engine's frontend, written against the exact
constants of the DataFrame translations in benchmarks/tpcds_queries.py (which
adapt the public spec's parameters to the generator's calendar and pools) —
so `sess.sql(SQL_QUERIES[q])` must produce results identical to
`QUERIES[q](dfs)`, the fidelity bar Catalyst gets for free.

Queries are standard TPC-DS SQL shapes: star joins over channel fact tables,
derived tables, CTEs, window functions, ROLLUP, and correlated/scalar
subqueries — exercising the full frontend surface.
"""

SQL_QUERIES = {
    "q3": """
select d_year, i_brand_id as brand_id, i_brand as brand, sum_agg
from (select d_year, i_brand, i_brand_id,
             sum(ss_ext_sales_price) as sum_agg
      from date_dim, store_sales, item
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and d_moy = 11 and i_manufact_id = 128
      group by d_year, i_brand, i_brand_id) x
order by d_year, sum_agg desc, brand_id
limit 100
""",
    "q7": """
select i_item_id,
       avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, date_dim, item, customer_demographics, promotion
where ss_sold_date_sk = d_date_sk and d_year = 2000
  and ss_item_sk = i_item_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and ss_promo_sk = p_promo_sk
  and (p_channel_email = 'N' or p_channel_event = 'N')
group by i_item_id
order by i_item_id
limit 100
""",
    "q19": """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id, i_manufact,
       ext_price
from (select i_brand, i_brand_id, i_manufact_id, i_manufact,
             sum(ss_ext_sales_price) as ext_price
      from date_dim, store_sales, item, customer, customer_address, store
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and d_moy = 11 and d_year = 1998 and i_manager_id = 8
        and ss_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk
        and ss_store_sk = s_store_sk
        and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
      group by i_brand, i_brand_id, i_manufact_id, i_manufact) x
order by ext_price desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
""",
    "q34": """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and d_year in (1999, 2000, 2001)
        and hd_buy_potential in ('>10000', 'unknown')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then hd_dep_count / hd_vehicle_count
                  else null end) > 1.2
        and s_county = 'Williamson County'
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk and cnt between 15 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
""",
    "q42": """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as s
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and d_moy = 11 and d_year = 2000 and i_manager_id = 1
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
""",
    "q46": """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ss_addr_sk,
             ca_city as bought_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dow in (5, 6) and d_year in (1999, 2000, 2001)
        and s_city in ('Fairview', 'Midway')
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
""",
    "q52": """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and d_moy = 11 and d_year = 2000 and i_manager_id = 1
group by d_year, i_brand, i_brand_id
order by d_year, ext_price desc, brand_id
limit 100
""",
    "q55": """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and d_moy = 11 and d_year = 1999 and i_manager_id = 28
group by i_brand, i_brand_id
order by ext_price desc, brand_id
limit 100
""",
    "q16": """
select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales, date_dim, customer_address, call_center
where cs_ship_date_sk = d_date_sk
  and d_date between date '2002-02-01' and date '2002-04-02'
  and cs_ship_addr_sk = ca_address_sk and ca_state = 'GA'
  and cs_call_center_sk = cc_call_center_sk
  and cc_county = 'Williamson County'
  and exists (select *
              from (select cs_order_number as o2,
                           count(distinct cs_warehouse_sk) as nw
                    from catalog_sales
                    where cs_warehouse_sk is not null
                    group by cs_order_number) m
              where m.o2 = cs_order_number and m.nw >= 2)
  and not exists (select * from catalog_returns
                  where cr_order_number = cs_order_number)
""",
    "q94": """
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales, date_dim, customer_address, web_site
where ws_ship_date_sk = d_date_sk
  and d_date between date '1999-02-01' and date '1999-04-02'
  and ws_ship_addr_sk = ca_address_sk and ca_state = 'GA'
  and ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and exists (select *
              from (select ws_order_number as o2,
                           count(distinct ws_warehouse_sk) as nw
                    from web_sales
                    where ws_warehouse_sk is not null
                    group by ws_order_number) m
              where m.o2 = ws_order_number and m.nw >= 2)
  and not exists (select * from web_returns
                  where wr_order_number = ws_order_number)
""",
    "q20": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / sum(itemrevenue)
           over (partition by i_class) as revenueratio
from (select i_item_id, i_item_desc, i_category, i_class, i_current_price,
             sum(cs_ext_sales_price) as itemrevenue
      from catalog_sales, item, date_dim
      where cs_item_sk = i_item_sk
        and i_category in ('Sports', 'Books', 'Home')
        and cs_sold_date_sk = d_date_sk
        and d_date between date '1999-02-22' and date '1999-03-24'
      group by i_item_id, i_item_desc, i_category, i_class,
               i_current_price) base
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    "q21": """
select w_warehouse_name, i_item_id, inv_before, inv_after
from (select w_warehouse_name, i_item_id,
             sum(case when d_date < date '2000-03-11'
                      then inv_quantity_on_hand else 0 end) as inv_before,
             sum(case when d_date >= date '2000-03-11'
                      then inv_quantity_on_hand else 0 end) as inv_after
      from inventory, warehouse, item, date_dim
      where inv_warehouse_sk = w_warehouse_sk and inv_item_sk = i_item_sk
        and inv_date_sk = d_date_sk
        and i_current_price between 0.99 and 1.49
        and datediff(d_date, date '2000-03-11') between -30 and 30
      group by w_warehouse_name, i_item_id) x
where (case when inv_before > 0 then inv_after / inv_before else null end)
      >= 2.0 / 3.0
  and (case when inv_before > 0 then inv_after / inv_before else null end)
      <= 3.0 / 2.0
order by w_warehouse_name, i_item_id
limit 100
""",
    "q25": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, date_dim, item, store, store_returns d2, catalog_sales
where ss_sold_date_sk = d_date_sk and d_moy = 4 and d_year = 2001
  and ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk in
      (select d_date_sk from date_dim
       where d_moy between 4 and 10 and d_year = 2001)
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk in
      (select d_date_sk from date_dim
       where d_moy between 4 and 10 and d_year = 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    "q29": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, date_dim, item, store, store_returns d2, catalog_sales
where ss_sold_date_sk = d_date_sk and d_moy = 9 and d_year = 1999
  and ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk in
      (select d_date_sk from date_dim
       where d_moy between 9 and 12 and d_year = 1999)
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk in
      (select d_date_sk from date_dim
       where d_year in (1999, 2000, 2001))
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    "q26": """
select i_item_id,
       avg(cs_quantity) as agg1, avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3, avg(cs_sales_price) as agg4
from catalog_sales, date_dim, item, customer_demographics, promotion
where cs_sold_date_sk = d_date_sk and d_year = 2000
  and cs_item_sk = i_item_sk and cs_bill_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and cs_promo_sk = p_promo_sk
  and (p_channel_email = 'N' or p_channel_event = 'N')
group by i_item_id
order by i_item_id
limit 100
""",
    "q32": """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 77 and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt >
      (select 1.3 * avg(cs_ext_discount_amt)
       from catalog_sales, date_dim
       where cs_item_sk = i_item_sk and d_date_sk = cs_sold_date_sk
         and d_date between date '2000-01-27' and date '2000-04-26')
""",
    "q92": """
select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 50 and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt >
      (select 1.3 * avg(ws_ext_discount_amt)
       from web_sales, date_dim
       where ws_item_sk = i_item_sk and d_date_sk = ws_sold_date_sk
         and d_date between date '2000-01-27' and date '2000-04-26')
""",
    "q43": """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price else null end)
           as sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price else null end)
           as mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price else null
           end) as tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price else null
           end) as wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price else null
           end) as thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price else null
           end) as fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price else null
           end) as sat_sales
from store_sales, date_dim, store
where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
  and d_year = 2000 and s_gmt_offset = -5.0
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
""",
    "q65": """
with base as (
  select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
  group by ss_store_sk, ss_item_sk),
avg_rev as (
  select ss_store_sk as sb_store_sk, avg(revenue) as ave
  from base group by ss_store_sk)
select s_store_name, i_item_desc, revenue, i_current_price,
       i_wholesale_cost, i_brand
from base, avg_rev, store, item
where ss_store_sk = sb_store_sk and revenue <= ave * 0.1
  and ss_store_sk = s_store_sk and ss_item_sk = i_item_sk
order by s_store_name, i_item_desc
limit 100
""",
    "q68": """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ss_addr_sk,
             ca_city as bought_city,
             sum(ss_ext_sales_price) as extended_price,
             sum(ss_ext_list_price) as list_price,
             sum(ss_ext_tax) as extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dom between 1 and 2 and d_year in (1999, 2000, 2001)
        and s_city in ('Midway', 'Fairview')
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
""",
    "q73": """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dom between 1 and 2 and d_year in (1999, 2000, 2001)
        and hd_buy_potential in ('>10000', 'unknown')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then hd_dep_count / hd_vehicle_count
                  else null end) > 1
        and s_county in ('Williamson County', 'Franklin Parish',
                         'Bronx County', 'Orange County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_last_name
""",
    "q79": """
select c_last_name, c_first_name, substring(s_city, 1, 30) as city,
       ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dow = 1 and d_year in (1999, 2000, 2001)
        and s_number_employees between 200 and 295
        and (hd_dep_count = 6 or hd_vehicle_count > 2)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city, profit desc
limit 100
""",
    "q89": """
select *
from (select i_category, i_class, i_brand, s_store_name, s_company_name,
             d_moy, sum_sales, avg_monthly_sales
      from (select i_category, i_class, i_brand, s_store_name,
                   s_company_name, d_moy, sum_sales,
                   avg(sum_sales) over (partition by i_category, i_brand,
                                        s_store_name, s_company_name)
                       as avg_monthly_sales
            from (select i_category, i_class, i_brand, s_store_name,
                         s_company_name, d_moy,
                         sum(ss_sales_price) as sum_sales
                  from store_sales, item, date_dim, store
                  where ss_item_sk = i_item_sk
                    and ss_sold_date_sk = d_date_sk
                    and ss_store_sk = s_store_sk and d_year = 1999
                    and ((i_category in ('Books', 'Electronics', 'Sports')
                          and i_class in ('computers', 'stereo', 'football'))
                         or (i_category in ('Men', 'Jewelry', 'Women')
                             and i_class in ('shirts', 'birdal', 'dresses')))
                  group by i_category, i_class, i_brand, s_store_name,
                           s_company_name, d_moy) t1) t2
      where case when avg_monthly_sales <> 0.0
                 then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
                 else null end > 0.1
      order by sum_sales - avg_monthly_sales, s_store_name
      limit 100) t3
""",
    "q96": """
select count(*) as cnt
from store_sales, time_dim, household_demographics, store
where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
  and s_store_name = 'ese'
""",
    "q98": """
select i_item_desc, i_category, i_class, i_current_price, itemrevenue,
       revenueratio
from (select i_item_id, i_item_desc, i_category, i_class, i_current_price,
             itemrevenue,
             itemrevenue * 100.0 / sum(itemrevenue)
                 over (partition by i_class) as revenueratio
      from (select i_item_id, i_item_desc, i_category, i_class,
                   i_current_price,
                   sum(ss_ext_sales_price) as itemrevenue
            from store_sales, item, date_dim
            where ss_item_sk = i_item_sk
              and i_category in ('Sports', 'Books', 'Home')
              and ss_sold_date_sk = d_date_sk
              and d_date between date '1999-02-22' and date '1999-03-24'
            group by i_item_id, i_item_desc, i_category, i_class,
                     i_current_price) base) x
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
""",
    "q15": """
select ca_zip, sum(cs_sales_price) as sum_sales_price
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
  and (substring(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                   '86475', '85392', '85460', '80348',
                                   '81792')
       or ca_state in ('CA', 'WA', 'GA')
       or cs_sales_price > 500)
group by ca_zip
order by ca_zip
limit 100
""",
    "q37": """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim
where i_current_price between 68 and 98
  and i_manufact_id in (8, 33, 58, 83)
  and inv_item_sk = i_item_sk
  and inv_quantity_on_hand between 100 and 500
  and inv_date_sk = d_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and exists (select * from catalog_sales where cs_item_sk = i_item_sk)
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",
    "q40": """
select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0.0)
                else 0.0 end) as sales_before,
       sum(case when d_date >= date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0.0)
                else 0.0 end) as sales_after
from catalog_sales left join catalog_returns
       on cs_order_number = cr_order_number and cs_item_sk = cr_item_sk,
     warehouse, item, date_dim
where cs_warehouse_sk = w_warehouse_sk and cs_item_sk = i_item_sk
  and i_current_price between 0.99 and 1.49
  and cs_sold_date_sk = d_date_sk
  and datediff(d_date, date '2000-03-11') between -30 and 30
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
""",
    "q45": """
select ca_zip, ca_city, sum(ws_sales_price) as sum_ws_sales_price
from web_sales, customer, customer_address, item, date_dim
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and ws_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
  and (substring(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                   '86475', '85392', '85460', '80348',
                                   '81792')
       or i_item_id in (select i_item_id from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19,
                                            23, 29)))
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
""",
    "q62": """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30 then 1
                else 0 end) as d30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60 then 1
                else 0 end) as d31_60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
                 and ws_ship_date_sk - ws_sold_date_sk <= 90 then 1
                else 0 end) as d61_90,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 90
                 and ws_ship_date_sk - ws_sold_date_sk <= 120 then 1
                else 0 end) as d91_120,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 120 then 1
                else 0 end) as d_over_120
from web_sales, date_dim, warehouse, ship_mode, web_site
where ws_ship_date_sk = d_date_sk
  and d_month_seq between 1200 and 1211
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by substring(w_warehouse_name, 1, 20), sm_type, web_name
order by wname, sm_type, web_name
limit 100
""",
    "q99": """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, cc_name,
       sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30 then 1
                else 0 end) as d30,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
                 and cs_ship_date_sk - cs_sold_date_sk <= 60 then 1
                else 0 end) as d31_60,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
                 and cs_ship_date_sk - cs_sold_date_sk <= 90 then 1
                else 0 end) as d61_90,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 90
                 and cs_ship_date_sk - cs_sold_date_sk <= 120 then 1
                else 0 end) as d91_120,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 120 then 1
                else 0 end) as d_over_120
from catalog_sales, date_dim, warehouse, ship_mode, call_center
where cs_ship_date_sk = d_date_sk
  and d_month_seq between 1200 and 1211
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substring(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
""",
    "q90": """
select amc / pmc as am_pm_ratio
from (select count(*) as amc
      from web_sales, household_demographics, time_dim, web_page
      where ws_ship_hdemo_sk = hd_demo_sk and hd_dep_count = 6
        and ws_sold_time_sk = t_time_sk
        and t_hour between 8 and 9
        and ws_web_page_sk = wp_web_page_sk
        and wp_char_count between 5000 and 5200) at,
     (select count(*) as pmc
      from web_sales, household_demographics, time_dim, web_page
      where ws_ship_hdemo_sk = hd_demo_sk and hd_dep_count = 6
        and ws_sold_time_sk = t_time_sk
        and t_hour between 19 and 20
        and ws_web_page_sk = wp_web_page_sk
        and wp_char_count between 5000 and 5200) pt
""",
    "q93": """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end as act_sales,
             sr_reason_sk
      from store_sales left join store_returns
             on ss_item_sk = sr_item_sk
            and ss_ticket_number = sr_ticket_number) x, reason
where sr_reason_sk = r_reason_sk
  and r_reason_desc = 'Package was damaged'
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
""",
    "q13": """
select avg(ss_quantity) as avg_quantity,
       avg(ss_ext_sales_price) as avg_ext_sales_price,
       avg(ss_ext_wholesale_cost) as avg_ext_wholesale,
       sum(ss_ext_wholesale_cost) as sum_ext_wholesale
from store_sales, store, date_dim, customer_demographics,
     household_demographics, customer_address
where ss_store_sk = s_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ss_cdemo_sk = cd_demo_sk and ss_hdemo_sk = hd_demo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.0 and 150.0 and hd_dep_count = 3)
       or (cd_marital_status = 'S' and cd_education_status = 'College'
           and ss_sales_price between 50.0 and 100.0 and hd_dep_count = 1)
       or (cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
           and ss_sales_price between 150.0 and 200.0 and hd_dep_count = 1))
  and ((ca_country = 'United States' and ca_state in ('TX', 'OH', 'GA')
        and ss_net_profit between 100 and 200)
       or (ca_country = 'United States' and ca_state in ('TN', 'IN', 'SD')
           and ss_net_profit between 150 and 300)
       or (ca_country = 'United States' and ca_state in ('LA', 'MI', 'SC')
           and ss_net_profit between 50 and 250))
""",
    "q17": """
select i_item_id, i_item_desc, s_state,
       count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev(ss_quantity) as store_sales_quantitystdev,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev(sr_return_quantity) as store_returns_quantitystdev,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev(cs_quantity) as catalog_sales_quantitystdev,
       stddev(ss_quantity) / avg(ss_quantity) as store_sales_quantitycov,
       stddev(sr_return_quantity) / avg(sr_return_quantity)
           as store_returns_quantitycov,
       stddev(cs_quantity) / avg(cs_quantity) as catalog_sales_quantitycov
from store_sales, date_dim, item, store, store_returns, catalog_sales
where ss_sold_date_sk = d_date_sk and d_quarter_name = '2001Q1'
  and ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk in
      (select d_date_sk from date_dim
       where d_quarter_name in ('2001Q1', '2001Q2', '2001Q3'))
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk in
      (select d_date_sk from date_dim
       where d_quarter_name in ('2001Q1', '2001Q2', '2001Q3'))
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
""",
    "q28": """
select *
from (select avg(ss_list_price) as b1_lp, count(ss_list_price) as b1_cnt,
             count(distinct ss_list_price) as b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between 8 and 18
             or ss_coupon_amt between 459 and 1459
             or ss_wholesale_cost between 57 and 77)) b1,
     (select avg(ss_list_price) as b2_lp, count(ss_list_price) as b2_cnt,
             count(distinct ss_list_price) as b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between 90 and 100
             or ss_coupon_amt between 2323 and 3323
             or ss_wholesale_cost between 31 and 51)) b2,
     (select avg(ss_list_price) as b3_lp, count(ss_list_price) as b3_cnt,
             count(distinct ss_list_price) as b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between 142 and 152
             or ss_coupon_amt between 12214 and 13214
             or ss_wholesale_cost between 79 and 99)) b3,
     (select avg(ss_list_price) as b4_lp, count(ss_list_price) as b4_cnt,
             count(distinct ss_list_price) as b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between 135 and 145
             or ss_coupon_amt between 6071 and 7071
             or ss_wholesale_cost between 38 and 58)) b4,
     (select avg(ss_list_price) as b5_lp, count(ss_list_price) as b5_cnt,
             count(distinct ss_list_price) as b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between 122 and 132
             or ss_coupon_amt between 836 and 1836
             or ss_wholesale_cost between 17 and 37)) b5,
     (select avg(ss_list_price) as b6_lp, count(ss_list_price) as b6_cnt,
             count(distinct ss_list_price) as b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between 154 and 164
             or ss_coupon_amt between 7326 and 8326
             or ss_wholesale_cost between 7 and 27)) b6
limit 100
""",
    "q33": """
with subset as (
  select distinct i_manufact_id as sub_key from item
  where i_category in ('Electronics')),
dd as (select d_date_sk from date_dim where d_year = 1998 and d_moy = 5),
addr as (select ca_address_sk from customer_address
         where ca_gmt_offset = -5.0),
ss as (
  select i_manufact_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, item
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk in (select d_date_sk from dd)
    and ss_addr_sk in (select ca_address_sk from addr)
    and i_manufact_id in (select sub_key from subset)
  group by i_manufact_id),
cs as (
  select i_manufact_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, item
  where cs_item_sk = i_item_sk
    and cs_sold_date_sk in (select d_date_sk from dd)
    and cs_bill_addr_sk in (select ca_address_sk from addr)
    and i_manufact_id in (select sub_key from subset)
  group by i_manufact_id),
ws as (
  select i_manufact_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, item
  where ws_item_sk = i_item_sk
    and ws_sold_date_sk in (select d_date_sk from dd)
    and ws_bill_addr_sk in (select ca_address_sk from addr)
    and i_manufact_id in (select sub_key from subset)
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) u
group by i_manufact_id
order by total_sales
limit 100
""",
    "q60": """
with subset as (
  select distinct i_item_id as sub_key from item
  where i_category in ('Music')),
dd as (select d_date_sk from date_dim where d_year = 1998 and d_moy = 9),
addr as (select ca_address_sk from customer_address
         where ca_gmt_offset = -5.0),
ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, item
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk in (select d_date_sk from dd)
    and ss_addr_sk in (select ca_address_sk from addr)
    and i_item_id in (select sub_key from subset)
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, item
  where cs_item_sk = i_item_sk
    and cs_sold_date_sk in (select d_date_sk from dd)
    and cs_bill_addr_sk in (select ca_address_sk from addr)
    and i_item_id in (select sub_key from subset)
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, item
  where ws_item_sk = i_item_sk
    and ws_sold_date_sk in (select d_date_sk from dd)
    and ws_bill_addr_sk in (select ca_address_sk from addr)
    and i_item_id in (select sub_key from subset)
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) u
group by i_item_id
order by i_item_id, total_sales
limit 100
""",
    "q86": """
select total_sum, i_category, i_class, lochierarchy, rank_within_parent
from (select total_sum, i_category, i_class, lochierarchy,
             rank() over (partition by lochierarchy, _parent
                          order by total_sum desc) as rank_within_parent
      from (select sum(ws_net_paid) as total_sum, i_category, i_class,
                   (case when i_category is null then 1 else 0 end
                    + case when i_class is null then 1 else 0 end)
                       as lochierarchy,
                   case when i_class is not null then i_category
                        else null end as _parent
            from web_sales, date_dim, item
            where ws_sold_date_sk = d_date_sk
              and d_month_seq between 1200 and 1211
              and ws_item_sk = i_item_sk
            group by rollup(i_category, i_class)) x) y
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category else null end,
         rank_within_parent
limit 100
""",
    "q12": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / sum(itemrevenue)
           over (partition by i_class) as revenueratio
from (select i_item_id, i_item_desc, i_category, i_class, i_current_price,
             sum(ws_ext_sales_price) as itemrevenue
      from web_sales, item, date_dim
      where ws_item_sk = i_item_sk
        and i_category in ('Sports', 'Books', 'Home')
        and ws_sold_date_sk = d_date_sk
        and d_date between date '1999-02-22' and date '1999-03-24'
      group by i_item_id, i_item_desc, i_category, i_class,
               i_current_price) base
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
}
