"""TPC-DS queries as raw SQL text through the SQL frontend.

Reference analog: TpcdsLikeSpark.scala runs every TPC-DS query as SQL text
through Catalyst (TpcdsLikeSpark.scala:761 onward). This module carries the
same queries as SQL for THIS engine's frontend, written against the exact
constants of the DataFrame translations in benchmarks/tpcds_queries.py (which
adapt the public spec's parameters to the generator's calendar and pools) —
so `sess.sql(SQL_QUERIES[q])` must produce results identical to
`QUERIES[q](dfs)`, the fidelity bar Catalyst gets for free.

Queries are standard TPC-DS SQL shapes: star joins over channel fact tables,
derived tables, CTEs, window functions, ROLLUP, and correlated/scalar
subqueries — exercising the full frontend surface.
"""

SQL_QUERIES = {
    "q3": """
select d_year, i_brand_id as brand_id, i_brand as brand, sum_agg
from (select d_year, i_brand, i_brand_id,
             sum(ss_ext_sales_price) as sum_agg
      from date_dim, store_sales, item
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and d_moy = 11 and i_manufact_id = 128
      group by d_year, i_brand, i_brand_id) x
order by d_year, sum_agg desc, brand_id
limit 100
""",
    "q7": """
select i_item_id,
       avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, date_dim, item, customer_demographics, promotion
where ss_sold_date_sk = d_date_sk and d_year = 2000
  and ss_item_sk = i_item_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and ss_promo_sk = p_promo_sk
  and (p_channel_email = 'N' or p_channel_event = 'N')
group by i_item_id
order by i_item_id
limit 100
""",
    "q19": """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id, i_manufact,
       ext_price
from (select i_brand, i_brand_id, i_manufact_id, i_manufact,
             sum(ss_ext_sales_price) as ext_price
      from date_dim, store_sales, item, customer, customer_address, store
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and d_moy = 11 and d_year = 1998 and i_manager_id = 8
        and ss_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk
        and ss_store_sk = s_store_sk
        and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
      group by i_brand, i_brand_id, i_manufact_id, i_manufact) x
order by ext_price desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
""",
    "q34": """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and d_year in (1999, 2000, 2001)
        and hd_buy_potential in ('>10000', 'unknown')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then hd_dep_count / hd_vehicle_count
                  else null end) > 1.2
        and s_county = 'Williamson County'
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk and cnt between 15 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
""",
    "q42": """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as s
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and d_moy = 11 and d_year = 2000 and i_manager_id = 1
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
""",
    "q46": """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ss_addr_sk,
             ca_city as bought_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dow in (5, 6) and d_year in (1999, 2000, 2001)
        and s_city in ('Fairview', 'Midway')
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
""",
    "q52": """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and d_moy = 11 and d_year = 2000 and i_manager_id = 1
group by d_year, i_brand, i_brand_id
order by d_year, ext_price desc, brand_id
limit 100
""",
    "q55": """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and d_moy = 11 and d_year = 1999 and i_manager_id = 28
group by i_brand, i_brand_id
order by ext_price desc, brand_id
limit 100
""",
    "q16": """
select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales, date_dim, customer_address, call_center
where cs_ship_date_sk = d_date_sk
  and d_date between date '2002-02-01' and date '2002-04-02'
  and cs_ship_addr_sk = ca_address_sk and ca_state = 'GA'
  and cs_call_center_sk = cc_call_center_sk
  and cc_county = 'Williamson County'
  and exists (select *
              from (select cs_order_number as o2,
                           count(distinct cs_warehouse_sk) as nw
                    from catalog_sales
                    where cs_warehouse_sk is not null
                    group by cs_order_number) m
              where m.o2 = cs_order_number and m.nw >= 2)
  and not exists (select * from catalog_returns
                  where cr_order_number = cs_order_number)
""",
    "q94": """
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales, date_dim, customer_address, web_site
where ws_ship_date_sk = d_date_sk
  and d_date between date '1999-02-01' and date '1999-04-02'
  and ws_ship_addr_sk = ca_address_sk and ca_state = 'GA'
  and ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and exists (select *
              from (select ws_order_number as o2,
                           count(distinct ws_warehouse_sk) as nw
                    from web_sales
                    where ws_warehouse_sk is not null
                    group by ws_order_number) m
              where m.o2 = ws_order_number and m.nw >= 2)
  and not exists (select * from web_returns
                  where wr_order_number = ws_order_number)
""",
    "q20": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / sum(itemrevenue)
           over (partition by i_class) as revenueratio
from (select i_item_id, i_item_desc, i_category, i_class, i_current_price,
             sum(cs_ext_sales_price) as itemrevenue
      from catalog_sales, item, date_dim
      where cs_item_sk = i_item_sk
        and i_category in ('Sports', 'Books', 'Home')
        and cs_sold_date_sk = d_date_sk
        and d_date between date '1999-02-22' and date '1999-03-24'
      group by i_item_id, i_item_desc, i_category, i_class,
               i_current_price) base
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    "q21": """
select w_warehouse_name, i_item_id, inv_before, inv_after
from (select w_warehouse_name, i_item_id,
             sum(case when d_date < date '2000-03-11'
                      then inv_quantity_on_hand else 0 end) as inv_before,
             sum(case when d_date >= date '2000-03-11'
                      then inv_quantity_on_hand else 0 end) as inv_after
      from inventory, warehouse, item, date_dim
      where inv_warehouse_sk = w_warehouse_sk and inv_item_sk = i_item_sk
        and inv_date_sk = d_date_sk
        and i_current_price between 0.99 and 1.49
        and datediff(d_date, date '2000-03-11') between -30 and 30
      group by w_warehouse_name, i_item_id) x
where (case when inv_before > 0 then inv_after / inv_before else null end)
      >= 2.0 / 3.0
  and (case when inv_before > 0 then inv_after / inv_before else null end)
      <= 3.0 / 2.0
order by w_warehouse_name, i_item_id
limit 100
""",
    "q25": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, date_dim, item, store, store_returns d2, catalog_sales
where ss_sold_date_sk = d_date_sk and d_moy = 4 and d_year = 2001
  and ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk in
      (select d_date_sk from date_dim
       where d_moy between 4 and 10 and d_year = 2001)
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk in
      (select d_date_sk from date_dim
       where d_moy between 4 and 10 and d_year = 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    "q29": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, date_dim, item, store, store_returns d2, catalog_sales
where ss_sold_date_sk = d_date_sk and d_moy = 9 and d_year = 1999
  and ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk in
      (select d_date_sk from date_dim
       where d_moy between 9 and 12 and d_year = 1999)
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk in
      (select d_date_sk from date_dim
       where d_year in (1999, 2000, 2001))
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    "q26": """
select i_item_id,
       avg(cs_quantity) as agg1, avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3, avg(cs_sales_price) as agg4
from catalog_sales, date_dim, item, customer_demographics, promotion
where cs_sold_date_sk = d_date_sk and d_year = 2000
  and cs_item_sk = i_item_sk and cs_bill_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and cs_promo_sk = p_promo_sk
  and (p_channel_email = 'N' or p_channel_event = 'N')
group by i_item_id
order by i_item_id
limit 100
""",
    "q32": """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 77 and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt >
      (select 1.3 * avg(cs_ext_discount_amt)
       from catalog_sales, date_dim
       where cs_item_sk = i_item_sk and d_date_sk = cs_sold_date_sk
         and d_date between date '2000-01-27' and date '2000-04-26')
""",
    "q92": """
select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 50 and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt >
      (select 1.3 * avg(ws_ext_discount_amt)
       from web_sales, date_dim
       where ws_item_sk = i_item_sk and d_date_sk = ws_sold_date_sk
         and d_date between date '2000-01-27' and date '2000-04-26')
""",
    "q43": """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price else null end)
           as sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price else null end)
           as mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price else null
           end) as tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price else null
           end) as wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price else null
           end) as thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price else null
           end) as fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price else null
           end) as sat_sales
from store_sales, date_dim, store
where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
  and d_year = 2000 and s_gmt_offset = -5.0
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
""",
    "q65": """
with base as (
  select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
  group by ss_store_sk, ss_item_sk),
avg_rev as (
  select ss_store_sk as sb_store_sk, avg(revenue) as ave
  from base group by ss_store_sk)
select s_store_name, i_item_desc, revenue, i_current_price,
       i_wholesale_cost, i_brand
from base, avg_rev, store, item
where ss_store_sk = sb_store_sk and revenue <= ave * 0.1
  and ss_store_sk = s_store_sk and ss_item_sk = i_item_sk
order by s_store_name, i_item_desc
limit 100
""",
    "q68": """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ss_addr_sk,
             ca_city as bought_city,
             sum(ss_ext_sales_price) as extended_price,
             sum(ss_ext_list_price) as list_price,
             sum(ss_ext_tax) as extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dom between 1 and 2 and d_year in (1999, 2000, 2001)
        and s_city in ('Midway', 'Fairview')
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
""",
    "q73": """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dom between 1 and 2 and d_year in (1999, 2000, 2001)
        and hd_buy_potential in ('>10000', 'unknown')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then hd_dep_count / hd_vehicle_count
                  else null end) > 1
        and s_county in ('Williamson County', 'Franklin Parish',
                         'Bronx County', 'Orange County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_last_name
""",
    "q79": """
select c_last_name, c_first_name, substring(s_city, 1, 30) as city,
       ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dow = 1 and d_year in (1999, 2000, 2001)
        and s_number_employees between 200 and 295
        and (hd_dep_count = 6 or hd_vehicle_count > 2)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city, profit desc
limit 100
""",
    "q89": """
select *
from (select i_category, i_class, i_brand, s_store_name, s_company_name,
             d_moy, sum_sales, avg_monthly_sales
      from (select i_category, i_class, i_brand, s_store_name,
                   s_company_name, d_moy, sum_sales,
                   avg(sum_sales) over (partition by i_category, i_brand,
                                        s_store_name, s_company_name)
                       as avg_monthly_sales
            from (select i_category, i_class, i_brand, s_store_name,
                         s_company_name, d_moy,
                         sum(ss_sales_price) as sum_sales
                  from store_sales, item, date_dim, store
                  where ss_item_sk = i_item_sk
                    and ss_sold_date_sk = d_date_sk
                    and ss_store_sk = s_store_sk and d_year = 1999
                    and ((i_category in ('Books', 'Electronics', 'Sports')
                          and i_class in ('computers', 'stereo', 'football'))
                         or (i_category in ('Men', 'Jewelry', 'Women')
                             and i_class in ('shirts', 'birdal', 'dresses')))
                  group by i_category, i_class, i_brand, s_store_name,
                           s_company_name, d_moy) t1) t2
      where case when avg_monthly_sales <> 0.0
                 then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
                 else null end > 0.1
      order by sum_sales - avg_monthly_sales, s_store_name
      limit 100) t3
""",
    "q96": """
select count(*) as cnt
from store_sales, time_dim, household_demographics, store
where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
  and s_store_name = 'ese'
""",
    "q98": """
select i_item_desc, i_category, i_class, i_current_price, itemrevenue,
       revenueratio
from (select i_item_id, i_item_desc, i_category, i_class, i_current_price,
             itemrevenue,
             itemrevenue * 100.0 / sum(itemrevenue)
                 over (partition by i_class) as revenueratio
      from (select i_item_id, i_item_desc, i_category, i_class,
                   i_current_price,
                   sum(ss_ext_sales_price) as itemrevenue
            from store_sales, item, date_dim
            where ss_item_sk = i_item_sk
              and i_category in ('Sports', 'Books', 'Home')
              and ss_sold_date_sk = d_date_sk
              and d_date between date '1999-02-22' and date '1999-03-24'
            group by i_item_id, i_item_desc, i_category, i_class,
                     i_current_price) base) x
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
""",
    "q15": """
select ca_zip, sum(cs_sales_price) as sum_sales_price
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
  and (substring(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                   '86475', '85392', '85460', '80348',
                                   '81792')
       or ca_state in ('CA', 'WA', 'GA')
       or cs_sales_price > 500)
group by ca_zip
order by ca_zip
limit 100
""",
    "q37": """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim
where i_current_price between 68 and 98
  and i_manufact_id in (8, 33, 58, 83)
  and inv_item_sk = i_item_sk
  and inv_quantity_on_hand between 100 and 500
  and inv_date_sk = d_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and exists (select * from catalog_sales where cs_item_sk = i_item_sk)
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",
    "q40": """
select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0.0)
                else 0.0 end) as sales_before,
       sum(case when d_date >= date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0.0)
                else 0.0 end) as sales_after
from catalog_sales left join catalog_returns
       on cs_order_number = cr_order_number and cs_item_sk = cr_item_sk,
     warehouse, item, date_dim
where cs_warehouse_sk = w_warehouse_sk and cs_item_sk = i_item_sk
  and i_current_price between 0.99 and 1.49
  and cs_sold_date_sk = d_date_sk
  and datediff(d_date, date '2000-03-11') between -30 and 30
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
""",
    "q45": """
select ca_zip, ca_city, sum(ws_sales_price) as sum_ws_sales_price
from web_sales, customer, customer_address, item, date_dim
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and ws_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
  and (substring(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                   '86475', '85392', '85460', '80348',
                                   '81792')
       or i_item_id in (select i_item_id from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19,
                                            23, 29)))
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
""",
    "q62": """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30 then 1
                else 0 end) as d30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60 then 1
                else 0 end) as d31_60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
                 and ws_ship_date_sk - ws_sold_date_sk <= 90 then 1
                else 0 end) as d61_90,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 90
                 and ws_ship_date_sk - ws_sold_date_sk <= 120 then 1
                else 0 end) as d91_120,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 120 then 1
                else 0 end) as d_over_120
from web_sales, date_dim, warehouse, ship_mode, web_site
where ws_ship_date_sk = d_date_sk
  and d_month_seq between 1200 and 1211
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by substring(w_warehouse_name, 1, 20), sm_type, web_name
order by wname, sm_type, web_name
limit 100
""",
    "q99": """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, cc_name,
       sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30 then 1
                else 0 end) as d30,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
                 and cs_ship_date_sk - cs_sold_date_sk <= 60 then 1
                else 0 end) as d31_60,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
                 and cs_ship_date_sk - cs_sold_date_sk <= 90 then 1
                else 0 end) as d61_90,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 90
                 and cs_ship_date_sk - cs_sold_date_sk <= 120 then 1
                else 0 end) as d91_120,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 120 then 1
                else 0 end) as d_over_120
from catalog_sales, date_dim, warehouse, ship_mode, call_center
where cs_ship_date_sk = d_date_sk
  and d_month_seq between 1200 and 1211
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substring(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
""",
    "q90": """
select amc / pmc as am_pm_ratio
from (select count(*) as amc
      from web_sales, household_demographics, time_dim, web_page
      where ws_ship_hdemo_sk = hd_demo_sk and hd_dep_count = 6
        and ws_sold_time_sk = t_time_sk
        and t_hour between 8 and 9
        and ws_web_page_sk = wp_web_page_sk
        and wp_char_count between 5000 and 5200) at,
     (select count(*) as pmc
      from web_sales, household_demographics, time_dim, web_page
      where ws_ship_hdemo_sk = hd_demo_sk and hd_dep_count = 6
        and ws_sold_time_sk = t_time_sk
        and t_hour between 19 and 20
        and ws_web_page_sk = wp_web_page_sk
        and wp_char_count between 5000 and 5200) pt
""",
    "q93": """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end as act_sales,
             sr_reason_sk
      from store_sales left join store_returns
             on ss_item_sk = sr_item_sk
            and ss_ticket_number = sr_ticket_number) x, reason
where sr_reason_sk = r_reason_sk
  and r_reason_desc = 'Package was damaged'
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
""",
    "q13": """
select avg(ss_quantity) as avg_quantity,
       avg(ss_ext_sales_price) as avg_ext_sales_price,
       avg(ss_ext_wholesale_cost) as avg_ext_wholesale,
       sum(ss_ext_wholesale_cost) as sum_ext_wholesale
from store_sales, store, date_dim, customer_demographics,
     household_demographics, customer_address
where ss_store_sk = s_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ss_cdemo_sk = cd_demo_sk and ss_hdemo_sk = hd_demo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.0 and 150.0 and hd_dep_count = 3)
       or (cd_marital_status = 'S' and cd_education_status = 'College'
           and ss_sales_price between 50.0 and 100.0 and hd_dep_count = 1)
       or (cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
           and ss_sales_price between 150.0 and 200.0 and hd_dep_count = 1))
  and ((ca_country = 'United States' and ca_state in ('TX', 'OH', 'GA')
        and ss_net_profit between 100 and 200)
       or (ca_country = 'United States' and ca_state in ('TN', 'IN', 'SD')
           and ss_net_profit between 150 and 300)
       or (ca_country = 'United States' and ca_state in ('LA', 'MI', 'SC')
           and ss_net_profit between 50 and 250))
""",
    "q17": """
select i_item_id, i_item_desc, s_state,
       count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev(ss_quantity) as store_sales_quantitystdev,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev(sr_return_quantity) as store_returns_quantitystdev,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev(cs_quantity) as catalog_sales_quantitystdev,
       stddev(ss_quantity) / avg(ss_quantity) as store_sales_quantitycov,
       stddev(sr_return_quantity) / avg(sr_return_quantity)
           as store_returns_quantitycov,
       stddev(cs_quantity) / avg(cs_quantity) as catalog_sales_quantitycov
from store_sales, date_dim, item, store, store_returns, catalog_sales
where ss_sold_date_sk = d_date_sk and d_quarter_name = '2001Q1'
  and ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk in
      (select d_date_sk from date_dim
       where d_quarter_name in ('2001Q1', '2001Q2', '2001Q3'))
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk in
      (select d_date_sk from date_dim
       where d_quarter_name in ('2001Q1', '2001Q2', '2001Q3'))
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
""",
    "q28": """
select *
from (select avg(ss_list_price) as b1_lp, count(ss_list_price) as b1_cnt,
             count(distinct ss_list_price) as b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between 8 and 18
             or ss_coupon_amt between 459 and 1459
             or ss_wholesale_cost between 57 and 77)) b1,
     (select avg(ss_list_price) as b2_lp, count(ss_list_price) as b2_cnt,
             count(distinct ss_list_price) as b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between 90 and 100
             or ss_coupon_amt between 2323 and 3323
             or ss_wholesale_cost between 31 and 51)) b2,
     (select avg(ss_list_price) as b3_lp, count(ss_list_price) as b3_cnt,
             count(distinct ss_list_price) as b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between 142 and 152
             or ss_coupon_amt between 12214 and 13214
             or ss_wholesale_cost between 79 and 99)) b3,
     (select avg(ss_list_price) as b4_lp, count(ss_list_price) as b4_cnt,
             count(distinct ss_list_price) as b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between 135 and 145
             or ss_coupon_amt between 6071 and 7071
             or ss_wholesale_cost between 38 and 58)) b4,
     (select avg(ss_list_price) as b5_lp, count(ss_list_price) as b5_cnt,
             count(distinct ss_list_price) as b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between 122 and 132
             or ss_coupon_amt between 836 and 1836
             or ss_wholesale_cost between 17 and 37)) b5,
     (select avg(ss_list_price) as b6_lp, count(ss_list_price) as b6_cnt,
             count(distinct ss_list_price) as b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between 154 and 164
             or ss_coupon_amt between 7326 and 8326
             or ss_wholesale_cost between 7 and 27)) b6
limit 100
""",
    "q33": """
with subset as (
  select distinct i_manufact_id as sub_key from item
  where i_category in ('Electronics')),
dd as (select d_date_sk from date_dim where d_year = 1998 and d_moy = 5),
addr as (select ca_address_sk from customer_address
         where ca_gmt_offset = -5.0),
ss as (
  select i_manufact_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, item
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk in (select d_date_sk from dd)
    and ss_addr_sk in (select ca_address_sk from addr)
    and i_manufact_id in (select sub_key from subset)
  group by i_manufact_id),
cs as (
  select i_manufact_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, item
  where cs_item_sk = i_item_sk
    and cs_sold_date_sk in (select d_date_sk from dd)
    and cs_bill_addr_sk in (select ca_address_sk from addr)
    and i_manufact_id in (select sub_key from subset)
  group by i_manufact_id),
ws as (
  select i_manufact_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, item
  where ws_item_sk = i_item_sk
    and ws_sold_date_sk in (select d_date_sk from dd)
    and ws_bill_addr_sk in (select ca_address_sk from addr)
    and i_manufact_id in (select sub_key from subset)
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) u
group by i_manufact_id
order by total_sales
limit 100
""",
    "q60": """
with subset as (
  select distinct i_item_id as sub_key from item
  where i_category in ('Music')),
dd as (select d_date_sk from date_dim where d_year = 1998 and d_moy = 9),
addr as (select ca_address_sk from customer_address
         where ca_gmt_offset = -5.0),
ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, item
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk in (select d_date_sk from dd)
    and ss_addr_sk in (select ca_address_sk from addr)
    and i_item_id in (select sub_key from subset)
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, item
  where cs_item_sk = i_item_sk
    and cs_sold_date_sk in (select d_date_sk from dd)
    and cs_bill_addr_sk in (select ca_address_sk from addr)
    and i_item_id in (select sub_key from subset)
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, item
  where ws_item_sk = i_item_sk
    and ws_sold_date_sk in (select d_date_sk from dd)
    and ws_bill_addr_sk in (select ca_address_sk from addr)
    and i_item_id in (select sub_key from subset)
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) u
group by i_item_id
order by i_item_id, total_sales
limit 100
""",
    "q86": """
select total_sum, i_category, i_class, lochierarchy, rank_within_parent
from (select total_sum, i_category, i_class, lochierarchy,
             rank() over (partition by lochierarchy, _parent
                          order by total_sum desc) as rank_within_parent
      from (select sum(ws_net_paid) as total_sum, i_category, i_class,
                   (case when i_category is null then 1 else 0 end
                    + case when i_class is null then 1 else 0 end)
                       as lochierarchy,
                   case when i_class is not null then i_category
                        else null end as _parent
            from web_sales, date_dim, item
            where ws_sold_date_sk = d_date_sk
              and d_month_seq between 1200 and 1211
              and ws_item_sk = i_item_sk
            group by rollup(i_category, i_class)) x) y
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category else null end,
         rank_within_parent
limit 100
""",
    "q12": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / sum(itemrevenue)
           over (partition by i_class) as revenueratio
from (select i_item_id, i_item_desc, i_category, i_class, i_current_price,
             sum(ws_ext_sales_price) as itemrevenue
      from web_sales, item, date_dim
      where ws_item_sk = i_item_sk
        and i_category in ('Sports', 'Books', 'Home')
        and ws_sold_date_sk = d_date_sk
        and d_date between date '1999-02-22' and date '1999-03-24'
      group by i_item_id, i_item_desc, i_category, i_class,
               i_current_price) base
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    "q1": """
with customer_total_return as (
  select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
         sum(sr_return_amt) as ctr_total_return
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk and d_year = 2000
  group by sr_customer_sk, sr_store_sk)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk
  and s_state = 'TN'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
""",
    "q2": """
with wscs as (
  select d_week_seq,
         sum(case when d_day_name = 'Sunday' then sales_price else 0.0 end) as sun,
         sum(case when d_day_name = 'Monday' then sales_price else 0.0 end) as mon,
         sum(case when d_day_name = 'Tuesday' then sales_price else 0.0 end) as tue,
         sum(case when d_day_name = 'Wednesday' then sales_price else 0.0 end) as wed,
         sum(case when d_day_name = 'Thursday' then sales_price else 0.0 end) as thu,
         sum(case when d_day_name = 'Friday' then sales_price else 0.0 end) as fri,
         sum(case when d_day_name = 'Saturday' then sales_price else 0.0 end) as sat
  from (select ws_sold_date_sk as sold_date_sk,
               ws_ext_sales_price as sales_price from web_sales
        union all
        select cs_sold_date_sk as sold_date_sk,
               cs_ext_sales_price as sales_price from catalog_sales) x,
       date_dim
  where sold_date_sk = d_date_sk
  group by d_week_seq),
y as (
  select d_week_seq as wk1, sun as sun1, mon as mon1, tue as tue1,
         wed as wed1, thu as thu1, fri as fri1, sat as sat1
  from wscs
  where d_week_seq in (select distinct d_week_seq from date_dim
                       where d_year = 1999)),
z as (
  select d_week_seq - 53 as wk2, sun as sun2, mon as mon2, tue as tue2,
         wed as wed2, thu as thu2, fri as fri2, sat as sat2
  from wscs
  where d_week_seq in (select distinct d_week_seq from date_dim
                       where d_year = 2000))
select wk1 as d_week_seq,
       round(case when sun2 <> 0 then sun1 / sun2 else null end, 2) as r_sun,
       round(case when mon2 <> 0 then mon1 / mon2 else null end, 2) as r_mon,
       round(case when tue2 <> 0 then tue1 / tue2 else null end, 2) as r_tue,
       round(case when wed2 <> 0 then wed1 / wed2 else null end, 2) as r_wed,
       round(case when thu2 <> 0 then thu1 / thu2 else null end, 2) as r_thu,
       round(case when fri2 <> 0 then fri1 / fri2 else null end, 2) as r_fri,
       round(case when sat2 <> 0 then sat1 / sat2 else null end, 2) as r_sat
from y, z
where wk1 = wk2
order by d_week_seq
""",
    "q4": """
with s1 as (
  select c_customer_id as s1_id,
         sum((ss_ext_list_price - ss_ext_wholesale_cost - ss_ext_discount_amt
              + ss_ext_sales_price) / 2) as s1_total,
         first(c_preferred_cust_flag) as s1_flag
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and d_year = 1999
    and ss_customer_sk = c_customer_sk
  group by c_customer_id),
s2 as (
  select c_customer_id as s2_id,
         sum((ss_ext_list_price - ss_ext_wholesale_cost - ss_ext_discount_amt
              + ss_ext_sales_price) / 2) as s2_total,
         first(c_preferred_cust_flag) as s2_flag
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and d_year = 2000
    and ss_customer_sk = c_customer_sk
  group by c_customer_id),
c1 as (
  select c_customer_id as c1_id,
         sum((cs_ext_list_price - cs_ext_wholesale_cost - cs_ext_discount_amt
              + cs_ext_sales_price) / 2) as c1_total,
         first(c_preferred_cust_flag) as c1_flag
  from catalog_sales, date_dim, customer
  where cs_sold_date_sk = d_date_sk and d_year = 1999
    and cs_bill_customer_sk = c_customer_sk
  group by c_customer_id),
c2 as (
  select c_customer_id as c2_id,
         sum((cs_ext_list_price - cs_ext_wholesale_cost - cs_ext_discount_amt
              + cs_ext_sales_price) / 2) as c2_total,
         first(c_preferred_cust_flag) as c2_flag
  from catalog_sales, date_dim, customer
  where cs_sold_date_sk = d_date_sk and d_year = 2000
    and cs_bill_customer_sk = c_customer_sk
  group by c_customer_id),
w1 as (
  select c_customer_id as w1_id,
         sum((ws_ext_list_price - ws_ext_wholesale_cost - ws_ext_discount_amt
              + ws_ext_sales_price) / 2) as w1_total,
         first(c_preferred_cust_flag) as w1_flag
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and d_year = 1999
    and ws_bill_customer_sk = c_customer_sk
  group by c_customer_id),
w2 as (
  select c_customer_id as w2_id,
         sum((ws_ext_list_price - ws_ext_wholesale_cost - ws_ext_discount_amt
              + ws_ext_sales_price) / 2) as w2_total,
         first(c_preferred_cust_flag) as w2_flag
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and d_year = 2000
    and ws_bill_customer_sk = c_customer_sk
  group by c_customer_id)
select s1_id as customer_id, s2_flag as customer_preferred_cust_flag
from s1, s2, c1, c2, w1, w2
where s1_total > 0 and s1_id = s2_id
  and c1_total > 0 and s1_id = c1_id and s1_id = c2_id
  and w1_total > 0 and s1_id = w1_id and s1_id = w2_id
  and c2_total / c1_total > s2_total / s1_total
  and c2_total / c1_total > w2_total / w1_total
order by customer_id
limit 100
""",
    "q74": """
with s1 as (
  select c_customer_id as s1_id, sum(ss_net_paid) as s1_total,
         first(c_preferred_cust_flag) as s1_flag
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and d_year = 1999
    and ss_customer_sk = c_customer_sk
  group by c_customer_id),
s2 as (
  select c_customer_id as s2_id, sum(ss_net_paid) as s2_total,
         first(c_preferred_cust_flag) as s2_flag
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and d_year = 2000
    and ss_customer_sk = c_customer_sk
  group by c_customer_id),
w1 as (
  select c_customer_id as w1_id, sum(ws_net_paid) as w1_total,
         first(c_preferred_cust_flag) as w1_flag
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and d_year = 1999
    and ws_bill_customer_sk = c_customer_sk
  group by c_customer_id),
w2 as (
  select c_customer_id as w2_id, sum(ws_net_paid) as w2_total,
         first(c_preferred_cust_flag) as w2_flag
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and d_year = 2000
    and ws_bill_customer_sk = c_customer_sk
  group by c_customer_id)
select s1_id as customer_id
from s1, s2, w1, w2
where s1_total > 0 and s1_id = s2_id
  and w1_total > 0 and s1_id = w1_id and s1_id = w2_id
  and w2_total / w1_total > s2_total / s1_total
order by customer_id
limit 100
""",
    "q5": """
with ssr as (
  select s.sid, s.sales, coalesce(r.returns_amt, 0.0) as returns_amt,
         s.profit - coalesce(r.net_loss, 0.0) as profit
  from (select ss_store_sk as sid, sum(ss_ext_sales_price) as sales,
               sum(ss_net_profit) as profit
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk
          and d_date between date '2000-08-01' and date '2000-08-14'
        group by ss_store_sk) s
  left join (select sr_store_sk as sid_r, sum(sr_return_amt) as returns_amt,
                    sum(sr_net_loss) as net_loss
             from store_returns, date_dim
             where sr_returned_date_sk = d_date_sk
               and d_date between date '2000-08-01' and date '2000-08-14'
             group by sr_store_sk) r
  on s.sid = r.sid_r),
csr as (
  select s.sid, s.sales, coalesce(r.returns_amt, 0.0) as returns_amt,
         s.profit - coalesce(r.net_loss, 0.0) as profit
  from (select cs_catalog_page_sk as sid, sum(cs_ext_sales_price) as sales,
               sum(cs_net_profit) as profit
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk
          and d_date between date '2000-08-01' and date '2000-08-14'
        group by cs_catalog_page_sk) s
  left join (select cr_catalog_page_sk as sid_r,
                    sum(cr_return_amount) as returns_amt,
                    sum(cr_net_loss) as net_loss
             from catalog_returns, date_dim
             where cr_returned_date_sk = d_date_sk
               and d_date between date '2000-08-01' and date '2000-08-14'
             group by cr_catalog_page_sk) r
  on s.sid = r.sid_r),
wsr as (
  select s.sid, s.sales, coalesce(r.returns_amt, 0.0) as returns_amt,
         s.profit - coalesce(r.net_loss, 0.0) as profit
  from (select ws_web_site_sk as sid, sum(ws_ext_sales_price) as sales,
               sum(ws_net_profit) as profit
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk
          and d_date between date '2000-08-01' and date '2000-08-14'
        group by ws_web_site_sk) s
  left join (select wr_web_page_sk as sid_r, sum(wr_return_amt) as returns_amt,
                    sum(wr_net_loss) as net_loss
             from web_returns, date_dim
             where wr_returned_date_sk = d_date_sk
               and d_date between date '2000-08-01' and date '2000-08-14'
             group by wr_web_page_sk) r
  on s.sid = r.sid_r)
select channel, sid, sum(sales) as sales, sum(returns_amt) as returns_amt,
       sum(profit) as profit
from (select 'store channel' as channel, sid, sales, returns_amt, profit
      from ssr
      union all
      select 'catalog channel' as channel, sid, sales, returns_amt, profit
      from csr
      union all
      select 'web channel' as channel, sid, sales, returns_amt, profit
      from wsr) x
group by rollup(channel, sid)
order by channel, sid
limit 100
""",
    "q6": """
select ca_state as state, count(*) as cnt
from store_sales, date_dim, customer, customer_address
where ss_sold_date_sk = d_date_sk
  and d_month_seq in (select distinct d_month_seq from date_dim
                      where d_year = 2001 and d_moy = 1)
  and ss_item_sk in (
    select i_item_sk
    from item, (select i_category as cat, avg(i_current_price) as cat_avg
                from item group by i_category) j
    where i_category = cat and i_current_price > 1.2 * cat_avg)
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
group by ca_state
having count(*) >= 10
order by cnt
limit 100
""",
    "q8": """
select s_store_name, sum(ss_net_profit) as net_profit
from store_sales, date_dim, store
where ss_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 1998
  and ss_store_sk = s_store_sk
  and substring(s_zip, 1, 5) in (
    select substring(ca_zip, 1, 5) as zip5
    from customer, customer_address
    where c_preferred_cust_flag = 'Y'
      and c_current_addr_sk = ca_address_sk
    group by substring(ca_zip, 1, 5)
    having count(*) > 10)
group by s_store_name
order by s_store_name
""",
    "q9": """
select case when cnt1 > 62316.685 then disc1 else paid1 end as bucket1,
       case when cnt2 > 62316.685 then disc2 else paid2 end as bucket2,
       case when cnt3 > 62316.685 then disc3 else paid3 end as bucket3,
       case when cnt4 > 62316.685 then disc4 else paid4 end as bucket4,
       case when cnt5 > 62316.685 then disc5 else paid5 end as bucket5
from reason,
     (select
        sum(case when ss_quantity between 1 and 20 then 1 else 0 end) as cnt1,
        avg(case when ss_quantity between 1 and 20
            then ss_ext_discount_amt else null end) as disc1,
        avg(case when ss_quantity between 1 and 20
            then ss_net_paid else null end) as paid1,
        sum(case when ss_quantity between 21 and 40 then 1 else 0 end) as cnt2,
        avg(case when ss_quantity between 21 and 40
            then ss_ext_discount_amt else null end) as disc2,
        avg(case when ss_quantity between 21 and 40
            then ss_net_paid else null end) as paid2,
        sum(case when ss_quantity between 41 and 60 then 1 else 0 end) as cnt3,
        avg(case when ss_quantity between 41 and 60
            then ss_ext_discount_amt else null end) as disc3,
        avg(case when ss_quantity between 41 and 60
            then ss_net_paid else null end) as paid3,
        sum(case when ss_quantity between 61 and 80 then 1 else 0 end) as cnt4,
        avg(case when ss_quantity between 61 and 80
            then ss_ext_discount_amt else null end) as disc4,
        avg(case when ss_quantity between 61 and 80
            then ss_net_paid else null end) as paid4,
        sum(case when ss_quantity between 81 and 100 then 1 else 0 end) as cnt5,
        avg(case when ss_quantity between 81 and 100
            then ss_ext_discount_amt else null end) as disc5,
        avg(case when ss_quantity between 81 and 100
            then ss_net_paid else null end) as paid5
      from store_sales) stats
where r_reason_sk = 1
""",
    "q10": """
select cd_gender, cd_marital_status, cd_education_status,
       cd_purchase_estimate, cd_credit_rating, count(*) as cnt
from customer, customer_address, customer_demographics
where c_current_addr_sk = ca_address_sk
  and ca_county in ('Williamson County', 'Walker County', 'Ziebach County')
  and c_customer_sk in (
    select ss_customer_sk from store_sales, date_dim
    where ss_sold_date_sk = d_date_sk
      and d_year = 2002 and d_moy between 1 and 4)
  and (c_customer_sk in (
         select ws_bill_customer_sk from web_sales, date_dim
         where ws_sold_date_sk = d_date_sk
           and d_year = 2002 and d_moy between 1 and 4)
       or c_customer_sk in (
         select cs_bill_customer_sk from catalog_sales, date_dim
         where cs_sold_date_sk = d_date_sk
           and d_year = 2002 and d_moy between 1 and 4))
  and c_current_cdemo_sk = cd_demo_sk
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
""",
    "q11": """
with s1 as (
  select c_customer_id as s1_id,
         sum(ss_ext_list_price - ss_ext_discount_amt) as s1_total,
         first(c_preferred_cust_flag) as s1_flag
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and d_year = 1999
    and ss_customer_sk = c_customer_sk
  group by c_customer_id),
s2 as (
  select c_customer_id as s2_id,
         sum(ss_ext_list_price - ss_ext_discount_amt) as s2_total,
         first(c_preferred_cust_flag) as s2_flag
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and d_year = 2000
    and ss_customer_sk = c_customer_sk
  group by c_customer_id),
w1 as (
  select c_customer_id as w1_id,
         sum(ws_ext_list_price - ws_ext_discount_amt) as w1_total,
         first(c_preferred_cust_flag) as w1_flag
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and d_year = 1999
    and ws_bill_customer_sk = c_customer_sk
  group by c_customer_id),
w2 as (
  select c_customer_id as w2_id,
         sum(ws_ext_list_price - ws_ext_discount_amt) as w2_total,
         first(c_preferred_cust_flag) as w2_flag
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and d_year = 2000
    and ws_bill_customer_sk = c_customer_sk
  group by c_customer_id)
select s1_id as customer_id, s2_flag as customer_preferred_cust_flag
from s1, s2, w1, w2
where s1_total > 0 and s1_id = s2_id
  and w1_total > 0 and s1_id = w1_id and s1_id = w2_id
  and w2_total / w1_total > s2_total / s1_total
order by customer_id
limit 100
""",
    "q18": """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cs_quantity) as agg1, avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3, avg(cs_sales_price) as agg4,
       avg(cs_net_profit) as agg5, avg(c_birth_year) as agg6,
       avg(cd1_dep_count) as agg7
from catalog_sales, date_dim, item,
     (select cd_demo_sk as cd1_sk, cd_dep_count as cd1_dep_count
      from customer_demographics
      where cd_gender = 'F' and cd_education_status = 'Unknown') cd1,
     customer,
     (select cd_demo_sk as cd2_sk from customer_demographics) cd2,
     customer_address
where cs_sold_date_sk = d_date_sk and d_year = 1998
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1_sk
  and cs_bill_customer_sk = c_customer_sk
  and c_birth_month in (1, 6, 8, 9, 12, 2)
  and c_current_cdemo_sk = cd2_sk
  and c_current_addr_sk = ca_address_sk
  and ca_state in ('TN', 'IN', 'SD', 'OH', 'TX', 'GA')
group by rollup(i_item_id, ca_country, ca_state, ca_county)
order by ca_country, ca_state, ca_county, i_item_id
limit 100
""",
    "q22": """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) as qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and d_month_seq between 1200 and 1211
  and inv_item_sk = i_item_sk
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 100
""",
    "q23": """
with freq as (
  select item_sk from (
    select ss_item_sk as item_sk, count(distinct d_date_sk) as cnt
    from store_sales, date_dim
    where ss_sold_date_sk = d_date_sk
      and d_year in (1998, 1999, 2000, 2001)
    group by ss_item_sk) f
  where cnt > 4),
totals as (
  select ss_customer_sk as csk,
         sum(ss_quantity * ss_sales_price) as csales
  from store_sales
  group by ss_customer_sk),
best as (
  select csk from totals,
       (select max(csales) as tpcds_cmax from totals) m
  where csales > 0.5 * tpcds_cmax)
select sum(v) as total
from (select cs_quantity * cs_list_price as v
      from catalog_sales
      where cs_sold_date_sk in (select d_date_sk from date_dim
                                where d_year = 2000 and d_moy = 2)
        and cs_item_sk in (select item_sk from freq)
        and cs_bill_customer_sk in (select csk from best)
      union all
      select ws_quantity * ws_list_price as v
      from web_sales
      where ws_sold_date_sk in (select d_date_sk from date_dim
                                where d_year = 2000 and d_moy = 2)
        and ws_item_sk in (select item_sk from freq)
        and ws_bill_customer_sk in (select csk from best)) x
""",
    "q24": """
with ssales as (
  select c_last_name, c_first_name, s_store_name, i_color,
         sum(ss_net_paid) as netpaid
  from store_sales, store_returns, store, item, customer
  where ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
    and ss_store_sk = s_store_sk and ss_item_sk = i_item_sk
    and ss_customer_sk = c_customer_sk
  group by c_last_name, c_first_name, s_store_name, i_color)
select c_last_name, c_first_name, s_store_name, netpaid
from ssales, (select avg(netpaid) * 0.05 as thr from ssales) a
where i_color = 'blue' and netpaid > thr
order by c_last_name, c_first_name, s_store_name
""",
    "q27": """
select i_item_id, s_state,
       avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, date_dim, store, customer_demographics, item
where ss_sold_date_sk = d_date_sk and d_year = 2002
  and ss_store_sk = s_store_sk and s_state in ('TN', 'GA', 'SD')
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and ss_item_sk = i_item_sk
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 100
""",
    "q30": """
with ctr as (
  select wr_returning_customer_sk as ctr_cust, ca_state as ctr_state,
         sum(wr_return_amt) as ctr_total
  from web_returns, date_dim, customer, customer_address
  where wr_returned_date_sk = d_date_sk and d_year = 2000
    and wr_returning_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
  group by wr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name, ctr_total
from ctr ctr1, customer
where ctr1.ctr_total > (select avg(ctr_total) * 1.2 from ctr ctr2
                        where ctr1.ctr_state = ctr2.ctr_state)
  and ctr1.ctr_cust = c_customer_sk
  and c_current_addr_sk in (select ca_address_sk from customer_address
                            where ca_state = 'GA')
order by c_customer_id, c_salutation, c_first_name, c_last_name, ctr_total
""",
    "q31": """
with ss1 as (
  select ca_county as ss1_county, sum(ss_ext_sales_price) as ss1_sales
  from store_sales, date_dim, customer_address
  where ss_sold_date_sk = d_date_sk and d_year = 2000 and d_qoy = 1
    and ss_addr_sk = ca_address_sk
  group by ca_county),
ss2 as (
  select ca_county as ss2_county, sum(ss_ext_sales_price) as ss2_sales
  from store_sales, date_dim, customer_address
  where ss_sold_date_sk = d_date_sk and d_year = 2000 and d_qoy = 2
    and ss_addr_sk = ca_address_sk
  group by ca_county),
ws1 as (
  select ca_county as ws1_county, sum(ws_ext_sales_price) as ws1_sales
  from web_sales, date_dim, customer_address
  where ws_sold_date_sk = d_date_sk and d_year = 2000 and d_qoy = 1
    and ws_bill_addr_sk = ca_address_sk
  group by ca_county),
ws2 as (
  select ca_county as ws2_county, sum(ws_ext_sales_price) as ws2_sales
  from web_sales, date_dim, customer_address
  where ws_sold_date_sk = d_date_sk and d_year = 2000 and d_qoy = 2
    and ws_bill_addr_sk = ca_address_sk
  group by ca_county)
select ss1_county as county, ws2_sales / ws1_sales as web_g,
       ss2_sales / ss1_sales as store_g
from ss1, ss2, ws1, ws2
where ss1_county = ss2_county and ss1_county = ws1_county
  and ss1_county = ws2_county
  and ws1_sales > 0 and ss1_sales > 0
  and ws2_sales / ws1_sales > ss2_sales / ss1_sales
order by county
""",
    "q35": """
select ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) as cnt, min(cd_dep_count) as mn, max(cd_dep_count) as mx,
       avg(cd_dep_count) as av
from customer, customer_address, customer_demographics
where c_customer_sk in (
    select ss_customer_sk from store_sales, date_dim
    where ss_sold_date_sk = d_date_sk and d_year = 2002 and d_qoy < 4)
  and (c_customer_sk in (
         select ws_bill_customer_sk from web_sales, date_dim
         where ws_sold_date_sk = d_date_sk and d_year = 2002 and d_qoy < 4)
       or c_customer_sk in (
         select cs_bill_customer_sk from catalog_sales, date_dim
         where cs_sold_date_sk = d_date_sk and d_year = 2002 and d_qoy < 4))
  and c_current_addr_sk = ca_address_sk
  and c_current_cdemo_sk = cd_demo_sk
group by ca_state, cd_gender, cd_marital_status, cd_dep_count
order by ca_state, cd_gender, cd_marital_status, cd_dep_count
limit 100
""",
    "q38": """
select count(*) as cnt
from (select distinct c_last_name, c_first_name
      from store_sales, customer
      where ss_sold_date_sk in (select d_date_sk from date_dim
                                where d_month_seq between 1200 and 1211)
        and ss_customer_sk = c_customer_sk) s
     left semi join
     (select distinct c_last_name as cl, c_first_name as cf
      from catalog_sales, customer
      where cs_sold_date_sk in (select d_date_sk from date_dim
                                where d_month_seq between 1200 and 1211)
        and cs_bill_customer_sk = c_customer_sk) c
     on c_last_name = cl and c_first_name = cf
     left semi join
     (select distinct c_last_name as wl, c_first_name as wf
      from web_sales, customer
      where ws_sold_date_sk in (select d_date_sk from date_dim
                                where d_month_seq between 1200 and 1211)
        and ws_bill_customer_sk = c_customer_sk) w
     on c_last_name = wl and c_first_name = wf
""",
    "q39": """
with inv as (
  select w_warehouse_sk, i_item_sk, d_moy,
         stddev(inv_quantity_on_hand) / avg(inv_quantity_on_hand) as cov,
         avg(inv_quantity_on_hand) as mean
  from inventory, date_dim, item, warehouse
  where inv_date_sk = d_date_sk and d_year = 2001 and d_moy in (1, 2)
    and inv_item_sk = i_item_sk
    and inv_warehouse_sk = w_warehouse_sk
  group by w_warehouse_sk, i_item_sk, d_moy),
qualified as (
  select w_warehouse_sk, i_item_sk, d_moy, mean, cov
  from inv
  where mean <> 0 and cov > 1.0)
select a.w1 as w1, a.i1 as i1, a.mean1 as mean1, a.cov1 as cov1,
       b.mean2 as mean2, b.cov2 as cov2
from (select w_warehouse_sk as w1, i_item_sk as i1, mean as mean1,
             cov as cov1 from qualified where d_moy = 1) a,
     (select w_warehouse_sk as w2, i_item_sk as i2, mean as mean2,
             cov as cov2 from qualified where d_moy = 2) b
where a.w1 = b.w2 and a.i1 = b.i2
order by w1, i1
""",
    "q41": """
select distinct i_product_name
from item
where i_manufact_id between 38 and 78
  and i_manufact in (
    select i_manufact from item
    where (i_category = 'Women' and i_color in ('powder', 'khaki')
           and i_units in ('Ounce', 'Oz')
           and i_size in ('medium', 'extra large'))
       or (i_category = 'Women' and i_color in ('brown', 'honeydew')
           and i_units in ('Bunch', 'Ton') and i_size in ('N/A', 'small'))
       or (i_category = 'Men' and i_color in ('floral', 'deep')
           and i_units in ('N/A', 'Dozen') and i_size in ('petite', 'large'))
       or (i_category = 'Men' and i_color in ('light', 'cornflower')
           and i_units in ('Box', 'Pound')
           and i_size in ('medium', 'extra large'))
       or (i_category = 'Women' and i_color in ('midnight', 'snow')
           and i_units in ('Pallet', 'Gross')
           and i_size in ('medium', 'extra large'))
       or (i_category = 'Women' and i_color in ('cyan', 'papaya')
           and i_units in ('Cup', 'Dram') and i_size in ('N/A', 'small'))
       or (i_category = 'Men' and i_color in ('orange', 'frosted')
           and i_units in ('Each', 'Tbl') and i_size in ('petite', 'large'))
       or (i_category = 'Men' and i_color in ('forest', 'ghost')
           and i_units in ('Lb', 'Bundle')
           and i_size in ('medium', 'extra large')))
order by i_product_name
limit 100
""",
    "q44": """
with qualified as (
  select item_sk, rank_col
  from (select ss_item_sk as item_sk, avg(ss_net_profit) as rank_col
        from store_sales where ss_store_sk = 4 group by ss_item_sk) base,
       (select f_avg * 0.9 as floor_val
        from (select avg(ss_net_profit) as f_avg
              from store_sales
              where ss_store_sk = 4 and ss_addr_sk is null
              group by ss_store_sk) f) flr
  where rank_col > floor_val),
asc_r as (
  select item_sk, rank() over (order by rank_col asc) as rnk
  from qualified),
desc_r as (
  select item_sk as item_sk_d, rank() over (order by rank_col desc) as rnk_d
  from qualified)
select rnk, i1.i_product_name as best_performing,
       i2.i_product_name as worst_performing
from asc_r, desc_r, item i1, item i2
where rnk < 11 and rnk_d < 11 and rnk = rnk_d
  and item_sk = i1.i_item_sk and item_sk_d = i2.i_item_sk
order by rnk
limit 100
""",
    "q47": """
with base as (
  select i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum(ss_sales_price) as sum_sales
  from store_sales, item, date_dim, store
  where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and (d_year = 1999 or (d_year = 1998 and d_moy = 12)
         or (d_year = 2000 and d_moy = 1))
    and ss_store_sk = s_store_sk
  group by i_category, i_brand, s_store_name, s_company_name, d_year, d_moy),
v1 as (
  select i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum_sales,
         avg(sum_sales) over (partition by i_category, i_brand, s_store_name,
                              s_company_name, d_year) as avg_monthly_sales,
         rank() over (partition by i_category, i_brand, s_store_name,
                      s_company_name
                      order by d_year, d_moy) as rn
  from base)
select v1.i_category as i_category, v1.i_brand as i_brand,
       v1.s_store_name as s_store_name, v1.s_company_name as s_company_name,
       v1.d_year as d_year, v1.d_moy as d_moy,
       v1.avg_monthly_sales as avg_monthly_sales, v1.sum_sales as sum_sales,
       v1_lag.sum_sales as psum, v1_lead.sum_sales as nsum
from v1, v1 v1_lag, v1 v1_lead
where v1.i_category = v1_lag.i_category and v1.i_brand = v1_lag.i_brand
  and v1.s_store_name = v1_lag.s_store_name
  and v1.s_company_name = v1_lag.s_company_name
  and v1.rn = v1_lag.rn + 1
  and v1.i_category = v1_lead.i_category and v1.i_brand = v1_lead.i_brand
  and v1.s_store_name = v1_lead.s_store_name
  and v1.s_company_name = v1_lead.s_company_name
  and v1.rn = v1_lead.rn - 1
  and v1.d_year = 1999
  and v1.avg_monthly_sales > 0
  and case when v1.avg_monthly_sales > 0
      then abs(v1.sum_sales - v1.avg_monthly_sales) / v1.avg_monthly_sales
      else null end > 0.1
order by v1.sum_sales - v1.avg_monthly_sales, s_store_name
limit 100
""",
    "q48": """
select sum(ss_quantity) as sum_quantity
from store_sales, store, date_dim, customer_demographics, customer_address
where ss_store_sk = s_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = 2000
  and ss_cdemo_sk = cd_demo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M' and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.0 and 150.0)
       or (cd_marital_status = 'D' and cd_education_status = '2 yr Degree'
           and ss_sales_price between 50.0 and 100.0)
       or (cd_marital_status = 'S' and cd_education_status = 'College'
           and ss_sales_price between 150.0 and 200.0))
  and ((ca_country = 'United States' and ca_state in ('TX', 'OH', 'GA')
        and ss_net_profit between 0 and 2000)
       or (ca_country = 'United States' and ca_state in ('TN', 'IN', 'SD')
           and ss_net_profit between 150 and 3000)
       or (ca_country = 'United States' and ca_state in ('LA', 'MI', 'CA')
           and ss_net_profit between 50 and 25000))
""",
    "q50": """
select s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30
           then 1 else 0 end) as d30,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
                and sr_returned_date_sk - ss_sold_date_sk <= 60
           then 1 else 0 end) as d31_60,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 60
                and sr_returned_date_sk - ss_sold_date_sk <= 90
           then 1 else 0 end) as d61_90,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 90
                and sr_returned_date_sk - ss_sold_date_sk <= 120
           then 1 else 0 end) as d91_120,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 120
           then 1 else 0 end) as d_over_120
from store_sales, store_returns, date_dim, store
where ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
  and ss_customer_sk = sr_customer_sk
  and sr_returned_date_sk = d_date_sk and d_year = 2001 and d_moy = 8
  and ss_store_sk = s_store_sk
group by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
order by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
limit 100
""",
    "q53": """
with base as (
  select i_manufact_id, d_qoy, sum(ss_sales_price) as sum_sales
  from store_sales, item, date_dim, store
  where ss_item_sk = i_item_sk
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('personal', 'portable', 'reference', 'self-help')
          and i_brand in ('scholaramalgamalg #14', 'scholaramalgamalg #7',
                          'exportiunivamalg #9', 'scholaramalgamalg #9'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('accessories', 'classical', 'fragrances',
                             'pants')
             and i_brand in ('amalgimporto #1', 'edu packscholar #1',
                             'exportiimporto #1', 'importoamalg #1')))
    and ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
    and ss_store_sk = s_store_sk
  group by i_manufact_id, d_qoy)
select i_manufact_id, sum_sales, avg_quarterly_sales
from (select i_manufact_id, sum_sales,
             avg(sum_sales) over (partition by i_manufact_id)
               as avg_quarterly_sales
      from base) tmp
where case when avg_quarterly_sales > 0
      then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
""",
    "q63": """
with base as (
  select i_manager_id, d_moy, sum(ss_sales_price) as sum_sales
  from store_sales, item, date_dim, store
  where ss_item_sk = i_item_sk
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('personal', 'portable', 'reference', 'self-help')
          and i_brand in ('scholaramalgamalg #14', 'scholaramalgamalg #7',
                          'exportiunivamalg #9', 'scholaramalgamalg #9'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('accessories', 'classical', 'fragrances',
                             'pants')
             and i_brand in ('amalgimporto #1', 'edu packscholar #1',
                             'exportiimporto #1', 'importoamalg #1')))
    and ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
    and ss_store_sk = s_store_sk
  group by i_manager_id, d_moy)
select i_manager_id, sum_sales, avg_monthly_sales
from (select i_manager_id, sum_sales,
             avg(sum_sales) over (partition by i_manager_id)
               as avg_monthly_sales
      from base) tmp
where case when avg_monthly_sales > 0
      then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
""",
    "q54": """
with my_customers as (
  select distinct cust
  from (select cs_sold_date_sk as sold, cs_item_sk as item,
               cs_bill_customer_sk as cust from catalog_sales
        union all
        select ws_sold_date_sk as sold, ws_item_sk as item,
               ws_bill_customer_sk as cust from web_sales) u
  where sold in (select d_date_sk from date_dim
                 where d_year = 1999 and d_moy = 5)
    and item in (select i_item_sk from item
                 where i_category = 'Women' and i_class = 'dresses')),
rev as (
  select ss_customer_sk as c, sum(ss_ext_sales_price) as revenue
  from store_sales
  where ss_customer_sk in (select cust from my_customers)
    and ss_sold_date_sk in (select d_date_sk from date_dim
                            where d_year = 1999 and d_moy in (6, 7, 8))
  group by ss_customer_sk)
select segment, count(*) as num_customers, segment * 50 as segment_base
from (select cast(floor(revenue / 50) as int) as segment from rev) seg
group by segment
order by segment, num_customers
limit 100
""",
    "q56": """
with ids as (
  select distinct i_item_id as f_item_id from item
  where i_color in ('blue', 'cyan', 'green')),
ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, date_dim, item
  where ss_sold_date_sk = d_date_sk and d_year = 2001 and d_moy in (2)
    and ss_item_sk = i_item_sk
    and i_item_id in (select f_item_id from ids)
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, date_dim, item
  where cs_sold_date_sk = d_date_sk and d_year = 2001 and d_moy in (2)
    and cs_item_sk = i_item_sk
    and i_item_id in (select f_item_id from ids)
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, date_dim, item
  where ws_sold_date_sk = d_date_sk and d_year = 2001 and d_moy in (2)
    and ws_item_sk = i_item_sk
    and i_item_id in (select f_item_id from ids)
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) x
group by i_item_id
order by total_sales, i_item_id
limit 100
""",
    "q57": """
with base as (
  select i_category, i_brand, cc_name, d_year, d_moy,
         sum(cs_sales_price) as sum_sales
  from catalog_sales, item, date_dim, call_center
  where cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and (d_year = 1999 or (d_year = 1998 and d_moy = 12)
         or (d_year = 2000 and d_moy = 1))
    and cs_call_center_sk = cc_call_center_sk
  group by i_category, i_brand, cc_name, d_year, d_moy),
v1 as (
  select i_category, i_brand, cc_name, d_year, d_moy, sum_sales,
         avg(sum_sales) over (partition by i_category, i_brand, cc_name,
                              d_year) as avg_monthly_sales,
         rank() over (partition by i_category, i_brand, cc_name
                      order by d_year, d_moy) as rn
  from base)
select v1.i_category as i_category, v1.i_brand as i_brand,
       v1.cc_name as cc_name, v1.d_year as d_year, v1.d_moy as d_moy,
       v1.avg_monthly_sales as avg_monthly_sales, v1.sum_sales as sum_sales,
       v1_lag.sum_sales as psum, v1_lead.sum_sales as nsum
from v1, v1 v1_lag, v1 v1_lead
where v1.i_category = v1_lag.i_category and v1.i_brand = v1_lag.i_brand
  and v1.cc_name = v1_lag.cc_name and v1.rn = v1_lag.rn + 1
  and v1.i_category = v1_lead.i_category and v1.i_brand = v1_lead.i_brand
  and v1.cc_name = v1_lead.cc_name and v1.rn = v1_lead.rn - 1
  and v1.d_year = 1999
  and v1.avg_monthly_sales > 0
  and case when v1.avg_monthly_sales > 0
      then abs(v1.sum_sales - v1.avg_monthly_sales) / v1.avg_monthly_sales
      else null end > 0.1
order by v1.sum_sales - v1.avg_monthly_sales, cc_name
limit 100
""",
    "q58": """
with dates as (
  select d_date_sk from date_dim
  where d_week_seq in (select d_week_seq from date_dim
                       where d_date = date '2000-01-03')),
ss_items as (
  select i_item_id as ss_item_id, sum(ss_ext_sales_price) as ss_rev
  from store_sales, item
  where ss_sold_date_sk in (select d_date_sk from dates)
    and ss_item_sk = i_item_sk
  group by i_item_id),
cs_items as (
  select i_item_id as cs_item_id, sum(cs_ext_sales_price) as cs_rev
  from catalog_sales, item
  where cs_sold_date_sk in (select d_date_sk from dates)
    and cs_item_sk = i_item_sk
  group by i_item_id),
ws_items as (
  select i_item_id as ws_item_id, sum(ws_ext_sales_price) as ws_rev
  from web_sales, item
  where ws_sold_date_sk in (select d_date_sk from dates)
    and ws_item_sk = i_item_sk
  group by i_item_id)
select ss_item_id as item_id, ss_rev, cs_rev, ws_rev
from ss_items, cs_items, ws_items
where ss_item_id = cs_item_id and ss_item_id = ws_item_id
  and ss_rev between 0.9 * cs_rev and 1.1 * cs_rev
  and ss_rev between 0.9 * ws_rev and 1.1 * ws_rev
  and cs_rev between 0.9 * ss_rev and 1.1 * ss_rev
  and cs_rev between 0.9 * ws_rev and 1.1 * ws_rev
  and ws_rev between 0.9 * ss_rev and 1.1 * ss_rev
  and ws_rev between 0.9 * cs_rev and 1.1 * cs_rev
order by item_id, ss_rev
limit 100
""",
    "q61": """
select promotions, total, promotions / total * 100.0 as promo_pct
from (select sum(ss_ext_sales_price) as promotions
      from store_sales, date_dim, store, customer, customer_address, item,
           promotion
      where ss_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 11
        and ss_store_sk = s_store_sk and s_gmt_offset = -5.0
        and ss_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk and ca_gmt_offset = -5.0
        and ss_item_sk = i_item_sk and i_category = 'Jewelry'
        and ss_promo_sk = p_promo_sk
        and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
             or p_channel_tv = 'Y')) p,
     (select sum(ss_ext_sales_price) as total
      from store_sales, date_dim, store, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 11
        and ss_store_sk = s_store_sk and s_gmt_offset = -5.0
        and ss_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk and ca_gmt_offset = -5.0
        and ss_item_sk = i_item_sk and i_category = 'Jewelry') t
""",
    "q64": """
with cs_ui as (
  select ui_item from (
    select cs_item_sk as ui_item, sum(cs_ext_list_price) as sale,
           sum(cr_refunded_cash + cr_fee) as refund
    from catalog_sales, catalog_returns
    where cs_item_sk = cr_item_sk and cs_order_number = cr_order_number
    group by cs_item_sk) u
  where sale > 2 * refund),
y1 as (
  select i_product_name as y1_pn, s_store_name as y1_sn, s_zip as y1_zip,
         count(*) as y1_cnt, sum(ss_wholesale_cost) as y1_s1,
         sum(ss_list_price) as y1_s2, sum(ss_coupon_amt) as y1_s3
  from store_sales, store_returns, date_dim, store, item
  where ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number
    and ss_item_sk in (select ui_item from cs_ui)
    and ss_sold_date_sk = d_date_sk and d_year = 1999
    and ss_store_sk = s_store_sk
    and ss_item_sk = i_item_sk and i_current_price is not null
  group by i_product_name, s_store_name, s_zip),
y2 as (
  select i_product_name as y2_pn, s_store_name as y2_sn, s_zip as y2_zip,
         count(*) as y2_cnt, sum(ss_wholesale_cost) as y2_s1,
         sum(ss_list_price) as y2_s2, sum(ss_coupon_amt) as y2_s3
  from store_sales, store_returns, date_dim, store, item
  where ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number
    and ss_item_sk in (select ui_item from cs_ui)
    and ss_sold_date_sk = d_date_sk and d_year = 2000
    and ss_store_sk = s_store_sk
    and ss_item_sk = i_item_sk and i_current_price is not null
  group by i_product_name, s_store_name, s_zip)
select y1_pn, y1_sn, y1_zip, y1_s1, y1_s2, y1_s3, y2_s1, y2_s2, y2_s3,
       y2_cnt, y1_cnt
from y1, y2
where y1_pn = y2_pn and y1_sn = y2_sn and y1_zip = y2_zip
  and y2_cnt <= y1_cnt
order by y1_pn, y1_sn, y2_cnt
limit 100
""",
    "q66": """
with ws as (
  select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country,
         sum(case when d_moy = 1 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m1,
         sum(case when d_moy = 2 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m2,
         sum(case when d_moy = 3 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m3,
         sum(case when d_moy = 4 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m4,
         sum(case when d_moy = 5 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m5,
         sum(case when d_moy = 6 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m6,
         sum(case when d_moy = 7 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m7,
         sum(case when d_moy = 8 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m8,
         sum(case when d_moy = 9 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m9,
         sum(case when d_moy = 10 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m10,
         sum(case when d_moy = 11 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m11,
         sum(case when d_moy = 12 then ws_ext_sales_price * ws_quantity
             else 0.0 end) as m_m12
  from web_sales, date_dim, time_dim, warehouse
  where ws_sold_date_sk = d_date_sk and d_year = 2001
    and ws_sold_time_sk = t_time_sk and t_hour between 8 and 17
    and ws_ship_mode_sk in (select sm_ship_mode_sk from ship_mode
                            where sm_carrier in ('DHL', 'BARIAN'))
    and ws_warehouse_sk = w_warehouse_sk
  group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
           w_country),
cs as (
  select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country,
         sum(case when d_moy = 1 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m1,
         sum(case when d_moy = 2 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m2,
         sum(case when d_moy = 3 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m3,
         sum(case when d_moy = 4 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m4,
         sum(case when d_moy = 5 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m5,
         sum(case when d_moy = 6 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m6,
         sum(case when d_moy = 7 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m7,
         sum(case when d_moy = 8 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m8,
         sum(case when d_moy = 9 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m9,
         sum(case when d_moy = 10 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m10,
         sum(case when d_moy = 11 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m11,
         sum(case when d_moy = 12 then cs_ext_sales_price * cs_quantity
             else 0.0 end) as m_m12
  from catalog_sales, date_dim, time_dim, warehouse
  where cs_sold_date_sk = d_date_sk and d_year = 2001
    and cs_sold_time_sk = t_time_sk and t_hour between 8 and 17
    and cs_ship_mode_sk in (select sm_ship_mode_sk from ship_mode
                            where sm_carrier in ('DHL', 'BARIAN'))
    and cs_warehouse_sk = w_warehouse_sk
  group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
           w_country)
select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country,
       sum(m_m1) as m_m1, sum(m_m2) as m_m2, sum(m_m3) as m_m3,
       sum(m_m4) as m_m4, sum(m_m5) as m_m5, sum(m_m6) as m_m6,
       sum(m_m7) as m_m7, sum(m_m8) as m_m8, sum(m_m9) as m_m9,
       sum(m_m10) as m_m10, sum(m_m11) as m_m11, sum(m_m12) as m_m12
from (select * from ws union all select * from cs) x
group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country
order by w_warehouse_name
limit 100
""",
    "q67": """
with base as (
  select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id,
         sum(coalesce(ss_sales_price * ss_quantity, 0.0)) as sumsales
  from store_sales, date_dim, store, item
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
    and ss_store_sk = s_store_sk
    and ss_item_sk = i_item_sk
  group by rollup(i_category, i_class, i_brand, i_product_name, d_year,
                  d_qoy, d_moy, s_store_id))
select i_category, i_class, i_brand, i_product_name, d_year, d_qoy, d_moy,
       s_store_id, sumsales, rk
from (select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
             d_moy, s_store_id, sumsales,
             rank() over (partition by i_category
                          order by sumsales desc) as rk
      from base) ranked
where rk <= 100
order by i_category, sumsales desc, rk
limit 100
""",
    "q69": """
select cd_gender, cd_marital_status, cd_education_status, count(*) as cnt1,
       cd_purchase_estimate, count(*) as cnt2, cd_credit_rating,
       count(*) as cnt3
from customer
     left anti join
     (select ws_bill_customer_sk as wk from web_sales, date_dim
      where ws_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy between 4 and 6) w
     on c_customer_sk = wk
     left anti join
     (select cs_ship_customer_sk as ck from catalog_sales, date_dim
      where cs_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy between 4 and 6) c
     on c_customer_sk = ck,
     customer_address, customer_demographics
where c_current_addr_sk = ca_address_sk
  and ca_state in ('TN', 'GA', 'SD')
  and c_current_cdemo_sk = cd_demo_sk
  and c_customer_sk in (
    select ss_customer_sk from store_sales, date_dim
    where ss_sold_date_sk = d_date_sk
      and d_year = 2001 and d_moy between 4 and 6)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
""",
    "q70": """
select s_state, s_county, sum(ss_net_profit) as total_sum
from store_sales, date_dim, store
where ss_sold_date_sk = d_date_sk
  and d_month_seq between 1200 and 1211
  and ss_store_sk = s_store_sk
  and s_state in (
    select rank_state from (
      select rank_state, rank() over (order by sp desc) as rnk
      from (select s_state as rank_state, sum(ss_net_profit) as sp
            from store_sales, date_dim, store
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 1200 and 1211
              and ss_store_sk = s_store_sk
            group by s_state) sr) ranked
    where rnk <= 5)
group by rollup(s_state, s_county)
order by total_sum desc, s_state, s_county
limit 100
""",
    "q71": """
select i_brand_id as brand_id, i_brand as brand, t_hour, t_minute,
       sum(ext_price) as ext_price
from (select ws_ext_sales_price as ext_price, ws_item_sk as sold_item_sk,
             ws_sold_time_sk as time_sk
      from web_sales
      where ws_sold_date_sk in (select d_date_sk from date_dim
                                where d_moy = 11 and d_year = 1999)
      union all
      select cs_ext_sales_price as ext_price, cs_item_sk as sold_item_sk,
             cs_sold_time_sk as time_sk
      from catalog_sales
      where cs_sold_date_sk in (select d_date_sk from date_dim
                                where d_moy = 11 and d_year = 1999)
      union all
      select ss_ext_sales_price as ext_price, ss_item_sk as sold_item_sk,
             ss_sold_time_sk as time_sk
      from store_sales
      where ss_sold_date_sk in (select d_date_sk from date_dim
                                where d_moy = 11 and d_year = 1999)) u,
     item, time_dim
where sold_item_sk = i_item_sk and i_manager_id = 1
  and time_sk = t_time_sk and t_meal_time in ('breakfast', 'dinner')
group by i_brand, i_brand_id, t_hour, t_minute
order by ext_price desc, brand_id
""",
    "q72": """
select i_item_desc, w_warehouse_name, sold_week, count(*) as no_promo
from catalog_sales, inventory, warehouse, item, customer_demographics,
     household_demographics,
     (select d_date_sk as sold_sk, d_week_seq as sold_week
      from date_dim where d_year = 1999) dd
where cs_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cd_marital_status = 'D'
  and cs_bill_hdemo_sk = hd_demo_sk and hd_buy_potential = '>10000'
  and cs_sold_date_sk = sold_sk
  and inv_quantity_on_hand < cs_quantity
group by i_item_desc, w_warehouse_name, sold_week
order by no_promo desc, i_item_desc, w_warehouse_name, sold_week
limit 100
""",
    "q75": """
with ss as (
  select d_year, i_brand_id, i_category_id,
         sum(ss_quantity) - sum(cast(coalesce(sr_return_quantity, 0)
                                     as long)) as sales_cnt,
         sum(ss_ext_sales_price) - sum(coalesce(sr_return_amt, 0.0))
           as sales_amt
  from store_sales
       left join store_returns
       on ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk,
       date_dim, item
  where ss_sold_date_sk = d_date_sk and d_year in (1999, 2000)
    and ss_item_sk = i_item_sk and i_category = 'Books'
  group by d_year, i_brand_id, i_category_id),
cs as (
  select d_year, i_brand_id, i_category_id,
         sum(cs_quantity) - sum(cast(coalesce(cr_return_quantity, 0)
                                     as long)) as sales_cnt,
         sum(cs_ext_sales_price) - sum(coalesce(cr_return_amount, 0.0))
           as sales_amt
  from catalog_sales
       left join catalog_returns
       on cs_order_number = cr_order_number and cs_item_sk = cr_item_sk,
       date_dim, item
  where cs_sold_date_sk = d_date_sk and d_year in (1999, 2000)
    and cs_item_sk = i_item_sk and i_category = 'Books'
  group by d_year, i_brand_id, i_category_id),
ws as (
  select d_year, i_brand_id, i_category_id,
         sum(ws_quantity) - sum(cast(coalesce(wr_return_quantity, 0)
                                     as long)) as sales_cnt,
         sum(ws_ext_sales_price) - sum(coalesce(wr_return_amt, 0.0))
           as sales_amt
  from web_sales
       left join web_returns
       on ws_order_number = wr_order_number and ws_item_sk = wr_item_sk,
       date_dim, item
  where ws_sold_date_sk = d_date_sk and d_year in (1999, 2000)
    and ws_item_sk = i_item_sk and i_category = 'Books'
  group by d_year, i_brand_id, i_category_id),
all_y as (
  select d_year, i_brand_id, i_category_id, sum(sales_cnt) as sales_cnt,
         sum(sales_amt) as sales_amt
  from (select * from ss union all select * from cs
        union all select * from ws) x
  group by d_year, i_brand_id, i_category_id)
select curr.i_brand_id as i_brand_id, curr.i_category_id as i_category_id,
       prev.sales_cnt as prev_cnt, curr.sales_cnt as curr_cnt,
       curr.sales_cnt - prev.sales_cnt as delta_cnt,
       curr.sales_amt - prev.sales_amt as delta_amt
from (select * from all_y where d_year = 2000) curr,
     (select * from all_y where d_year = 1999) prev
where curr.i_brand_id = prev.i_brand_id
  and curr.i_category_id = prev.i_category_id
  and prev.sales_cnt > 0
  and cast(curr.sales_cnt as double) / prev.sales_cnt < 0.9
order by delta_cnt, i_brand_id, i_category_id
limit 100
""",
    "q76": """
select channel, col_name, d_year, d_qoy, i_category, count(*) as sales_cnt,
       sum(ext_sales_price) as sales_amt
from (select 'store' as channel, 'ss_store_sk' as col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price as ext_sales_price
      from store_sales, item, date_dim
      where ss_store_sk is null and ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
      union all
      select 'web' as channel, 'ws_ship_customer_sk' as col_name, d_year,
             d_qoy, i_category, ws_ext_sales_price as ext_sales_price
      from web_sales, item, date_dim
      where ws_ship_customer_sk is null and ws_item_sk = i_item_sk
        and ws_sold_date_sk = d_date_sk
      union all
      select 'catalog' as channel, 'cs_ship_addr_sk' as col_name, d_year,
             d_qoy, i_category, cs_ext_sales_price as ext_sales_price
      from catalog_sales, item, date_dim
      where cs_ship_addr_sk is null and cs_item_sk = i_item_sk
        and cs_sold_date_sk = d_date_sk) u
group by channel, col_name, d_year, d_qoy, i_category
order by channel, col_name, d_year, d_qoy, i_category
limit 100
""",
    "q77": """
with ssr as (
  select s.sid, s.sales, coalesce(r.returns_amt, 0.0) as returns_amt,
         s.profit - coalesce(r.net_loss, 0.0) as profit
  from (select ss_store_sk as sid, sum(ss_ext_sales_price) as sales,
               sum(ss_net_profit) as profit
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk
          and d_date between date '2000-08-01' and date '2000-08-30'
        group by ss_store_sk) s
  left join (select sr_store_sk as sid_r, sum(sr_return_amt) as returns_amt,
                    sum(sr_net_loss) as net_loss
             from store_returns, date_dim
             where sr_returned_date_sk = d_date_sk
               and d_date between date '2000-08-01' and date '2000-08-30'
             group by sr_store_sk) r
  on s.sid = r.sid_r),
csr as (
  select s.sid, s.sales, coalesce(r.returns_amt, 0.0) as returns_amt,
         s.profit - coalesce(r.net_loss, 0.0) as profit
  from (select cs_call_center_sk as sid, sum(cs_ext_sales_price) as sales,
               sum(cs_net_profit) as profit
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk
          and d_date between date '2000-08-01' and date '2000-08-30'
        group by cs_call_center_sk) s
  left join (select cr_call_center_sk as sid_r,
                    sum(cr_return_amount) as returns_amt,
                    sum(cr_net_loss) as net_loss
             from catalog_returns, date_dim
             where cr_returned_date_sk = d_date_sk
               and d_date between date '2000-08-01' and date '2000-08-30'
             group by cr_call_center_sk) r
  on s.sid = r.sid_r),
wsr as (
  select s.sid, s.sales, coalesce(r.returns_amt, 0.0) as returns_amt,
         s.profit - coalesce(r.net_loss, 0.0) as profit
  from (select ws_web_page_sk as sid, sum(ws_ext_sales_price) as sales,
               sum(ws_net_profit) as profit
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk
          and d_date between date '2000-08-01' and date '2000-08-30'
        group by ws_web_page_sk) s
  left join (select wr_web_page_sk as sid_r, sum(wr_return_amt) as returns_amt,
                    sum(wr_net_loss) as net_loss
             from web_returns, date_dim
             where wr_returned_date_sk = d_date_sk
               and d_date between date '2000-08-01' and date '2000-08-30'
             group by wr_web_page_sk) r
  on s.sid = r.sid_r)
select channel, sid, sum(sales) as sales, sum(returns_amt) as returns_amt,
       sum(profit) as profit
from (select 'store channel' as channel, sid, sales, returns_amt, profit
      from ssr
      union all
      select 'catalog channel' as channel, sid, sales, returns_amt, profit
      from csr
      union all
      select 'web channel' as channel, sid, sales, returns_amt, profit
      from wsr) x
group by rollup(channel, sid)
order by channel, sid
limit 100
""",
    "q88": """
select *
from (select count(*) as h8_30_to_9 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 8 and t_minute >= 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s1,
     (select count(*) as h9_to_9_30 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 9 and t_minute < 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s2,
     (select count(*) as h9_30_to_10 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 9 and t_minute >= 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s3,
     (select count(*) as h10_to_10_30 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 10 and t_minute < 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s4,
     (select count(*) as h10_30_to_11 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 10 and t_minute >= 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s5,
     (select count(*) as h11_to_11_30 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 11 and t_minute < 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s6,
     (select count(*) as h11_30_to_12 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 11 and t_minute >= 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s7,
     (select count(*) as h12_to_12_30 from store_sales
      where ss_sold_time_sk in (select t_time_sk from time_dim
                                where t_hour = 12 and t_minute < 30)
        and ss_hdemo_sk in (select hd_demo_sk from household_demographics
                            where (hd_dep_count = 4 and hd_vehicle_count <= 6)
                               or (hd_dep_count = 2 and hd_vehicle_count <= 4)
                               or (hd_dep_count = 0
                                   and hd_vehicle_count <= 2))
        and ss_store_sk in (select s_store_sk from store
                            where s_store_name = 'ese')) s8
""",
    "q14": """
with items as (
  select i_item_sk, i_brand_id, i_class as i_class_id_, i_category_id
  from item),
ssi as (
  select distinct i_brand_id as sb, i_class_id_ as sc, i_category_id as sg
  from store_sales, date_dim, items
  where ss_sold_date_sk = d_date_sk and d_year in (1999, 2000)
    and ss_item_sk = i_item_sk),
csi as (
  select distinct i_brand_id as cb, i_class_id_ as cc, i_category_id as cg
  from catalog_sales, date_dim, items
  where cs_sold_date_sk = d_date_sk and d_year in (1999, 2000)
    and cs_item_sk = i_item_sk),
wsi as (
  select distinct i_brand_id as wb, i_class_id_ as wc, i_category_id as wg
  from web_sales, date_dim, items
  where ws_sold_date_sk = d_date_sk and d_year in (1999, 2000)
    and ws_item_sk = i_item_sk),
cross_ids as (
  select sb, sc, sg from ssi
  left semi join csi on sb = cb and sc = cc and sg = cg
  left semi join wsi on sb = wb and sc = wc and sg = wg),
cross_items as (
  select i_item_sk from items
  left semi join cross_ids
  on i_brand_id = sb and i_class_id_ = sc and i_category_id = sg),
avg_sales as (
  select avg(v) as avg_v
  from (select ss_quantity * ss_list_price as v
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk and d_year in (1999, 2000)
        union all
        select cs_quantity * cs_list_price as v
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk and d_year in (1999, 2000)
        union all
        select ws_quantity * ws_list_price as v
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk and d_year in (1999, 2000)) x),
ch as (
  select ss_item_sk as item, sum(ss_quantity * ss_list_price) as sales,
         count(*) as number_sales
  from store_sales
  where ss_sold_date_sk in (select d_date_sk from date_dim
                            where d_year = 2000 and d_moy = 11)
    and ss_item_sk in (select i_item_sk from cross_items)
  group by ss_item_sk)
select sum(sales) as total_sales, sum(number_sales) as total_number
from ch, avg_sales
where sales > avg_v
""",
    "q36": """
with rolled as (
  select sum(ss_net_profit) as _num, sum(ss_ext_sales_price) as _den,
         i_category, i_class
  from store_sales, date_dim, store, item
  where ss_sold_date_sk = d_date_sk and d_year = 2001
    and ss_store_sk = s_store_sk and s_state = 'TN'
    and ss_item_sk = i_item_sk
  group by rollup(i_category, i_class)),
tmp as (
  select _num / _den as total_sum, i_category, i_class,
         case when i_category is null then 1 else 0 end
         + case when i_class is null then 1 else 0 end as lochierarchy,
         case when i_class is not null then i_category
              else null end as _parent
  from rolled)
select total_sum, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy, _parent
                    order by total_sum asc) as rank_within_parent
from tmp
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category else null end,
         rank_within_parent
limit 100
""",
    "q49": """
with web_g as (
  select item, return_ratio, currency_ratio,
         rank() over (order by return_ratio) as return_rank,
         rank() over (order by currency_ratio) as currency_rank
  from (select ws_item_sk as item,
               cast(sum(cast(coalesce(wr_return_quantity, 0) as long))
                    as double) / sum(ws_quantity) as return_ratio,
               sum(coalesce(wr_return_amt, 0.0)) / sum(ws_net_paid)
                 as currency_ratio,
               sum(ws_quantity) as sale_q
        from web_sales
             left join web_returns
             on ws_order_number = wr_order_number
                and ws_item_sk = wr_item_sk
        where ws_sold_date_sk in (select d_date_sk from date_dim
                                  where d_year = 2000 and d_moy = 12)
          and ws_net_paid > 0
        group by ws_item_sk) g
  where sale_q > 0),
cat_g as (
  select item, return_ratio, currency_ratio,
         rank() over (order by return_ratio) as return_rank,
         rank() over (order by currency_ratio) as currency_rank
  from (select cs_item_sk as item,
               cast(sum(cast(coalesce(cr_return_quantity, 0) as long))
                    as double) / sum(cs_quantity) as return_ratio,
               sum(coalesce(cr_return_amount, 0.0)) / sum(cs_net_paid)
                 as currency_ratio,
               sum(cs_quantity) as sale_q
        from catalog_sales
             left join catalog_returns
             on cs_order_number = cr_order_number
                and cs_item_sk = cr_item_sk
        where cs_sold_date_sk in (select d_date_sk from date_dim
                                  where d_year = 2000 and d_moy = 12)
          and cs_net_paid > 0
        group by cs_item_sk) g
  where sale_q > 0),
store_g as (
  select item, return_ratio, currency_ratio,
         rank() over (order by return_ratio) as return_rank,
         rank() over (order by currency_ratio) as currency_rank
  from (select ss_item_sk as item,
               cast(sum(cast(coalesce(sr_return_quantity, 0) as long))
                    as double) / sum(ss_quantity) as return_ratio,
               sum(coalesce(sr_return_amt, 0.0)) / sum(ss_net_paid)
                 as currency_ratio,
               sum(ss_quantity) as sale_q
        from store_sales
             left join store_returns
             on ss_ticket_number = sr_ticket_number
                and ss_item_sk = sr_item_sk
        where ss_sold_date_sk in (select d_date_sk from date_dim
                                  where d_year = 2000 and d_moy = 12)
          and ss_net_paid > 0
        group by ss_item_sk) g
  where sale_q > 0)
select channel, item, return_ratio, return_rank, currency_rank
from (select 'wr' as channel, item, return_ratio, return_rank, currency_rank
      from web_g where return_rank <= 10 or currency_rank <= 10
      union all
      select 'cr' as channel, item, return_ratio, return_rank, currency_rank
      from cat_g where return_rank <= 10 or currency_rank <= 10
      union all
      select 'sr' as channel, item, return_ratio, return_rank, currency_rank
      from store_g where return_rank <= 10 or currency_rank <= 10) u
order by channel, return_rank, currency_rank, item
limit 100
""",
    "q51": """
with wss as (
  select ws_item_sk as item_sk, d_date, sum(ws_sales_price) as daily
  from web_sales, date_dim
  where ws_sold_date_sk = d_date_sk and d_month_seq between 1200 and 1211
  group by ws_item_sk, d_date),
sss as (
  select ss_item_sk as item_sk, d_date, sum(ss_sales_price) as daily
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk and d_month_seq between 1200 and 1211
  group by ss_item_sk, d_date),
web as (
  select item_sk, d_date,
         sum(daily) over (partition by item_sk order by d_date
                          rows between unbounded preceding and current row)
           as web_cum
  from wss),
store as (
  select item_sk as s_item, d_date as s_date,
         sum(daily) over (partition by item_sk order by d_date
                          rows between unbounded preceding and current row)
           as store_cum
  from sss)
select item_sk, d_date, web_cum, store_cum
from web, store
where item_sk = s_item and d_date = s_date and web_cum > store_cum
order by item_sk, d_date
limit 100
""",
    "q59": """
with wss as (
  select d_week_seq, ss_store_sk,
         sum(case when d_day_name = 'Sunday' then ss_sales_price
             else null end) as sun_sales,
         sum(case when d_day_name = 'Monday' then ss_sales_price
             else null end) as mon_sales,
         sum(case when d_day_name = 'Tuesday' then ss_sales_price
             else null end) as tue_sales,
         sum(case when d_day_name = 'Wednesday' then ss_sales_price
             else null end) as wed_sales,
         sum(case when d_day_name = 'Thursday' then ss_sales_price
             else null end) as thu_sales,
         sum(case when d_day_name = 'Friday' then ss_sales_price
             else null end) as fri_sales,
         sum(case when d_day_name = 'Saturday' then ss_sales_price
             else null end) as sat_sales
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
  group by d_week_seq, ss_store_sk),
weeks as (select distinct d_week_seq as wseq, d_month_seq from date_dim),
y as (
  select s_store_name as s_store_name1, d_week_seq as d_week_seq1,
         s_store_id as s_store_id1, sun_sales as sun_sales1,
         mon_sales as mon_sales1, tue_sales as tue_sales1,
         wed_sales as wed_sales1, thu_sales as thu_sales1,
         fri_sales as fri_sales1, sat_sales as sat_sales1
  from wss, weeks, store
  where d_week_seq = wseq and d_month_seq between 1212 and 1223
    and ss_store_sk = s_store_sk),
x as (
  select s_store_name as s_store_name2, d_week_seq as d_week_seq2,
         s_store_id as s_store_id2, sun_sales as sun_sales2,
         mon_sales as mon_sales2, tue_sales as tue_sales2,
         wed_sales as wed_sales2, thu_sales as thu_sales2,
         fri_sales as fri_sales2, sat_sales as sat_sales2
  from wss, weeks, store
  where d_week_seq = wseq and d_month_seq between 1224 and 1235
    and ss_store_sk = s_store_sk)
select s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 as sun_r, mon_sales1 / mon_sales2 as mon_r,
       tue_sales1 / tue_sales2 as tue_r, wed_sales1 / wed_sales2 as wed_r,
       thu_sales1 / thu_sales2 as thu_r, fri_sales1 / fri_sales2 as fri_r,
       sat_sales1 / sat_sales2 as sat_r
from y, x
where s_store_id1 = s_store_id2 and d_week_seq1 = d_week_seq2 - 52
order by s_store_name1, s_store_id1, d_week_seq1
limit 100
""",
    "q78": """
with ss as (
  select ss_item_sk as ss_item, ss_customer_sk as ss_cust,
         sum(ss_quantity) as ss_qty, sum(ss_wholesale_cost) as ss_wc,
         sum(ss_sales_price) as ss_sp
  from store_sales
       left anti join store_returns
       on ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk,
       date_dim
  where ss_sold_date_sk = d_date_sk and d_year = 2000
  group by ss_item_sk, ss_customer_sk),
ws as (
  select ws_item_sk as ws_item, ws_bill_customer_sk as ws_cust,
         sum(ws_quantity) as ws_qty, sum(ws_wholesale_cost) as ws_wc,
         sum(ws_sales_price) as ws_sp
  from web_sales
       left anti join web_returns
       on ws_order_number = wr_order_number and ws_item_sk = wr_item_sk,
       date_dim
  where ws_sold_date_sk = d_date_sk and d_year = 2000
  group by ws_item_sk, ws_bill_customer_sk),
cs as (
  select cs_item_sk as cs_item, cs_bill_customer_sk as cs_cust,
         sum(cs_quantity) as cs_qty, sum(cs_wholesale_cost) as cs_wc,
         sum(cs_sales_price) as cs_sp
  from catalog_sales
       left anti join catalog_returns
       on cs_order_number = cr_order_number and cs_item_sk = cr_item_sk,
       date_dim
  where cs_sold_date_sk = d_date_sk and d_year = 2000
  group by cs_item_sk, cs_bill_customer_sk)
select ss_item, ss_cust, ss_qty, ss_wc, ss_sp,
       round(cast(ss_qty as double) / (ws_qty + cs_qty), 2) as ratio
from ss, ws, cs
where ss_item = ws_item and ss_cust = ws_cust
  and ss_item = cs_item and ss_cust = cs_cust
  and (ws_qty > 0 or cs_qty > 0)
order by ss_item, ss_cust
limit 100
""",
    "q80": """
with ssr as (
  select ss_store_sk as id, sum(ss_ext_sales_price) as sales,
         sum(coalesce(sr_return_amt, 0.0)) as returns_amt,
         sum(ss_net_profit) - sum(coalesce(sr_net_loss, 0.0)) as profit
  from store_sales
       left join store_returns
       on ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk,
       date_dim
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-01' and date '2000-08-30'
    and ss_promo_sk in (select p_promo_sk from promotion
                        where p_channel_tv = 'N')
  group by ss_store_sk),
csr as (
  select cs_catalog_page_sk as id, sum(cs_ext_sales_price) as sales,
         sum(coalesce(cr_return_amount, 0.0)) as returns_amt,
         sum(cs_net_profit) - sum(coalesce(cr_net_loss, 0.0)) as profit
  from catalog_sales
       left join catalog_returns
       on cs_order_number = cr_order_number and cs_item_sk = cr_item_sk,
       date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-01' and date '2000-08-30'
    and cs_promo_sk in (select p_promo_sk from promotion
                        where p_channel_tv = 'N')
  group by cs_catalog_page_sk),
wsr as (
  select ws_web_site_sk as id, sum(ws_ext_sales_price) as sales,
         sum(coalesce(wr_return_amt, 0.0)) as returns_amt,
         sum(ws_net_profit) - sum(coalesce(wr_net_loss, 0.0)) as profit
  from web_sales
       left join web_returns
       on ws_order_number = wr_order_number and ws_item_sk = wr_item_sk,
       date_dim
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-01' and date '2000-08-30'
    and ws_promo_sk in (select p_promo_sk from promotion
                        where p_channel_tv = 'N')
  group by ws_web_site_sk)
select channel, id, sum(sales) as sales, sum(returns_amt) as returns_amt,
       sum(profit) as profit
from (select 'store channel' as channel, id, sales, returns_amt, profit
      from ssr
      union all
      select 'catalog channel' as channel, id, sales, returns_amt, profit
      from csr
      union all
      select 'web channel' as channel, id, sales, returns_amt, profit
      from wsr) x
group by rollup(channel, id)
order by channel, id
limit 100
""",
    "q81": """
with ctr as (
  select cr_returning_customer_sk as ctr_cust, ca_state as ctr_state,
         sum(cr_return_amt_inc_tax) as ctr_total
  from catalog_returns, date_dim, customer, customer_address
  where cr_returned_date_sk = d_date_sk and d_year = 2000
    and cr_returning_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
  group by cr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name, ca_city,
       ca_zip, ctr_total
from ctr ctr1, customer, customer_address
where ctr1.ctr_total > (select avg(ctr_total) * 1.2 from ctr ctr2
                        where ctr1.ctr_state = ctr2.ctr_state)
  and ctr1.ctr_cust = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_state = 'GA'
order by c_customer_id, c_salutation, c_first_name, c_last_name, ca_city,
         ca_zip
limit 100
""",
    "q82": """
select distinct i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim
where i_current_price between 62 and 92
  and i_manufact_id in (8, 33, 58, 83)
  and inv_item_sk = i_item_sk
  and inv_quantity_on_hand between 100 and 500
  and inv_date_sk = d_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_item_sk in (select ss_item_sk from store_sales)
order by i_item_id
limit 100
""",
    "q83": """
with dates as (
  select d_date_sk from date_dim
  where d_week_seq in (select d_week_seq from date_dim
                       where d_date in (date '2000-06-30',
                                        date '2000-09-27',
                                        date '2000-11-17'))),
sr_items as (
  select i_item_id as sr_item_id, sum(sr_return_quantity) as sr_qty
  from store_returns, item
  where sr_returned_date_sk in (select d_date_sk from dates)
    and sr_item_sk = i_item_sk
  group by i_item_id),
cr_items as (
  select i_item_id as cr_item_id, sum(cr_return_quantity) as cr_qty
  from catalog_returns, item
  where cr_returned_date_sk in (select d_date_sk from dates)
    and cr_item_sk = i_item_sk
  group by i_item_id),
wr_items as (
  select i_item_id as wr_item_id, sum(wr_return_quantity) as wr_qty
  from web_returns, item
  where wr_returned_date_sk in (select d_date_sk from dates)
    and wr_item_sk = i_item_sk
  group by i_item_id)
select sr_item_id as item_id, sr_qty,
       sr_qty / cast(sr_qty + cr_qty + wr_qty as double) * 100 as sr_dev,
       cr_qty,
       cr_qty / cast(sr_qty + cr_qty + wr_qty as double) * 100 as cr_dev,
       wr_qty,
       wr_qty / cast(sr_qty + cr_qty + wr_qty as double) * 100 as wr_dev,
       cast(sr_qty + cr_qty + wr_qty as double) / 3.0 as average
from sr_items, cr_items, wr_items
where sr_item_id = cr_item_id and sr_item_id = wr_item_id
order by item_id, sr_qty
limit 100
""",
    "q84": """
select c_customer_id as customer_id, c_last_name, c_first_name
from customer, customer_address, customer_demographics, store_returns
where c_current_addr_sk = ca_address_sk and ca_city = 'Fairview'
  and c_current_cdemo_sk = cd_demo_sk
  and cd_demo_sk = sr_cdemo_sk
order by customer_id
limit 100
""",
    "q85": """
select r_reason_desc, avg(ws_quantity) as avg_q,
       avg(wr_refunded_cash) as avg_cash, avg(wr_fee) as avg_fee
from web_returns, web_sales, date_dim, web_page, reason,
     customer_demographics
where wr_order_number = ws_order_number and wr_item_sk = ws_item_sk
  and ws_sold_date_sk = d_date_sk and d_year = 2000
  and ws_web_page_sk = wp_web_page_sk
  and wr_reason_sk = r_reason_sk
  and wr_refunded_cdemo_sk = cd_demo_sk
  and ((cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ws_sales_price >= 100.0)
       or (cd_marital_status = 'S' and cd_education_status = 'College'
           and ws_sales_price >= 50.0)
       or (cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
           and ws_sales_price >= 0.0))
group by r_reason_desc
order by r_reason_desc, avg_q, avg_cash, avg_fee
limit 100
""",
    "q87": """
select count(*) as cnt
from (select distinct c_last_name, c_first_name, d_date
      from store_sales, date_dim, customer
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1211
        and ss_customer_sk = c_customer_sk) store_c
     left anti join
     (select distinct c_last_name as ln, c_first_name as fn, d_date as dt
      from catalog_sales, date_dim, customer
      where cs_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1211
        and cs_bill_customer_sk = c_customer_sk) catalog_c
     on c_last_name = ln and c_first_name = fn and d_date = dt
     left anti join
     (select distinct c_last_name as wl, c_first_name as wf, d_date as wd
      from web_sales, date_dim, customer
      where ws_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1211
        and ws_bill_customer_sk = c_customer_sk) web_c
     on c_last_name = wl and c_first_name = wf and d_date = wd
""",
    "q91": """
select cc_call_center_id, cc_name, cc_manager, cd_marital_status,
       cd_education_status, sum(cr_net_loss) as returns_loss
from catalog_returns, date_dim, call_center, customer,
     customer_demographics, household_demographics, customer_address
where cr_returned_date_sk = d_date_sk and d_year = 1998 and d_moy = 11
  and cr_call_center_sk = cc_call_center_sk
  and cr_returning_customer_sk = c_customer_sk
  and c_current_cdemo_sk = cd_demo_sk
  and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
       or (cd_marital_status = 'W'
           and cd_education_status = 'Advanced Degree'))
  and c_current_hdemo_sk = hd_demo_sk
  and hd_buy_potential like 'Unknown%'
  and c_current_addr_sk = ca_address_sk
  and ca_gmt_offset = -7
group by cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
order by returns_loss desc
limit 100
""",
    "q95": """
with multi_wh as (
  select distinct won
  from (select ws_order_number as won, ws_warehouse_sk as wwh
        from web_sales) ws1,
       (select ws_order_number as won2, ws_warehouse_sk as wwh2
        from web_sales) ws2
  where won = won2 and wwh <> wwh2)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales, date_dim, customer_address
where ws_ship_date_sk = d_date_sk
  and d_date between date '1999-02-01' and date '1999-04-02'
  and ws_ship_addr_sk = ca_address_sk and ca_state = 'GA'
  and ws_order_number in (select won from multi_wh)
  and ws_order_number in (select distinct wr_order_number from web_returns)
""",
    "q97": """
with ssci as (
  select distinct ss_customer_sk as s_cust, ss_item_sk as s_item
  from store_sales
  where ss_sold_date_sk in (select d_date_sk from date_dim
                            where d_month_seq between 1200 and 1211)),
csci as (
  select distinct cs_bill_customer_sk as c_cust, cs_item_sk as c_item
  from catalog_sales
  where cs_sold_date_sk in (select d_date_sk from date_dim
                            where d_month_seq between 1200 and 1211))
select sum(case when s_item is not null and c_item is null
           then 1 else 0 end) as store_only,
       sum(case when s_item is null and c_item is not null
           then 1 else 0 end) as catalog_only,
       sum(case when s_item is not null and c_item is not null
           then 1 else 0 end) as store_and_catalog
from ssci full join csci on s_cust = c_cust and s_item = c_item
""",
}
