"""Mortgage ETL benchmark (reference: MortgageSpark.scala, 437 LoC — the
FannieMae single-family loan performance ETL used for the perf/cost chart in
docs/index.md).

Faithful translation of the reference pipeline: seller-name normalization via
a mapping-table left join (NameMapping:120), per-loan delinquency milestones
(CreatePerformanceDelinquency:213 — the ever_30/90/180 aggregation, the
12-month window trick via ``explode`` of a literal month array, and the
"josh_mody" month-bucket arithmetic kept intact), acquisition cleanup
(CreateAcquisition:301), and the final prime join (CleanAcquisitionPrime:317).
The generator emits typed columns directly (dates as dates), standing in for
the reference's CSV parse + to_date stage.
"""
from __future__ import annotations

import datetime
from typing import Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.dataframe import DataFrame

col, lit, when = F.col, F.lit, F.when

_EPOCH = datetime.date(1970, 1, 1)

#: NameMapping analog (MortgageSpark.scala:120) — raw seller spellings to
#: canonical names
NAME_MAPPING = [
    ("WITMER FINANCING, INC", "Witmer"),
    ("WITMER FINANCING INC", "Witmer"),
    ("BANK OF AMERICA, N.A.", "Bank of America"),
    ("BANK OF AMERICA NA", "Bank of America"),
    ("QUICKEN LOANS INC.", "Quicken Loans"),
    ("QUICKEN LOANS, INC.", "Quicken Loans"),
    ("WELLS FARGO BANK, N.A.", "Wells Fargo"),
    ("WELLS FARGO BANK NA", "Wells Fargo"),
    ("FLAGSTAR BANK, FSB", "Flagstar Bank"),
    ("PENNYMAC CORP.", "PennyMac"),
]
_RAW_SELLERS = [m[0] for m in NAME_MAPPING] + ["OTHER", "UNMAPPED LENDER LLC"]


def n_loans(scale: float) -> int:
    return max(int(10_000 * scale), 200)


def gen_performance(scale: float = 0.02, seed: int = 0) -> pa.Table:
    loans = n_loans(scale)
    rng = np.random.default_rng(seed + 21)
    months_per = rng.integers(6, 37, loans)
    loan_id = np.repeat(np.arange(1, loans + 1, dtype=np.int64), months_per)
    n = loan_id.shape[0]
    quarter = np.char.add(
        rng.integers(2000, 2008, loans).astype(str),
        np.char.add("Q", rng.integers(1, 5, loans).astype(str)))
    start = rng.integers(0, 12 * 8, loans)  # months after 2000-01
    seq = (np.arange(n, dtype=np.int64)
           - np.repeat(np.cumsum(months_per) - months_per, months_per))
    month_idx = start[loan_id - 1] + seq
    year = 2000 + month_idx // 12
    month = month_idx % 12 + 1
    period = np.array([(datetime.date(int(y), int(m), 1) - _EPOCH).days
                       for y, m in zip(year, month)], np.int32)
    # delinquency mostly 0; troubled loans escalate
    troubled = rng.random(loans) < 0.2
    status = np.where(np.repeat(troubled, months_per),
                      rng.integers(0, 10, n), 0).astype(np.int32)
    upb = np.round(np.repeat(rng.uniform(50_000, 500_000, loans), months_per)
                   * (1 - seq * 0.01), 2)
    upb = np.where(rng.random(n) < 0.01, 0.0, upb)
    return pa.table({
        "quarter": pa.array(np.repeat(quarter, months_per)),
        "loan_id": pa.array(loan_id),
        "monthly_reporting_period": pa.array(period, type=pa.date32()),
        "current_loan_delinquency_status": pa.array(status),
        "current_actual_upb": pa.array(upb),
        "servicer": pa.array(np.repeat(
            np.array(_RAW_SELLERS)[rng.integers(0, len(_RAW_SELLERS), loans)],
            months_per)),
        "interest_rate": pa.array(np.round(np.repeat(
            rng.uniform(2.5, 8.0, loans), months_per), 3)),
    })


def gen_acquisition(scale: float = 0.02, seed: int = 0) -> pa.Table:
    loans = n_loans(scale)
    rng = np.random.default_rng(seed + 22)
    loan_id = np.arange(1, loans + 1, dtype=np.int64)
    # quarters must line up with the performance table's per-loan quarter
    perf_rng = np.random.default_rng(seed + 21)
    perf_rng.integers(6, 37, loans)  # consume months_per draw
    quarter = np.char.add(
        perf_rng.integers(2000, 2008, loans).astype(str),
        np.char.add("Q", perf_rng.integers(1, 5, loans).astype(str)))
    orig = rng.integers(0, 12 * 8, loans)
    orig_date = np.array([(datetime.date(2000 + int(m) // 12,
                                         int(m) % 12 + 1, 1) - _EPOCH).days
                          for m in orig], np.int32)
    return pa.table({
        "loan_id": pa.array(loan_id),
        "quarter": pa.array(quarter),
        "seller_name": pa.array(
            np.array(_RAW_SELLERS)[rng.integers(0, len(_RAW_SELLERS), loans)]),
        "orig_date": pa.array(orig_date, type=pa.date32()),
        "first_pay_date": pa.array(orig_date + 31, type=pa.date32()),
        "orig_interest_rate": pa.array(np.round(rng.uniform(2.5, 8.0, loans), 3)),
        "orig_upb": pa.array(np.round(rng.uniform(50_000, 500_000, loans), 2)),
        "orig_loan_term": pa.array(rng.choice([180, 240, 360], loans).astype(np.int32)),
        "orig_ltv": pa.array(np.round(rng.uniform(40, 97, loans), 1)),
        "dti": pa.array(np.round(rng.uniform(10, 50, loans), 1)),
        "borrower_credit_score": pa.array(rng.integers(550, 840, loans).astype(np.int32)),
        "state": pa.array(np.array(["CA", "TX", "NY", "FL", "IL", "WA", "CO"])[
            rng.integers(0, 7, loans)]),
    })


def create_performance_delinquency(perf: DataFrame) -> DataFrame:
    """CreatePerformanceDelinquency.apply analog (MortgageSpark.scala:229)."""
    base = perf.withColumn("timestamp_month",
                           F.month("monthly_reporting_period")) \
               .withColumn("timestamp_year",
                           F.year("monthly_reporting_period"))
    agg_df = (perf.select(
        "quarter", "loan_id", "current_loan_delinquency_status",
        when(col("current_loan_delinquency_status") >= 1,
             col("monthly_reporting_period")).alias("delinquency_30"),
        when(col("current_loan_delinquency_status") >= 3,
             col("monthly_reporting_period")).alias("delinquency_90"),
        when(col("current_loan_delinquency_status") >= 6,
             col("monthly_reporting_period")).alias("delinquency_180"))
        .groupBy("quarter", "loan_id")
        .agg(F.max("current_loan_delinquency_status").alias("delinquency_12"),
             F.min("delinquency_30").alias("delinquency_30"),
             F.min("delinquency_90").alias("delinquency_90"),
             F.min("delinquency_180").alias("delinquency_180"))
        .select("quarter", "loan_id",
                (col("delinquency_12") >= 1).alias("ever_30"),
                (col("delinquency_12") >= 3).alias("ever_90"),
                (col("delinquency_12") >= 6).alias("ever_180"),
                "delinquency_30", "delinquency_90", "delinquency_180"))

    joined = (base
              .withColumnRenamed("monthly_reporting_period", "timestamp")
              .withColumnRenamed("current_loan_delinquency_status",
                                 "delinquency_12")
              .withColumnRenamed("current_actual_upb", "upb_12")
              .select("quarter", "loan_id", "timestamp", "delinquency_12",
                      "upb_12", "timestamp_month", "timestamp_year")
              .join(agg_df, ["loan_id", "quarter"], "left"))

    months = 12
    mody = (col("timestamp_year") * 12 + col("timestamp_month")) - 24000
    test_df = (joined
               .select("quarter", "loan_id", "ever_30", "ever_90", "ever_180",
                       "delinquency_30", "delinquency_90", "delinquency_180",
                       "delinquency_12", "upb_12", "timestamp_month",
                       "timestamp_year",
                       F.explode(list(range(12))).alias("month_y"))
               .select("quarter", "loan_id", "ever_30", "ever_90", "ever_180",
                       "delinquency_30", "delinquency_90", "delinquency_180",
                       "delinquency_12", "upb_12", "month_y",
                       F.floor((mody - col("month_y")) / float(months))
                       .alias("josh_mody_n"))
               .groupBy("quarter", "loan_id", "josh_mody_n", "ever_30",
                        "ever_90", "ever_180", "delinquency_30",
                        "delinquency_90", "delinquency_180", "month_y")
               .agg(F.max("delinquency_12").alias("delinquency_12"),
                    F.min("upb_12").alias("upb_12"))
               .withColumn("timestamp_year",
                           F.floor((lit(24000) + col("josh_mody_n") * months
                                    + (col("month_y") - 1)) / 12.0))
               .withColumn("timestamp_month_tmp",
                           F.pmod(lit(24000) + col("josh_mody_n") * months
                                  + col("month_y"), lit(12)))
               .withColumn("timestamp_month",
                           when(col("timestamp_month_tmp") == 0, 12)
                           .otherwise(col("timestamp_month_tmp")))
               .withColumn("delinquency_12",
                           (col("delinquency_12") > 3).cast("int")
                           + (col("upb_12") == 0).cast("int"))
               .drop("timestamp_month_tmp", "josh_mody_n", "month_y"))

    out = (base
           .withColumn("timestamp_year", col("timestamp_year").cast("double"))
           .withColumn("timestamp_month", col("timestamp_month").cast("double"))
           .join(test_df,
                 ["quarter", "loan_id", "timestamp_year", "timestamp_month"],
                 "left")
           .drop("timestamp_year", "timestamp_month"))
    return out


def create_acquisition(acq: DataFrame) -> DataFrame:
    """CreateAcquisition analog (MortgageSpark.scala:301)."""
    session = acq.session
    mapping = session.create_dataframe(pa.table({
        "from_seller_name": pa.array([m[0] for m in NAME_MAPPING]),
        "to_seller_name": pa.array([m[1] for m in NAME_MAPPING]),
    }))
    return (acq.join(mapping, [("seller_name", "from_seller_name")], "left")
            .drop("from_seller_name")
            .withColumn("old_name", col("seller_name"))
            .withColumn("seller_name", F.coalesce(col("to_seller_name"),
                                                  col("seller_name")))
            .drop("to_seller_name"))


def clean_acquisition_prime(perf: DataFrame, acq: DataFrame) -> DataFrame:
    """CleanAcquisitionPrime analog: the full ETL output."""
    p = create_performance_delinquency(perf)
    a = create_acquisition(acq)
    return p.join(a, ["loan_id", "quarter"]).drop("quarter")


def simple_aggregates(perf: DataFrame, acq: DataFrame) -> DataFrame:
    """SimpleAggregates.csv analog (MortgageSpark.scala:349)."""
    return (clean_acquisition_prime(perf, acq)
            .groupBy("seller_name", "state")
            .agg(F.count().alias("loans"),
                 F.avg("interest_rate").alias("avg_rate"),
                 F.max("delinquency_12").alias("max_delinquency_12"),
                 F.sum("upb_12").alias("total_upb"))
            .sort("seller_name", "state"))
