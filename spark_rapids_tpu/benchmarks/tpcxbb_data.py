"""Deterministic data generator for the TPCx-BB ("BigBench") table set.

Reference analog: TpcxbbLikeSpark.scala's 19 table schemas
(integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala:172-767). The reference
loads vendor-generated CSVs; this module synthesizes the same shapes with the
structural properties the supported queries depend on:

- store_returns rows are drawn FROM store_sales lines (same ticket/item/
  customer, returned 1-60 days later) so the return-ratio and
  returned-then-repurchased queries (q20, q21) have matches;
- a slice of web_sales is derived from store_returns (same item, returning
  customer buys on the web afterwards) for q21's re-purchase chain;
- web_returns rows are drawn from web_sales orders (q16's order/item join);
- a slice of web_clickstreams replays store_sales purchases as logged-in views
  1-30 days earlier (q5's per-user click profile, q12's view-then-buy funnel);
- inventory quantity is zero-inflated Poisson with per-item rates so some items
  exceed q23's coefficient-of-variation >= 1.3 cutoff;
- item_marketprices carries several competitor price records per item (q24).

Dimensions shared with TPC-DS (date_dim, time_dim, item, customer, store,
demographics, promotion, customer_address) reuse the tpcds_data generators,
extended with the extra columns the TPCx-BB queries touch (i_class_id,
c_login/c_email_address). Doubles stand in for decimals (v0 scope).
"""
from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.benchmarks.tpcds_data import (
    _D0, _DAYS, _EPOCH, _SK0, _null_some, _price_lines, gen_customer,
    gen_customer_address, gen_customer_demographics, gen_date_dim,
    gen_household_demographics, gen_item, gen_promotion, gen_store,
    gen_store_sales, gen_time_dim, n_customer, n_item, STORE_NAMES)


def date_sk(d: datetime.date) -> int:
    """The d_date_sk of a calendar date in the generated date_dim."""
    return _SK0 + (d - _D0).days


def n_warehouse(scale): return max(int(8 * scale), 4)
def n_web_page(scale): return max(int(120 * scale), 30)
def n_reviews(scale): return max(int(6_000 * scale), 250)
def n_web_orders(scale): return max(int(60_000 * scale), 400)
def n_clicks(scale): return max(int(400_000 * scale), 2_000)


def _extend_item(t: pa.Table, seed: int) -> pa.Table:
    """i_class_id 1..15 (q26 buckets on it; cycled so every id exists) and a
    guaranteed population in q22's 0.98-1.5 price window (uniform prices over
    0.09-99.99 would leave ~0 such items at small scales)."""
    rng = np.random.default_rng(seed + 30)
    n = t.num_rows
    class_id = (np.arange(n) % 15 + 1).astype(np.int32)
    price = t.column("i_current_price").to_numpy(zero_copy_only=False).copy()
    cheap = np.arange(n) % 25 == 3
    price[cheap] = np.round(rng.uniform(1.0, 1.45, int(cheap.sum())), 2)
    idx = t.schema.get_field_index("i_current_price")
    t = t.set_column(idx, "i_current_price", pa.array(price))
    return t.append_column("i_class_id", pa.array(class_id))


def _extend_customer(t: pa.Table, seed: int) -> pa.Table:
    """c_login / c_email_address (q6/q13 report them)."""
    n = t.num_rows
    sk = np.arange(1, n + 1)
    login = np.char.add("user", sk.astype(str))
    email = np.char.add(login, "@example.com")
    return (t.append_column("c_login", pa.array(login))
            .append_column("c_email_address", pa.array(email)))


def gen_warehouse(scale: float, seed: int) -> pa.Table:
    n = n_warehouse(scale)
    sk = np.arange(1, n + 1, dtype=np.int64)
    states = np.array(["TN", "GA", "SD", "IN", "LA", "MI", "SC", "OH"])
    return pa.table({
        "w_warehouse_sk": pa.array(sk),
        "w_warehouse_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "w_warehouse_name": pa.array(np.char.add("Warehouse no ",
                                                 sk.astype(str))),
        "w_state": pa.array(states[(sk - 1) % len(states)]),
    })


#: BigBench page taxonomy (the spec's wp_type domain): q4 looks for 'order'
#: pages without a following 'confirmation', q8 for 'review' pages before a
#: purchase — cycled so every type exists at every scale
_PAGE_TYPES = np.array(["ad", "dynamic", "feedback", "general", "order",
                        "protected", "review", "welcome", "confirmation"])


def gen_web_page(scale: float, seed: int) -> pa.Table:
    n = n_web_page(scale)
    rng = np.random.default_rng(seed + 31)
    sk = np.arange(1, n + 1, dtype=np.int64)
    # ~1/3 of pages land in q14's 5000-6000 char window
    chars = rng.integers(3000, 9000, n).astype(np.int32)
    return pa.table({
        "wp_web_page_sk": pa.array(sk),
        "wp_web_page_id": pa.array(np.char.add(
            "AAAAAAAA", np.char.zfill(sk.astype(str), 8))),
        "wp_char_count": pa.array(chars),
        "wp_link_count": pa.array(rng.integers(2, 25, n).astype(np.int32)),
        "wp_type": pa.array(_PAGE_TYPES[(sk - 1) % len(_PAGE_TYPES)]),
    })


#: sentiment vocabulary for the review-NLP queries (q10/q18/q19 classify
#: sentences by word-list matching — the spec's sentiment lexicon role)
POSITIVE_WORDS = ("great", "love", "works", "premium", "solid", "fast")
NEGATIVE_WORDS = ("poor", "broken", "hate", "slow", "failed", "cheap")
_REVIEW_WORDS = np.array(POSITIVE_WORDS + NEGATIVE_WORDS
                         + ("classic", "value"))
_REVIEW_NOUNS = np.array(["product", "item", "quality", "packaging"])
#: competitor names q27's entity extraction looks for
COMPETITOR_COMPANIES = ("Acme", "Globex", "Initech", "Vandelay", "Hooli")


def gen_product_reviews(scale: float, seed: int) -> pa.Table:
    """Reviews with 1-3 short sentences ('. '-separated). Sentence kinds:

    - plain:   "<word> <noun>"                    (q10/q19 sentiment)
    - store:   "<word> service at store <name>"   (q18: mentions a store by
                name; <name> drawn from gen_store's s_store_name domain)
    - company: "<word> compared to <Company>"     (q27 entity extraction)
    """
    n = n_reviews(scale)
    rng = np.random.default_rng(seed + 32)
    sk = np.arange(1, n + 1, dtype=np.int64)
    stores = np.array(STORE_NAMES)
    companies = np.array(COMPETITOR_COMPANIES)

    def sentence():
        w = _REVIEW_WORDS[rng.integers(0, len(_REVIEW_WORDS), n)]
        kind = rng.random(n)
        plain = np.char.add(np.char.add(w, " "),
                            _REVIEW_NOUNS[rng.integers(
                                0, len(_REVIEW_NOUNS), n)])
        store = np.char.add(np.char.add(w, " service at store "),
                            stores[rng.integers(0, len(stores), n)])
        comp = np.char.add(np.char.add(w, " compared to "),
                           companies[rng.integers(0, len(companies), n)])
        return np.where(kind < 0.2, store, np.where(kind < 0.4, comp, plain))

    content = sentence()
    for extra in range(2):
        more = rng.random(n) < 0.5
        content = np.where(
            more, np.char.add(np.char.add(content, ". "), sentence()),
            content)
    return pa.table({
        "pr_review_sk": pa.array(sk),
        "pr_review_rating": pa.array(rng.integers(1, 6, n).astype(np.int32)),
        "pr_item_sk": _null_some(
            rng, rng.integers(1, n_item(scale) + 1, n).astype(np.int64), 0.02),
        "pr_user_sk": _null_some(
            rng, rng.integers(1, n_customer(scale) + 1, n).astype(np.int64),
            0.04),
        "pr_review_content": pa.array(content),
    })


def gen_store_returns(scale: float, seed: int,
                      store_sales: pa.Table) -> pa.Table:
    """~8% of store_sales lines come back 1-60 days later (dsdgen links
    returns to sales the same way; q20/q21 join on ticket+item+customer)."""
    rng = np.random.default_rng(seed + 33)
    n_ss = store_sales.num_rows
    take = np.flatnonzero(rng.random(n_ss) < 0.08)
    sold_date = store_sales.column("ss_sold_date_sk").to_numpy(
        zero_copy_only=False)
    cust = store_sales.column("ss_customer_sk").to_numpy(zero_copy_only=False)
    item = store_sales.column("ss_item_sk").to_numpy(zero_copy_only=False)
    tick = store_sales.column("ss_ticket_number").to_numpy(
        zero_copy_only=False)
    qty = store_sales.column("ss_quantity").to_numpy(zero_copy_only=False)
    net = store_sales.column("ss_net_paid").to_numpy(zero_copy_only=False)

    k = take.shape[0]
    ret_date = sold_date[take] + rng.integers(1, 61, k)
    ret_qty = np.minimum(rng.integers(1, 101, k), qty[take]).astype(np.int32)
    frac = ret_qty / np.maximum(qty[take], 1)
    amt = np.round(np.nan_to_num(net[take]) * frac, 2)
    return pa.table({
        "sr_returned_date_sk": pa.array(
            np.where(np.isnan(ret_date), 0, ret_date).astype(np.int64),
            mask=np.isnan(ret_date)),
        "sr_item_sk": pa.array(item[take].astype(np.int64)),
        "sr_customer_sk": pa.array(
            np.where(np.isnan(cust[take]), 0, cust[take]).astype(np.int64),
            mask=np.isnan(cust[take])),
        "sr_ticket_number": pa.array(tick[take].astype(np.int64)),
        "sr_return_quantity": pa.array(ret_qty),
        "sr_return_amt": pa.array(amt),
    })


def gen_web_sales(scale: float, seed: int,
                  store_returns: pa.Table) -> pa.Table:
    """Random web orders plus a replay slice: every 3rd store return's
    (item, customer) re-buys online 30-400 days after the return (q21's
    store->return->web chain, q6/q13's store-vs-web customers)."""
    rng = np.random.default_rng(seed + 34)
    orders = n_web_orders(scale)
    lines_per = rng.integers(1, 9, orders)
    n = int(lines_per.sum())
    order_no = np.repeat(np.arange(1, orders + 1, dtype=np.int64), lines_per)
    o_cust = rng.integers(1, n_customer(scale) + 1, orders).astype(np.int64)
    o_date = (rng.integers(0, _DAYS, orders) + _SK0).astype(np.int64)
    o_time = rng.integers(0, 1440, orders).astype(np.int64)
    o_hdemo = rng.integers(1, 6 * 10 * 5 + 1, orders).astype(np.int64)
    o_page = rng.integers(1, n_web_page(scale) + 1, orders).astype(np.int64)
    rep = lambda a: a[order_no - 1]  # noqa: E731

    item = rng.integers(1, n_item(scale) + 1, n).astype(np.int64)
    cust = rep(o_cust)
    date = rep(o_date)
    time, hdemo, page = rep(o_time), rep(o_hdemo), rep(o_page)

    # replay slice from store_returns
    sr_item = store_returns.column("sr_item_sk").to_numpy(zero_copy_only=False)
    sr_cust = store_returns.column("sr_customer_sk").to_numpy(
        zero_copy_only=False)
    sr_date = store_returns.column("sr_returned_date_sk").to_numpy(
        zero_copy_only=False)
    sel = np.flatnonzero(~np.isnan(sr_cust) & ~np.isnan(sr_date))[::3]
    m = sel.shape[0]
    if m:
        r_date = np.minimum(sr_date[sel] + rng.integers(30, 401, m),
                            _SK0 + _DAYS - 1).astype(np.int64)
        item = np.concatenate([item, sr_item[sel].astype(np.int64)])
        cust = np.concatenate([cust, sr_cust[sel].astype(np.int64)])
        date = np.concatenate([date, r_date])
        order_no = np.concatenate(
            [order_no, np.arange(orders + 1, orders + m + 1, dtype=np.int64)])
        time = np.concatenate(
            [time, rng.integers(0, 1440, m).astype(np.int64)])
        hdemo = np.concatenate(
            [hdemo, rng.integers(1, 6 * 10 * 5 + 1, m).astype(np.int64)])
        page = np.concatenate(
            [page, rng.integers(1, n_web_page(scale) + 1, m).astype(np.int64)])
        n += m

    p = _price_lines(rng, n)
    return pa.table({
        "ws_sold_date_sk": _null_some(rng, date, 0.04),
        "ws_sold_time_sk": _null_some(rng, time, 0.04),
        "ws_item_sk": pa.array(item),
        "ws_bill_customer_sk": _null_some(rng, cust, 0.04),
        "ws_ship_hdemo_sk": _null_some(rng, hdemo, 0.04),
        "ws_web_page_sk": _null_some(rng, page, 0.04),
        "ws_warehouse_sk": pa.array(
            rng.integers(1, n_warehouse(scale) + 1, n).astype(np.int64)),
        "ws_order_number": pa.array(order_no),
        "ws_quantity": pa.array(p["qty"]),
        "ws_wholesale_cost": pa.array(p["wholesale"]),
        "ws_list_price": pa.array(p["list_price"]),
        "ws_sales_price": pa.array(p["sales_price"]),
        "ws_ext_discount_amt": pa.array(p["ext_discount"]),
        "ws_ext_sales_price": pa.array(p["ext_sales"]),
        "ws_ext_wholesale_cost": pa.array(p["ext_wholesale"]),
        "ws_ext_list_price": pa.array(p["ext_list"]),
        "ws_net_paid": pa.array(p["ext_sales"]),
    })


def gen_web_returns(scale: float, seed: int, web_sales: pa.Table) -> pa.Table:
    """~8% of web_sales lines refunded (q16 left-joins on order+item)."""
    rng = np.random.default_rng(seed + 35)
    n_ws = web_sales.num_rows
    take = np.flatnonzero(rng.random(n_ws) < 0.08)
    order = web_sales.column("ws_order_number").to_numpy(zero_copy_only=False)
    item = web_sales.column("ws_item_sk").to_numpy(zero_copy_only=False)
    net = web_sales.column("ws_net_paid").to_numpy(zero_copy_only=False)
    k = take.shape[0]
    cash = np.round(net[take] * rng.uniform(0.1, 1.0, k), 2)
    return pa.table({
        "wr_order_number": pa.array(order[take].astype(np.int64)),
        "wr_item_sk": pa.array(item[take].astype(np.int64)),
        "wr_refunded_cash": _null_some(rng, cash, 0.05),
    })


def gen_web_clickstreams(scale: float, seed: int,
                         store_sales: pa.Table) -> pa.Table:
    """Random browsing plus a replay slice: every 4th store-sales line was
    viewed logged-in 1-30 days before purchase with no sale recorded (q12's
    view-then-buy window; q5 profiles clicks per user).

    Random clicks are BURSTY per user — each click lands near one of the
    user's few session anchors (deterministic anchor date/minute), so the
    60-minute sessionization queries (q2/q4/q8/q30) find real multi-click
    sessions the way dsdgen's clickstream does. Item popularity is skewed
    (u^2 mapping) so pair/co-view queries have frequent items."""
    rng = np.random.default_rng(seed + 36)
    n = n_clicks(scale)
    item = (np.minimum(rng.random(n) ** 2 * n_item(scale),
                       n_item(scale) - 1) + 1).astype(np.int64)
    user = rng.integers(1, n_customer(scale) + 1, n).astype(np.int64)
    anchor = rng.integers(0, 3, n)
    a_date = (user * 131 + anchor * 211) % _DAYS + _SK0
    a_min = (user * 97 + anchor * 311) % 1380
    date = a_date.astype(np.int64)
    minute = (a_min + rng.integers(0, 45, n)).astype(np.int64)
    sales = rng.integers(1, 1_000_000, n).astype(np.int64)
    # ~60% of random clicks are views (no sale), ~25% anonymous
    view = rng.random(n) < 0.6
    anon = rng.random(n) < 0.25

    ss_item = store_sales.column("ss_item_sk").to_numpy(zero_copy_only=False)
    ss_cust = store_sales.column("ss_customer_sk").to_numpy(
        zero_copy_only=False)
    ss_date = store_sales.column("ss_sold_date_sk").to_numpy(
        zero_copy_only=False)
    ok = np.flatnonzero(~np.isnan(ss_cust) & ~np.isnan(ss_date))[::4]
    m = ok.shape[0]
    item = np.concatenate([item, ss_item[ok].astype(np.int64)])
    user = np.concatenate([user, ss_cust[ok].astype(np.int64)])
    date = np.concatenate(
        [date, (ss_date[ok] - rng.integers(1, 31, m)).astype(np.int64)])
    minute = np.concatenate(
        [minute, rng.integers(0, 1440, m).astype(np.int64)])
    sales = np.concatenate([sales, np.zeros(m, dtype=np.int64)])
    view = np.concatenate([view, np.ones(m, dtype=bool)])
    anon = np.concatenate([anon, np.zeros(m, dtype=bool)])
    n += m

    return pa.table({
        "wcs_click_date_sk": pa.array(date),
        "wcs_click_time_sk": pa.array(minute),
        "wcs_sales_sk": pa.array(sales, mask=view),
        "wcs_item_sk": _null_some(rng, item, 0.03),
        "wcs_web_page_sk": pa.array(
            rng.integers(1, n_web_page(scale) + 1, n).astype(np.int64)),
        "wcs_user_sk": pa.array(user, mask=anon),
    })


def gen_inventory(scale: float, seed: int) -> pa.Table:
    """Weekly per-item/warehouse snapshots for 2001 (the year q22/q23 probe).
    Zero-inflated Poisson with per-item rates: low-rate items clear q23's
    stddev/mean >= 1.3 bar, high-rate ones don't."""
    rng = np.random.default_rng(seed + 37)
    items = min(n_item(scale), 400)  # bound the cross product
    warehouses = n_warehouse(scale)
    week_starts = np.arange(date_sk(datetime.date(2001, 1, 1)),
                            date_sk(datetime.date(2002, 1, 1)), 7,
                            dtype=np.int64)
    ii, ww, dd = np.meshgrid(np.arange(1, items + 1, dtype=np.int64),
                             np.arange(1, warehouses + 1, dtype=np.int64),
                             week_starts, indexing="ij")
    n = ii.size
    lam = np.exp(rng.uniform(np.log(0.3), np.log(60.0), items))
    qty = rng.poisson(lam[ii.ravel() - 1]).astype(np.int32)
    return pa.table({
        "inv_date_sk": pa.array(dd.ravel()),
        "inv_item_sk": pa.array(ii.ravel()),
        "inv_warehouse_sk": pa.array(ww.ravel()),
        "inv_quantity_on_hand": _null_some(rng, qty, 0.02),
    })


def gen_item_marketprices(scale: float, seed: int,
                          item: pa.Table) -> pa.Table:
    """~3 competitor price records per item, consecutive date ranges (q24
    measures quantity sold inside/outside each record's window)."""
    rng = np.random.default_rng(seed + 38)
    n_i = item.num_rows
    price = item.column("i_current_price").to_numpy(zero_copy_only=False)
    per = rng.integers(2, 5, n_i)
    n = int(per.sum())
    isk = np.repeat(np.arange(1, n_i + 1, dtype=np.int64), per)
    comp_price = np.round(price[isk - 1] * rng.uniform(0.7, 1.3, n), 2)
    start = (rng.integers(0, _DAYS - 120, n) + _SK0).astype(np.int64)
    length = rng.integers(30, 121, n).astype(np.int64)
    return pa.table({
        "imp_sk": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "imp_item_sk": pa.array(isk),
        "imp_competitor_price": _null_some(rng, comp_price, 0.05),
        "imp_start_date": pa.array(start),
        "imp_end_date": pa.array(start + length),
    })


def gen_all(scale: float = 0.002, seed: int = 0) -> Dict[str, pa.Table]:
    store_sales = gen_store_sales(scale, seed)
    store_returns = gen_store_returns(scale, seed, store_sales)
    web_sales = gen_web_sales(scale, seed, store_returns)
    item = _extend_item(gen_item(scale, seed), seed)
    return {
        "date_dim": gen_date_dim(),
        "time_dim": gen_time_dim(),
        "item": item,
        "customer": _extend_customer(gen_customer(scale, seed), seed),
        "customer_address": gen_customer_address(scale, seed),
        "customer_demographics": gen_customer_demographics(),
        "household_demographics": gen_household_demographics(),
        "store": gen_store(scale, seed),
        "promotion": gen_promotion(scale, seed),
        "warehouse": gen_warehouse(scale, seed),
        "web_page": gen_web_page(scale, seed),
        "product_reviews": gen_product_reviews(scale, seed),
        "store_sales": store_sales,
        "store_returns": store_returns,
        "web_sales": web_sales,
        "web_returns": gen_web_returns(scale, seed, web_sales),
        "web_clickstreams": gen_web_clickstreams(scale, seed, store_sales),
        "inventory": gen_inventory(scale, seed),
        "item_marketprices": gen_item_marketprices(scale, seed, item),
    }
