"""TPC-DS query suite over the DataFrame API: the full 99-query inventory
spanning the store, catalog and web channels, returns, and inventory.

Reference analog: TpcdsLikeSpark.scala (the reference ships ~100 "Like"
queries as raw SQL through Catalyst; this engine has no SQL frontend, so each
is the standard DataFrame translation of the same query text), keeping the
same predicates, groupings and orderings. Constants are adapted to the
generator where its pools differ from dsdgen's (date windows shifted into the
1998-2003 calendar, state/manufact/brand lists drawn from the generated
pools), noted inline per query.
"""
from __future__ import annotations

import datetime
from typing import Dict

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

col, lit, when = F.col, F.lit, F.when


def q3(t):
    return (t["date_dim"].filter(col("d_moy") == 11)
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manufact_id") == 128),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "sum_agg")
            .sort("d_year", col("sum_agg").desc(), "brand_id")
            .limit(100))


def q7(t):
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].filter((col("p_channel_email") == "N")
                                  | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .join(cd, [("ss_cdemo_sk", "cd_demo_sk")])
            .join(promo, [("ss_promo_sk", "p_promo_sk")])
            .groupBy("i_item_id")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .sort("i_item_id").limit(100))


def q19(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1998))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 8),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")],
                  )
            .filter(F.substring("ca_zip", 1, 5) != F.substring("s_zip", 1, 5))
            .groupBy("i_brand", "i_brand_id", "i_manufact_id", "i_manufact")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "i_manufact_id",
                    "i_manufact", "ext_price")
            .sort(col("ext_price").desc(), "brand", "brand_id",
                  "i_manufact_id", "i_manufact")
            .limit(100))


def _ticket_counts(t, date_filter, hd_filter, store_filter):
    """Shared inner block of q34/q73: count items per (ticket, customer)."""
    return (t["store_sales"]
            .join(t["date_dim"].filter(date_filter),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(store_filter),
                  [("ss_store_sk", "s_store_sk")])
            .join(t["household_demographics"].filter(hd_filter),
                  [("ss_hdemo_sk", "hd_demo_sk")])
            .groupBy("ss_ticket_number", "ss_customer_sk")
            .agg(F.count().alias("cnt")))


def q34(t):
    dn = _ticket_counts(
        t,
        (((col("d_dom") >= 1) & (col("d_dom") <= 3))
         | ((col("d_dom") >= 25) & (col("d_dom") <= 28)))
        & col("d_year").isin(1999, 2000, 2001),
        (col("hd_buy_potential").isin(">10000", "unknown"))
        & (col("hd_vehicle_count") > 0)
        & (when(col("hd_vehicle_count") > 0,
                col("hd_dep_count") / col("hd_vehicle_count"))
           .otherwise(None) > 1.2),
        col("s_county") == "Williamson County")
    return (dn.filter((col("cnt") >= 15) & (col("cnt") <= 20))
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort("c_last_name", "c_first_name", "c_salutation",
                  col("c_preferred_cust_flag").desc(), "ss_ticket_number"))


def q42(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 2000))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 1),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("s"))
            .sort(col("s").desc(), "d_year", "i_category_id", "i_category")
            .limit(100))


def q46(t):
    dn = (t["store_sales"]
          .join(t["date_dim"].filter(col("d_dow").isin(5, 6)
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter(col("s_city").isin("Fairview", "Midway")),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                   col("ca_city").alias("bought_city"))
          .agg(F.sum("ss_coupon_amt").alias("amt"),
               F.sum("ss_net_profit").alias("profit")))
    return (dn.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "ca_city", "bought_city",
                  "ss_ticket_number")
            .limit(100))


def q52(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 2000))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 1),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price")
            .sort("d_year", col("ext_price").desc(), "brand_id")
            .limit(100))


def q55(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1999))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 28),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price")
            .sort(col("ext_price").desc(), "brand_id")
            .limit(100))


def _weekly_store_sales(t):
    day = lambda n: F.sum(when(col("d_day_name") == n,  # noqa: E731
                               col("ss_sales_price")).otherwise(None))
    return (t["store_sales"]
            .join(t["date_dim"], [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("d_week_seq", "ss_store_sk")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales")))


def q59(t):
    wss = _weekly_store_sales(t)
    weeks = (t["date_dim"].select("d_week_seq", "d_month_seq").distinct())

    def year_slice(lo, hi, suffix):
        cols = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
        sel = [col("s_store_name").alias(f"s_store_name{suffix}"),
               col("d_week_seq").alias(f"d_week_seq{suffix}"),
               col("s_store_id").alias(f"s_store_id{suffix}")]
        sel += [col(f"{c}_sales").alias(f"{c}_sales{suffix}") for c in cols]
        return (wss
                .join(weeks.filter((col("d_month_seq") >= lo)
                                   & (col("d_month_seq") <= hi)),
                      [("d_week_seq", "d_week_seq")])
                .join(t["store"], [("ss_store_sk", "s_store_sk")])
                .select(*sel))

    y = year_slice(1212, 1223, "1")
    x = year_slice(1224, 1235, "2")
    joined = y.join(x, [("s_store_id1", "s_store_id2")]).filter(
        col("d_week_seq1") == col("d_week_seq2") - 52)
    ratio = lambda c: (col(f"{c}_sales1") / col(f"{c}_sales2")).alias(f"{c}_r")  # noqa: E731
    return (joined.select("s_store_name1", "s_store_id1", "d_week_seq1",
                          *[ratio(c) for c in
                            ("sun", "mon", "tue", "wed", "thu", "fri", "sat")])
            .sort("s_store_name1", "s_store_id1", "d_week_seq1")
            .limit(100))


def q65(t):
    # d_month_seq window shifted into the generator calendar (reference uses
    # 1176..1187, which predates the 1998 epoch here)
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("ss_store_sk", "ss_item_sk")
            .agg(F.sum("ss_sales_price").alias("revenue")))
    avg_rev = (base.groupBy(col("ss_store_sk").alias("sb_store_sk"))
               .agg(F.avg("revenue").alias("ave")))
    return (base.join(avg_rev, [("ss_store_sk", "sb_store_sk")])
            .filter(col("revenue") <= col("ave") * 0.1)
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .select("s_store_name", "i_item_desc", "revenue",
                    "i_current_price", "i_wholesale_cost", "i_brand")
            .sort("s_store_name", "i_item_desc")
            .limit(100))


def q68(t):
    dn = (t["store_sales"]
          .join(t["date_dim"].filter(((col("d_dom") >= 1) & (col("d_dom") <= 2))
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter(col("s_city").isin("Midway", "Fairview")),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                   col("ca_city").alias("bought_city"))
          .agg(F.sum("ss_ext_sales_price").alias("extended_price"),
               F.sum("ss_ext_list_price").alias("list_price"),
               F.sum("ss_ext_tax").alias("extended_tax")))
    return (dn.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "extended_price", "extended_tax",
                    "list_price")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(t):
    dn = _ticket_counts(
        t,
        ((col("d_dom") >= 1) & (col("d_dom") <= 2))
        & col("d_year").isin(1999, 2000, 2001),
        (col("hd_buy_potential").isin(">10000", "unknown"))
        & (col("hd_vehicle_count") > 0)
        & (when(col("hd_vehicle_count") > 0,
                col("hd_dep_count") / col("hd_vehicle_count"))
           .otherwise(None) > 1),
        col("s_county").isin("Williamson County", "Franklin Parish",
                             "Bronx County", "Orange County"))
    return (dn.filter((col("cnt") >= 1) & (col("cnt") <= 5))
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort(col("cnt").desc(), "c_last_name"))


def q79(t):
    ms = (t["store_sales"]
          .join(t["date_dim"].filter((col("d_dow") == 1)
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter((col("s_number_employees") >= 200)
                                  & (col("s_number_employees") <= 295)),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "s_city")
          .agg(F.sum("ss_coupon_amt").alias("amt"),
               F.sum("ss_net_profit").alias("profit")))
    return (ms.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name",
                    F.substring("s_city", 1, 30).alias("city"),
                    "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "city", col("profit").desc())
            .limit(100))


def q89(t):
    cls_match = (
        (col("i_category").isin("Books", "Electronics", "Sports")
         & col("i_class").isin("computers", "stereo", "football"))
        | (col("i_category").isin("Men", "Jewelry", "Women")
           & col("i_class").isin("shirts", "birdal", "dresses")))
    base = (t["store_sales"]
            .join(t["item"].filter(cls_match), [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter(col("d_year") == 1999),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy("i_category", "i_class", "i_brand", "s_store_name",
                     "s_company_name", "d_moy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    w = Window.partitionBy("i_category", "i_brand", "s_store_name",
                           "s_company_name")
    tmp = base.select("i_category", "i_class", "i_brand", "s_store_name",
                      "s_company_name", "d_moy", "sum_sales",
                      F.avg("sum_sales").over(w).alias("avg_monthly_sales"))
    dev = when(col("avg_monthly_sales") != 0.0,
               F.abs(col("sum_sales") - col("avg_monthly_sales"))
               / col("avg_monthly_sales")).otherwise(None)
    return (tmp.filter(dev > 0.1)
            .select("i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy", "sum_sales",
                    "avg_monthly_sales",
                    (col("sum_sales") - col("avg_monthly_sales")).alias("_d"))
            .sort("_d", "s_store_name")
            .drop("_d")
            .limit(100))


def q96(t):
    return (t["store_sales"]
            .join(t["time_dim"].filter((col("t_hour") == 20)
                                       & (col("t_minute") >= 30)),
                  [("ss_sold_time_sk", "t_time_sk")])
            .join(t["household_demographics"].filter(col("hd_dep_count") == 7),
                  [("ss_hdemo_sk", "hd_demo_sk")])
            .join(t["store"].filter(col("s_store_name") == "ese"),
                  [("ss_store_sk", "s_store_sk")])
            .agg(F.count().alias("cnt")))


def q98(t):
    lo = datetime.date(1999, 2, 22)
    hi = lo + datetime.timedelta(days=30)
    base = (t["store_sales"]
            .join(t["item"].filter(col("i_category").isin("Sports", "Books",
                                                          "Home")),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("i_item_id", "i_item_desc", "i_category", "i_class",
                     "i_current_price")
            .agg(F.sum("ss_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (base.select("i_item_desc", "i_category", "i_class",
                        "i_current_price", "itemrevenue", "i_item_id",
                        (col("itemrevenue") * 100.0
                         / F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio")
            .drop("i_item_id"))


def q43(t):
    day = lambda n: F.sum(when(col("d_day_name") == n,  # noqa: E731
                               col("ss_sales_price")).otherwise(None))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                  [("ss_store_sk", "s_store_sk")])
            .groupBy("s_store_name", "s_store_id")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


# ---------------------------------------------------------------------------
# catalog / web channel queries (generator constants adapted to the pools:
# state lists -> the generator's state pool, manufact ids -> the 1..n_item
# cycle, reason desc -> the generated reason strings; noted per query)
# ---------------------------------------------------------------------------

def q15(t):
    zips = ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792"]
    cond = (F.substring("ca_zip", 1, 5).isin(*zips)
            | col("ca_state").isin("CA", "WA", "GA")
            | (col("cs_sales_price") > 500))
    return (t["catalog_sales"]
            .join(t["customer"], [("cs_bill_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk",
                                           "ca_address_sk")])
            .join(t["date_dim"].filter((col("d_qoy") == 2)
                                       & (col("d_year") == 2001)),
                  [("cs_sold_date_sk", "d_date_sk")])
            .filter(cond)
            .groupBy("ca_zip")
            .agg(F.sum("cs_sales_price").alias("sum_sales_price"))
            .sort("ca_zip").limit(100))


def _shipping_report(sales, returns, prefix, t, extra_join, state):
    """Shared q16/q94 shape: distinct orders shipping to a state within 60
    days, from orders spanning >1 warehouse (exists), never returned
    (not exists)."""
    p = prefix
    lo = datetime.date(2002, 2, 1) if p == "cs" else datetime.date(1999, 2, 1)
    hi = lo + datetime.timedelta(days=60)
    multi_wh = (sales
                .select(col(f"{p}_order_number").alias("o2"),
                        col(f"{p}_warehouse_sk").alias("w2"))
                .filter(col("w2").isNotNull())
                .groupBy("o2").agg(F.countDistinct("w2").alias("nw"))
                .filter(col("nw") >= 2).select("o2"))
    base = (sales
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [(f"{p}_ship_date_sk", "d_date_sk")])
            .join(t["customer_address"].filter(col("ca_state") == state),
                  [(f"{p}_ship_addr_sk", "ca_address_sk")])
            .join(extra_join[0], [extra_join[1]])
            .join(multi_wh, [(f"{p}_order_number", "o2")], "leftsemi")
            .join(returns, [(f"{p}_order_number", "ro")], "leftanti"))
    return (base.agg(
        F.countDistinct(f"{p}_order_number").alias("order_count"),
        F.sum(f"{p}_ext_ship_cost").alias("total_shipping_cost"),
        F.sum(f"{p}_net_profit").alias("total_net_profit")))


def q16(t):
    cc = t["call_center"].filter(col("cc_county") == "Williamson County")
    wr = t["catalog_returns"].select(col("cr_order_number").alias("ro"))
    return _shipping_report(t["catalog_sales"], wr, "cs", t,
                            (cc, ("cs_call_center_sk", "cc_call_center_sk")),
                            "GA")


def q94(t):
    # state IL -> GA (generator state pool); web company 'pri' is in the pool
    ws = t["web_site"].filter(col("web_company_name") == "pri")
    wr = t["web_returns"].select(col("wr_order_number").alias("ro"))
    return _shipping_report(t["web_sales"], wr, "ws", t,
                            (ws, ("ws_web_site_sk", "web_site_sk")), "GA")


def q18(t):
    # birth months / state list adapted to the generator pools
    cd1 = t["customer_demographics"].filter(
        (col("cd_gender") == "F") & (col("cd_education_status") == "Unknown"))
    cust = t["customer"].filter(col("c_birth_month").isin(1, 6, 8, 9, 12, 2))
    return (t["catalog_sales"]
            .join(t["date_dim"].filter(col("d_year") == 1998),
                  [("cs_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("cs_item_sk", "i_item_sk")])
            .join(cd1.select(col("cd_demo_sk").alias("cd1_sk"),
                             col("cd_dep_count").alias("cd1_dep_count")),
                  [("cs_bill_cdemo_sk", "cd1_sk")])
            .join(cust, [("cs_bill_customer_sk", "c_customer_sk")])
            .join(t["customer_demographics"].select(
                col("cd_demo_sk").alias("cd2_sk")),
                [("c_current_cdemo_sk", "cd2_sk")])
            .join(t["customer_address"].filter(
                col("ca_state").isin("TN", "IN", "SD", "OH", "TX", "GA")),
                [("c_current_addr_sk", "ca_address_sk")])
            .rollup("i_item_id", "ca_country", "ca_state", "ca_county")
            .agg(F.avg("cs_quantity").alias("agg1"),
                 F.avg("cs_list_price").alias("agg2"),
                 F.avg("cs_coupon_amt").alias("agg3"),
                 F.avg("cs_sales_price").alias("agg4"),
                 F.avg("cs_net_profit").alias("agg5"),
                 F.avg("c_birth_year").alias("agg6"),
                 F.avg("cd1_dep_count").alias("agg7"))
            .sort("ca_country", "ca_state", "ca_county", "i_item_id")
            .limit(100))


def q20(t):
    lo = datetime.date(1999, 2, 22)
    hi = lo + datetime.timedelta(days=30)
    base = (t["catalog_sales"]
            .join(t["item"].filter(col("i_category").isin("Sports", "Books",
                                                          "Home")),
                  [("cs_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [("cs_sold_date_sk", "d_date_sk")])
            .groupBy("i_item_id", "i_item_desc", "i_category", "i_class",
                     "i_current_price")
            .agg(F.sum("cs_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (base.select("i_item_id", "i_item_desc", "i_category", "i_class",
                        "i_current_price", "itemrevenue",
                        (col("itemrevenue") * 100.0
                         / F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio")
            .limit(100))


def q21(t):
    pivot = lit(datetime.date(2000, 3, 11))
    dd = t["date_dim"].filter(
        (F.datediff(col("d_date"), pivot) >= -30)
        & (F.datediff(col("d_date"), pivot) <= 30))
    base = (t["inventory"]
            .join(t["warehouse"], [("inv_warehouse_sk", "w_warehouse_sk")])
            .join(t["item"].filter((col("i_current_price") >= 0.99)
                                   & (col("i_current_price") <= 1.49)),
                  [("inv_item_sk", "i_item_sk")])
            .join(dd, [("inv_date_sk", "d_date_sk")])
            .groupBy("w_warehouse_name", "i_item_id")
            .agg(F.sum(when(col("d_date") < pivot,
                            col("inv_quantity_on_hand")).otherwise(0))
                 .alias("inv_before"),
                 F.sum(when(col("d_date") >= pivot,
                            col("inv_quantity_on_hand")).otherwise(0))
                 .alias("inv_after")))
    ratio = when(col("inv_before") > 0,
                 col("inv_after") / col("inv_before")).otherwise(None)
    return (base.filter((ratio >= 2.0 / 3.0) & (ratio <= 3.0 / 2.0))
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def _sold_returned_rebought(t, d1_filter, d2_filter, d3_filter, aggs):
    """Shared q25/q29 chain: store sale -> store return -> catalog re-buy by
    the same customer."""
    ss = (t["store_sales"]
          .join(t["date_dim"].filter(d1_filter).select("d_date_sk"),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["item"], [("ss_item_sk", "i_item_sk")])
          .join(t["store"], [("ss_store_sk", "s_store_sk")]))
    sr = (t["store_returns"]
          .join(t["date_dim"].filter(d2_filter).select(
              col("d_date_sk").alias("d2_sk")),
              [("sr_returned_date_sk", "d2_sk")]))
    cs = (t["catalog_sales"]
          .join(t["date_dim"].filter(d3_filter).select(
              col("d_date_sk").alias("d3_sk")),
              [("cs_sold_date_sk", "d3_sk")]))
    return (ss.join(sr, [("ss_customer_sk", "sr_customer_sk"),
                         ("ss_item_sk", "sr_item_sk"),
                         ("ss_ticket_number", "sr_ticket_number")])
            .join(cs, [("sr_customer_sk", "cs_bill_customer_sk"),
                       ("sr_item_sk", "cs_item_sk")])
            .groupBy("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .agg(*aggs)
            .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .limit(100))


def q25(t):
    return _sold_returned_rebought(
        t,
        (col("d_moy") == 4) & (col("d_year") == 2001),
        (col("d_moy") >= 4) & (col("d_moy") <= 10) & (col("d_year") == 2001),
        (col("d_moy") >= 4) & (col("d_moy") <= 10) & (col("d_year") == 2001),
        [F.sum("ss_net_profit").alias("store_sales_profit"),
         F.sum("sr_net_loss").alias("store_returns_loss"),
         F.sum("cs_net_profit").alias("catalog_sales_profit")])


def q29(t):
    return _sold_returned_rebought(
        t,
        (col("d_moy") == 9) & (col("d_year") == 1999),
        (col("d_moy") >= 9) & (col("d_moy") <= 12) & (col("d_year") == 1999),
        col("d_year").isin(1999, 2000, 2001),
        [F.sum("ss_quantity").alias("store_sales_quantity"),
         F.sum("sr_return_quantity").alias("store_returns_quantity"),
         F.sum("cs_quantity").alias("catalog_sales_quantity")])


def q26(t):
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].filter((col("p_channel_email") == "N")
                                  | (col("p_channel_event") == "N"))
    return (t["catalog_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("cs_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("cs_item_sk", "i_item_sk")])
            .join(cd, [("cs_bill_cdemo_sk", "cd_demo_sk")])
            .join(promo, [("cs_promo_sk", "p_promo_sk")])
            .groupBy("i_item_id")
            .agg(F.avg("cs_quantity").alias("agg1"),
                 F.avg("cs_list_price").alias("agg2"),
                 F.avg("cs_coupon_amt").alias("agg3"),
                 F.avg("cs_sales_price").alias("agg4"))
            .sort("i_item_id").limit(100))


def _excess_discount(t, sales, prefix, manufact_id):
    """Shared q32/q92: discounts above 1.3x the item's window average."""
    p = prefix
    lo = datetime.date(2000, 1, 27)
    hi = lo + datetime.timedelta(days=90)
    dd = (t["date_dim"]
          .filter((col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
          .select("d_date_sk"))
    windowed = sales.join(dd, [(f"{p}_sold_date_sk", "d_date_sk")])
    thresholds = (windowed
                  .groupBy(col(f"{p}_item_sk").alias("th_item"))
                  .agg(F.avg(f"{p}_ext_discount_amt").alias("th_avg"))
                  .select("th_item",
                          (col("th_avg") * 1.3).alias("threshold")))
    return (windowed
            .join(t["item"].filter(col("i_manufact_id") == manufact_id),
                  [(f"{p}_item_sk", "i_item_sk")])
            .join(thresholds, [(f"{p}_item_sk", "th_item")])
            .filter(col(f"{p}_ext_discount_amt") > col("threshold"))
            .agg(F.sum(f"{p}_ext_discount_amt")
                 .alias("excess_discount_amount")))


def q32(t):
    # manufact 977 -> 77 (the generator cycles manufact ids over 1..n_item)
    return _excess_discount(t, t["catalog_sales"], "cs", 77)


def q92(t):
    # manufact 350 -> 50
    return _excess_discount(t, t["web_sales"], "ws", 50)


def q37(t):
    lo = datetime.date(2000, 2, 1)
    hi = lo + datetime.timedelta(days=60)
    # manufact list 677/940/694/808 -> 8/33/58/83 (the generator's planted
    # mid-price band: manufact id == item sk cycle, plants at sk%25==8)
    items = t["item"].filter(
        (col("i_current_price") >= 68) & (col("i_current_price") <= 98)
        & col("i_manufact_id").isin(8, 33, 58, 83))
    inv = (t["inventory"]
           .filter((col("inv_quantity_on_hand") >= 100)
                   & (col("inv_quantity_on_hand") <= 500))
           .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                      & (col("d_date") <= lit(hi))),
                 [("inv_date_sk", "d_date_sk")]))
    return (items.join(inv, [("i_item_sk", "inv_item_sk")])
            .join(t["catalog_sales"], [("i_item_sk", "cs_item_sk")],
                  "leftsemi")
            .select("i_item_id", "i_item_desc", "i_current_price")
            .dropDuplicates()
            .sort("i_item_id").limit(100))


def q40(t):
    pivot = datetime.date(2000, 3, 11)
    dd = t["date_dim"].filter(
        (F.datediff(col("d_date"), lit(pivot)) >= -30)
        & (F.datediff(col("d_date"), lit(pivot)) <= 30))
    net = col("cs_sales_price") - F.coalesce(col("cr_refunded_cash"),
                                             lit(0.0))
    return (t["catalog_sales"]
            .join(t["catalog_returns"],
                  [("cs_order_number", "cr_order_number"),
                   ("cs_item_sk", "cr_item_sk")], "left")
            .join(t["warehouse"], [("cs_warehouse_sk", "w_warehouse_sk")])
            .join(t["item"].filter((col("i_current_price") >= 0.99)
                                   & (col("i_current_price") <= 1.49)),
                  [("cs_item_sk", "i_item_sk")])
            .join(dd, [("cs_sold_date_sk", "d_date_sk")])
            .groupBy("w_state", "i_item_id")
            .agg(F.sum(when(col("d_date") < lit(pivot), net).otherwise(0.0))
                 .alias("sales_before"),
                 F.sum(when(col("d_date") >= lit(pivot), net).otherwise(0.0))
                 .alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q45(t):
    zips = ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792"]
    marked = (t["item"]
              .filter(col("i_item_sk").isin(2, 3, 5, 7, 11, 13, 17, 19,
                                            23, 29))
              .select(col("i_item_id").alias("m_id"))
              .withColumn("m_flag", lit(1)))
    return (t["web_sales"]
            .join(t["customer"], [("ws_bill_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk",
                                           "ca_address_sk")])
            .join(t["item"], [("ws_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_qoy") == 2)
                                       & (col("d_year") == 2001)),
                  [("ws_sold_date_sk", "d_date_sk")])
            .join(marked.dropDuplicates(), [("i_item_id", "m_id")], "left")
            .filter(F.substring("ca_zip", 1, 5).isin(*zips)
                    | col("m_flag").isNotNull())
            .groupBy("ca_zip", "ca_city")
            .agg(F.sum("ws_sales_price").alias("sum_ws_sales_price"))
            .sort("ca_zip", "ca_city").limit(100))


def _ship_day_buckets(t, sales, prefix, dim, dim_key, dim_name):
    p = prefix
    days = col(f"{p}_ship_date_sk") - col(f"{p}_sold_date_sk")
    bucket = lambda lo, hi: F.sum(  # noqa: E731
        when(((days > lo) if lo is not None else lit(True))
             & ((days <= hi) if hi is not None else lit(True)), 1)
        .otherwise(0))
    return (sales
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [(f"{p}_ship_date_sk", "d_date_sk")])
            .join(t["warehouse"], [(f"{p}_warehouse_sk", "w_warehouse_sk")])
            .join(t["ship_mode"], [(f"{p}_ship_mode_sk", "sm_ship_mode_sk")])
            .join(dim, [dim_key])
            .groupBy(F.substring("w_warehouse_name", 1, 20).alias("wname"),
                     "sm_type", dim_name)
            .agg(bucket(None, 30).alias("d30"),
                 bucket(30, 60).alias("d31_60"),
                 bucket(60, 90).alias("d61_90"),
                 bucket(90, 120).alias("d91_120"),
                 bucket(120, None).alias("d_over_120"))
            .sort("wname", "sm_type", dim_name)
            .limit(100))


def q62(t):
    return _ship_day_buckets(t, t["web_sales"], "ws", t["web_site"],
                             ("ws_web_site_sk", "web_site_sk"), "web_name")


def q99(t):
    return _ship_day_buckets(t, t["catalog_sales"], "cs", t["call_center"],
                             ("cs_call_center_sk", "cc_call_center_sk"),
                             "cc_name")


def q90(t):
    def slot(h_lo):
        return (t["web_sales"]
                .join(t["household_demographics"]
                      .filter(col("hd_dep_count") == 6),
                      [("ws_ship_hdemo_sk", "hd_demo_sk")])
                .join(t["time_dim"].filter((col("t_hour") >= h_lo)
                                           & (col("t_hour") <= h_lo + 1)),
                      [("ws_sold_time_sk", "t_time_sk")])
                .join(t["web_page"].filter((col("wp_char_count") >= 5000)
                                           & (col("wp_char_count") <= 5200)),
                      [("ws_web_page_sk", "wp_web_page_sk")])
                .agg(F.count().alias("amc" if h_lo == 8 else "pmc")))

    return (slot(8).crossJoin(slot(19))
            .select((col("amc") / col("pmc")).alias("am_pm_ratio")))


def q93(t):
    # reason desc adapted to the generated reason table
    act = when(col("sr_return_quantity").isNotNull(),
               (col("ss_quantity") - col("sr_return_quantity"))
               * col("ss_sales_price")).otherwise(
        col("ss_quantity") * col("ss_sales_price"))
    return (t["store_sales"]
            .join(t["store_returns"],
                  [("ss_item_sk", "sr_item_sk"),
                   ("ss_ticket_number", "sr_ticket_number")], "left")
            .join(t["reason"].filter(
                col("r_reason_desc") == "Package was damaged"),
                [("sr_reason_sk", "r_reason_sk")])
            .select("ss_customer_sk", act.alias("act_sales"))
            .groupBy("ss_customer_sk")
            .agg(F.sum("act_sales").alias("sumsales"))
            .sort("sumsales", "ss_customer_sk")
            .limit(100))


# ---------------------------------------------------------------------------
# multi-channel, window and scalar-subquery queries
# ---------------------------------------------------------------------------

def q6(t):
    month = (t["date_dim"]
             .filter((col("d_year") == 2001) & (col("d_moy") == 1))
             .select("d_month_seq").distinct()
             .withColumnRenamed("d_month_seq", "m_seq"))
    cat_avg = (t["item"].groupBy(col("i_category").alias("cat"))
               .agg(F.avg("i_current_price").alias("cat_avg")))
    pricey = (t["item"].join(cat_avg, [("i_category", "cat")])
              .filter(col("i_current_price") > 1.2 * col("cat_avg"))
              .select("i_item_sk"))
    return (t["store_sales"]
            .join(t["date_dim"].join(month, [("d_month_seq", "m_seq")],
                                     "leftsemi"),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(pricey, [("ss_item_sk", "i_item_sk")], "leftsemi")
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk",
                                           "ca_address_sk")])
            .groupBy(col("ca_state").alias("state"))
            .agg(F.count().alias("cnt"))
            .filter(col("cnt") >= 10)
            .sort("cnt").limit(100))


def q13(t):
    # state triplets adapted to the generator pool
    demo_ok = (((col("cd_marital_status") == "M")
                & (col("cd_education_status") == "Advanced Degree")
                & (col("ss_sales_price") >= 100.0)
                & (col("ss_sales_price") <= 150.0)
                & (col("hd_dep_count") == 3))
               | ((col("cd_marital_status") == "S")
                  & (col("cd_education_status") == "College")
                  & (col("ss_sales_price") >= 50.0)
                  & (col("ss_sales_price") <= 100.0)
                  & (col("hd_dep_count") == 1))
               | ((col("cd_marital_status") == "W")
                  & (col("cd_education_status") == "2 yr Degree")
                  & (col("ss_sales_price") >= 150.0)
                  & (col("ss_sales_price") <= 200.0)
                  & (col("hd_dep_count") == 1)))
    geo_ok = (((col("ca_country") == "United States")
               & col("ca_state").isin("TX", "OH", "GA")
               & (col("ss_net_profit") >= 100)
               & (col("ss_net_profit") <= 200))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("TN", "IN", "SD")
                 & (col("ss_net_profit") >= 150)
                 & (col("ss_net_profit") <= 300))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("LA", "MI", "SC")
                 & (col("ss_net_profit") >= 50)
                 & (col("ss_net_profit") <= 250)))
    return (t["store_sales"]
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["date_dim"].filter(col("d_year") == 2001),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["customer_demographics"], [("ss_cdemo_sk", "cd_demo_sk")])
            .join(t["household_demographics"], [("ss_hdemo_sk", "hd_demo_sk")])
            .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
            .filter(demo_ok & geo_ok)
            .agg(F.avg("ss_quantity").alias("avg_quantity"),
                 F.avg("ss_ext_sales_price").alias("avg_ext_sales_price"),
                 F.avg("ss_ext_wholesale_cost").alias("avg_ext_wholesale"),
                 F.sum("ss_ext_wholesale_cost").alias("sum_ext_wholesale")))


def q17(t):
    ss = (t["store_sales"]
          .join(t["date_dim"].filter(col("d_quarter_name") == "2001Q1")
                .select("d_date_sk"),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["item"], [("ss_item_sk", "i_item_sk")])
          .join(t["store"], [("ss_store_sk", "s_store_sk")]))
    q123 = ("2001Q1", "2001Q2", "2001Q3")
    sr = (t["store_returns"]
          .join(t["date_dim"].filter(col("d_quarter_name").isin(*q123))
                .select(col("d_date_sk").alias("d2_sk")),
                [("sr_returned_date_sk", "d2_sk")]))
    cs = (t["catalog_sales"]
          .join(t["date_dim"].filter(col("d_quarter_name").isin(*q123))
                .select(col("d_date_sk").alias("d3_sk")),
                [("cs_sold_date_sk", "d3_sk")]))
    cov = lambda c: F.stddev(c) / F.avg(c)  # noqa: E731
    return (ss.join(sr, [("ss_customer_sk", "sr_customer_sk"),
                         ("ss_item_sk", "sr_item_sk"),
                         ("ss_ticket_number", "sr_ticket_number")])
            .join(cs, [("sr_customer_sk", "cs_bill_customer_sk"),
                       ("sr_item_sk", "cs_item_sk")])
            .groupBy("i_item_id", "i_item_desc", "s_state")
            .agg(F.count("ss_quantity").alias("store_sales_quantitycount"),
                 F.avg("ss_quantity").alias("store_sales_quantityave"),
                 F.stddev("ss_quantity").alias("store_sales_quantitystdev"),
                 F.count("sr_return_quantity")
                 .alias("store_returns_quantitycount"),
                 F.avg("sr_return_quantity")
                 .alias("store_returns_quantityave"),
                 F.stddev("sr_return_quantity")
                 .alias("store_returns_quantitystdev"),
                 F.count("cs_quantity").alias("catalog_sales_quantitycount"),
                 F.avg("cs_quantity").alias("catalog_sales_quantityave"),
                 F.stddev("cs_quantity").alias("catalog_sales_quantitystdev"))
            .withColumn("store_sales_quantitycov",
                        col("store_sales_quantitystdev")
                        / col("store_sales_quantityave"))
            .withColumn("store_returns_quantitycov",
                        col("store_returns_quantitystdev")
                        / col("store_returns_quantityave"))
            .withColumn("catalog_sales_quantitycov",
                        col("catalog_sales_quantitystdev")
                        / col("catalog_sales_quantityave"))
            .sort("i_item_id", "i_item_desc", "s_state")
            .limit(100))


def q28(t):
    buckets = [
        # (qty_lo, qty_hi, lp_lo, coupon_lo, cost_lo, name)
        (0, 5, 8, 459, 57, "b1"),
        (6, 10, 90, 2323, 31, "b2"),
        (11, 15, 142, 12214, 79, "b3"),
        (16, 20, 135, 6071, 38, "b4"),
        (21, 25, 122, 836, 17, "b5"),
        (26, 30, 154, 7326, 7, "b6"),
    ]

    def bucket(qlo, qhi, lp, cp, wc, name):
        return (t["store_sales"]
                .filter((col("ss_quantity") >= qlo)
                        & (col("ss_quantity") <= qhi)
                        & (((col("ss_list_price") >= lp)
                            & (col("ss_list_price") <= lp + 10))
                           | ((col("ss_coupon_amt") >= cp)
                              & (col("ss_coupon_amt") <= cp + 1000))
                           | ((col("ss_wholesale_cost") >= wc)
                              & (col("ss_wholesale_cost") <= wc + 20))))
                .agg(F.avg("ss_list_price").alias(f"{name}_lp"),
                     F.count("ss_list_price").alias(f"{name}_cnt"),
                     F.countDistinct("ss_list_price").alias(f"{name}_cntd")))

    out = bucket(*buckets[0])
    for b in buckets[1:]:
        out = out.crossJoin(bucket(*b))
    return out.limit(100)


def _channel_union_by(t, key_out, item_filter_col, item_filter_vals,
                      year, moy):
    """Shared q33/q60 shape: per-channel revenue for an item subset, unioned
    and re-aggregated. key_out is 'i_manufact_id' or 'i_item_id'."""
    subset = (t["item"]
              .filter(col(item_filter_col).isin(*item_filter_vals))
              .select(col(key_out).alias("sub_key")).distinct())
    dd = (t["date_dim"]
          .filter((col("d_year") == year) & (col("d_moy") == moy))
          .select("d_date_sk"))
    addr = (t["customer_address"].filter(col("ca_gmt_offset") == -5.0)
            .select("ca_address_sk"))

    def channel(sales, item_k, date_k, addr_k, amount):
        return (sales
                .join(dd, [(date_k, "d_date_sk")], "leftsemi")
                .join(addr, [(addr_k, "ca_address_sk")], "leftsemi")
                .join(t["item"], [(item_k, "i_item_sk")])
                .join(subset, [(key_out, "sub_key")], "leftsemi")
                .groupBy(key_out)
                .agg(F.sum(amount).alias("total_sales")))

    u = (channel(t["store_sales"], "ss_item_sk", "ss_sold_date_sk",
                 "ss_addr_sk", "ss_ext_sales_price")
         .union(channel(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk",
                        "cs_bill_addr_sk", "cs_ext_sales_price"))
         .union(channel(t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
                        "ws_bill_addr_sk", "ws_ext_sales_price")))
    return u.groupBy(key_out).agg(F.sum("total_sales").alias("total_sales"))


def q33(t):
    return (_channel_union_by(t, "i_manufact_id", "i_category",
                              ["Electronics"], 1998, 5)
            .sort("total_sales").limit(100))


def q60(t):
    return (_channel_union_by(t, "i_item_id", "i_category", ["Music"],
                              1998, 9)
            .sort("i_item_id", "total_sales").limit(100))


def _rollup_rank(t, sales, item_k, date_k, value, date_filter, extra_joins):
    """Shared q36/q86 shape: rollup over (category, class) with a rank within
    each hierarchy level. grouping() is derived from the rolled-up nulls
    (generated categories/classes are never null)."""
    base = sales.join(t["date_dim"].filter(date_filter),
                      [(date_k, "d_date_sk")])
    for frame, key in extra_joins:
        base = base.join(frame, [key])
    base = base.join(t["item"], [(item_k, "i_item_sk")])
    rolled = (base.rollup("i_category", "i_class")
              .agg(F.sum(value[0]).alias("_num"),
                   *([F.sum(value[1]).alias("_den")] if value[1] else [])))
    measure = (col("_num") / col("_den")) if value[1] else col("_num")
    lochierarchy = (when(col("i_category").isNull(), 1).otherwise(0)
                    + when(col("i_class").isNull(), 1).otherwise(0))
    tmp = rolled.select(
        measure.alias("total_sum"), "i_category", "i_class",
        lochierarchy.alias("lochierarchy"),
        when(col("i_class").isNotNull(), col("i_category"))
        .otherwise(None).alias("_parent"))
    w = (Window.partitionBy("lochierarchy", "_parent")
         .orderBy(col("total_sum").desc() if value[1] is None
                  else col("total_sum").asc()))
    return (tmp.select("total_sum", "i_category", "i_class", "lochierarchy",
                       F.rank().over(w).alias("rank_within_parent"))
            .sort(col("lochierarchy").desc(),
                  when(col("lochierarchy") == 0, col("i_category"))
                  .otherwise(None),
                  "rank_within_parent")
            .limit(100))


def q36(t):
    return _rollup_rank(
        t, t["store_sales"], "ss_item_sk", "ss_sold_date_sk",
        ("ss_net_profit", "ss_ext_sales_price"),
        col("d_year") == 2001,
        [(t["store"].filter(col("s_state") == "TN"),
          ("ss_store_sk", "s_store_sk"))])


def q86(t):
    return _rollup_rank(
        t, t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
        ("ws_net_paid", None),
        (col("d_month_seq") >= 1200) & (col("d_month_seq") <= 1211),
        [])


def q44(t):
    # store 4 -> the generator's 6-store pool includes it
    base = (t["store_sales"].filter(col("ss_store_sk") == 4)
            .groupBy(col("ss_item_sk").alias("item_sk"))
            .agg(F.avg("ss_net_profit").alias("rank_col")))
    floor_ = (t["store_sales"]
              .filter((col("ss_store_sk") == 4) & col("ss_addr_sk").isNull())
              .groupBy("ss_store_sk")
              .agg(F.avg("ss_net_profit").alias("f_avg"))
              .select((col("f_avg") * 0.9).alias("floor_val")))
    qualified = (base.crossJoin(floor_)
                 .filter(col("rank_col") > col("floor_val")))
    asc = (qualified.select(
        "item_sk", F.rank().over(Window.orderBy(col("rank_col").asc()))
        .alias("rnk")).filter(col("rnk") < 11))
    desc = (qualified.select(
        col("item_sk").alias("item_sk_d"),
        F.rank().over(Window.orderBy(col("rank_col").desc()))
        .alias("rnk_d")).filter(col("rnk_d") < 11))
    return (asc.join(desc, [("rnk", "rnk_d")])
            .join(t["item"].select(col("i_item_sk").alias("i1_sk"),
                                   col("i_product_name").alias(
                                       "best_performing")),
                  [("item_sk", "i1_sk")])
            .join(t["item"].select(col("i_item_sk").alias("i2_sk"),
                                   col("i_product_name").alias(
                                       "worst_performing")),
                  [("item_sk_d", "i2_sk")])
            .select("rnk", "best_performing", "worst_performing")
            .sort("rnk").limit(100))


def q47(t):
    v1 = (t["store_sales"]
          .join(t["item"], [("ss_item_sk", "i_item_sk")])
          .join(t["date_dim"].filter(
              (col("d_year") == 1999)
              | ((col("d_year") == 1998) & (col("d_moy") == 12))
              | ((col("d_year") == 2000) & (col("d_moy") == 1))),
              [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"], [("ss_store_sk", "s_store_sk")])
          .groupBy("i_category", "i_brand", "s_store_name", "s_company_name",
                   "d_year", "d_moy")
          .agg(F.sum("ss_sales_price").alias("sum_sales")))
    wavg = Window.partitionBy("i_category", "i_brand", "s_store_name",
                              "s_company_name", "d_year")
    wrank = (Window.partitionBy("i_category", "i_brand", "s_store_name",
                                "s_company_name")
             .orderBy("d_year", "d_moy"))
    v1 = v1.select("i_category", "i_brand", "s_store_name", "s_company_name",
                   "d_year", "d_moy", "sum_sales",
                   F.avg("sum_sales").over(wavg).alias("avg_monthly_sales"),
                   F.rank().over(wrank).alias("rn"))
    lagf = v1.select(col("i_category").alias("lc"), col("i_brand").alias("lb"),
                     col("s_store_name").alias("lsn"),
                     col("s_company_name").alias("lcn"),
                     col("rn").alias("lrn"),
                     col("sum_sales").alias("psum"))
    leadf = v1.select(col("i_category").alias("dc"),
                      col("i_brand").alias("db"),
                      col("s_store_name").alias("dsn"),
                      col("s_company_name").alias("dcn"),
                      col("rn").alias("drn"),
                      col("sum_sales").alias("nsum"))
    v2 = (v1.withColumn("rn_prev", col("rn") - 1)
          .withColumn("rn_next", col("rn") + 1)
          .join(lagf, [("i_category", "lc"), ("i_brand", "lb"),
                       ("s_store_name", "lsn"), ("s_company_name", "lcn"),
                       ("rn_prev", "lrn")])
          .join(leadf, [("i_category", "dc"), ("i_brand", "db"),
                        ("s_store_name", "dsn"), ("s_company_name", "dcn"),
                        ("rn_next", "drn")]))
    dev = when(col("avg_monthly_sales") > 0,
               F.abs(col("sum_sales") - col("avg_monthly_sales"))
               / col("avg_monthly_sales")).otherwise(None)
    return (v2.filter((col("d_year") == 1999)
                      & (col("avg_monthly_sales") > 0) & (dev > 0.1))
            .select("i_category", "i_brand", "s_store_name", "s_company_name",
                    "d_year", "d_moy", "avg_monthly_sales", "sum_sales",
                    "psum", "nsum",
                    (col("sum_sales") - col("avg_monthly_sales")).alias("_d"))
            .sort("_d", "s_store_name").drop("_d")
            .limit(100))


def _manager_monthly_deviation(t, group_key, time_key):
    """Shared q53/q63 shape."""
    cls_a = (col("i_category").isin("Books", "Children", "Electronics")
             & col("i_class").isin("personal", "portable", "reference",
                                   "self-help")
             & col("i_brand").isin("scholaramalgamalg #14",
                                   "scholaramalgamalg #7",
                                   "exportiunivamalg #9",
                                   "scholaramalgamalg #9"))
    cls_b = (col("i_category").isin("Women", "Music", "Men")
             & col("i_class").isin("accessories", "classical", "fragrances",
                                   "pants")
             & col("i_brand").isin("amalgimporto #1", "edu packscholar #1",
                                   "exportiimporto #1", "importoamalg #1"))
    base = (t["store_sales"]
            .join(t["item"].filter(cls_a | cls_b),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy(group_key, time_key)
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    w = Window.partitionBy(group_key)
    tmp = base.select(group_key, "sum_sales",
                      F.avg("sum_sales").over(w).alias("avg_sales"))
    dev = when(col("avg_sales") > 0,
               F.abs(col("sum_sales") - col("avg_sales"))
               / col("avg_sales")).otherwise(None)
    return tmp.filter(dev > 0.1)


def q53(t):
    return (_manager_monthly_deviation(t, "i_manufact_id", "d_qoy")
            .withColumnRenamed("avg_sales", "avg_quarterly_sales")
            .sort("avg_quarterly_sales", "sum_sales", "i_manufact_id")
            .limit(100))


def q63(t):
    return (_manager_monthly_deviation(t, "i_manager_id", "d_moy")
            .withColumnRenamed("avg_sales", "avg_monthly_sales")
            .sort("i_manager_id", "avg_monthly_sales", "sum_sales")
            .limit(100))


def q69(t):
    dd = (t["date_dim"]
          .filter((col("d_year") == 2001) & (col("d_moy") >= 4)
                  & (col("d_moy") <= 6))
          .select("d_date_sk"))
    bought_store = (t["store_sales"]
                    .join(dd, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
                    .select(col("ss_customer_sk").alias("b_sk")))
    bought_web = (t["web_sales"]
                  .join(dd, [("ws_sold_date_sk", "d_date_sk")], "leftsemi")
                  .select(col("ws_bill_customer_sk").alias("b_sk")))
    bought_cat = (t["catalog_sales"]
                  .join(dd, [("cs_sold_date_sk", "d_date_sk")], "leftsemi")
                  .select(col("cs_ship_customer_sk").alias("b_sk")))
    return (t["customer"]
            .join(t["customer_address"].filter(
                col("ca_state").isin("TN", "GA", "SD")),
                [("c_current_addr_sk", "ca_address_sk")])
            .join(t["customer_demographics"],
                  [("c_current_cdemo_sk", "cd_demo_sk")])
            .join(bought_store, [("c_customer_sk", "b_sk")], "leftsemi")
            .join(bought_web, [("c_customer_sk", "b_sk")], "leftanti")
            .join(bought_cat, [("c_customer_sk", "b_sk")], "leftanti")
            .groupBy("cd_gender", "cd_marital_status", "cd_education_status",
                     "cd_purchase_estimate", "cd_credit_rating")
            .agg(F.count().alias("cnt1"))
            .select("cd_gender", "cd_marital_status", "cd_education_status",
                    "cnt1", "cd_purchase_estimate",
                    col("cnt1").alias("cnt2"), "cd_credit_rating",
                    col("cnt1").alias("cnt3"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating")
            .limit(100))


def q76(t):
    def channel(sales, null_col, item_k, date_k, price, name):
        return (sales.filter(col(null_col).isNull())
                .join(t["item"], [(item_k, "i_item_sk")])
                .join(t["date_dim"], [(date_k, "d_date_sk")])
                .select(lit(name).alias("channel"),
                        lit(null_col).alias("col_name"), "d_year", "d_qoy",
                        "i_category", col(price).alias("ext_sales_price")))

    u = (channel(t["store_sales"], "ss_store_sk", "ss_item_sk",
                 "ss_sold_date_sk", "ss_ext_sales_price", "store")
         .union(channel(t["web_sales"], "ws_ship_customer_sk", "ws_item_sk",
                        "ws_sold_date_sk", "ws_ext_sales_price", "web"))
         .union(channel(t["catalog_sales"], "cs_ship_addr_sk", "cs_item_sk",
                        "cs_sold_date_sk", "cs_ext_sales_price", "catalog")))
    return (u.groupBy("channel", "col_name", "d_year", "d_qoy", "i_category")
            .agg(F.count().alias("sales_cnt"),
                 F.sum("ext_sales_price").alias("sales_amt"))
            .sort("channel", "col_name", "d_year", "d_qoy", "i_category")
            .limit(100))


def q88(t):
    hd = t["household_demographics"].filter(
        ((col("hd_dep_count") == 4) & (col("hd_vehicle_count") <= 6))
        | ((col("hd_dep_count") == 2) & (col("hd_vehicle_count") <= 4))
        | ((col("hd_dep_count") == 0) & (col("hd_vehicle_count") <= 2)))
    store = t["store"].filter(col("s_store_name") == "ese")

    def half_hour(hour, first_half, name):
        td = t["time_dim"].filter(
            (col("t_hour") == hour)
            & ((col("t_minute") < 30) if first_half
               else (col("t_minute") >= 30)))
        return (t["store_sales"]
                .join(td, [("ss_sold_time_sk", "t_time_sk")], "leftsemi")
                .join(hd, [("ss_hdemo_sk", "hd_demo_sk")], "leftsemi")
                .join(store, [("ss_store_sk", "s_store_sk")], "leftsemi")
                .agg(F.count().alias(name)))

    slots = [(8, False, "h8_30_to_9"), (9, True, "h9_to_9_30"),
             (9, False, "h9_30_to_10"), (10, True, "h10_to_10_30"),
             (10, False, "h10_30_to_11"), (11, True, "h11_to_11_30"),
             (11, False, "h11_30_to_12"), (12, True, "h12_to_12_30")]
    out = half_hour(*slots[0])
    for s in slots[1:]:
        out = out.crossJoin(half_hour(*s))
    return out


def q41(t):
    """Manufacturers with qualifying item variants (correlated count(*)>0 as
    a semi-join on i_manufact). Manufact-id window 738..778 -> 38..78 (the
    generator cycles ids over 1..n_item)."""
    combo = lambda cat, colors, units, sizes: (  # noqa: E731
        (col("i_category") == cat) & col("i_color").isin(*colors)
        & col("i_units").isin(*units) & col("i_size").isin(*sizes))
    variants = (combo("Women", ("powder", "khaki"), ("Ounce", "Oz"),
                      ("medium", "extra large"))
                | combo("Women", ("brown", "honeydew"), ("Bunch", "Ton"),
                        ("N/A", "small"))
                | combo("Men", ("floral", "deep"), ("N/A", "Dozen"),
                        ("petite", "large"))
                | combo("Men", ("light", "cornflower"), ("Box", "Pound"),
                        ("medium", "extra large"))
                | combo("Women", ("midnight", "snow"), ("Pallet", "Gross"),
                        ("medium", "extra large"))
                | combo("Women", ("cyan", "papaya"), ("Cup", "Dram"),
                        ("N/A", "small"))
                | combo("Men", ("orange", "frosted"), ("Each", "Tbl"),
                        ("petite", "large"))
                | combo("Men", ("forest", "ghost"), ("Lb", "Bundle"),
                        ("medium", "extra large")))
    qualifying = (t["item"].filter(variants)
                  .select(col("i_manufact").alias("qm")).distinct())
    return (t["item"]
            .filter((col("i_manufact_id") >= 38)
                    & (col("i_manufact_id") <= 78))
            .join(qualifying, [("i_manufact", "qm")], "leftsemi")
            .select("i_product_name").distinct()
            .sort("i_product_name").limit(100))


def q48(t):
    # state triplets adapted to the generator pool
    demo_ok = (((col("cd_marital_status") == "M")
                & (col("cd_education_status") == "4 yr Degree")
                & (col("ss_sales_price") >= 100.0)
                & (col("ss_sales_price") <= 150.0))
               | ((col("cd_marital_status") == "D")
                  & (col("cd_education_status") == "2 yr Degree")
                  & (col("ss_sales_price") >= 50.0)
                  & (col("ss_sales_price") <= 100.0))
               | ((col("cd_marital_status") == "S")
                  & (col("cd_education_status") == "College")
                  & (col("ss_sales_price") >= 150.0)
                  & (col("ss_sales_price") <= 200.0)))
    geo_ok = (((col("ca_country") == "United States")
               & col("ca_state").isin("TX", "OH", "GA")
               & (col("ss_net_profit") >= 0) & (col("ss_net_profit") <= 2000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("TN", "IN", "SD")
                 & (col("ss_net_profit") >= 150)
                 & (col("ss_net_profit") <= 3000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("LA", "MI", "CA")
                 & (col("ss_net_profit") >= 50)
                 & (col("ss_net_profit") <= 25000)))
    return (t["store_sales"]
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["customer_demographics"], [("ss_cdemo_sk", "cd_demo_sk")])
            .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
            .filter(demo_ok & geo_ok)
            .agg(F.sum("ss_quantity").alias("sum_quantity")))


def q50(t):
    days = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    bucket = lambda lo, hi: F.sum(  # noqa: E731
        when(((days > lo) if lo is not None else lit(True))
             & ((days <= hi) if hi is not None else lit(True)), 1)
        .otherwise(0))
    return (t["store_sales"]
            .join(t["store_returns"]
                  .join(t["date_dim"].filter((col("d_year") == 2001)
                                             & (col("d_moy") == 8))
                        .select(col("d_date_sk").alias("d2_sk")),
                        [("sr_returned_date_sk", "d2_sk")]),
                  [("ss_ticket_number", "sr_ticket_number"),
                   ("ss_item_sk", "sr_item_sk"),
                   ("ss_customer_sk", "sr_customer_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy("s_store_name", "s_company_id", "s_street_number",
                     "s_street_name", "s_street_type", "s_suite_number",
                     "s_city", "s_county", "s_state", "s_zip")
            .agg(bucket(None, 30).alias("d30"),
                 bucket(30, 60).alias("d31_60"),
                 bucket(60, 90).alias("d61_90"),
                 bucket(90, 120).alias("d91_120"),
                 bucket(120, None).alias("d_over_120"))
            .sort("s_store_name", "s_company_id", "s_street_number",
                  "s_street_name", "s_street_type", "s_suite_number",
                  "s_city", "s_county", "s_state", "s_zip")
            .limit(100))


def q61(t):
    def slice_sales(with_promo):
        base = (t["store_sales"]
                .join(t["date_dim"].filter((col("d_year") == 1998)
                                           & (col("d_moy") == 11)),
                      [("ss_sold_date_sk", "d_date_sk")])
                .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                      [("ss_store_sk", "s_store_sk")])
                .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
                .join(t["customer_address"]
                      .filter(col("ca_gmt_offset") == -5.0),
                      [("c_current_addr_sk", "ca_address_sk")])
                .join(t["item"].filter(col("i_category") == "Jewelry"),
                      [("ss_item_sk", "i_item_sk")]))
        if with_promo:
            base = base.join(
                t["promotion"].filter((col("p_channel_dmail") == "Y")
                                      | (col("p_channel_email") == "Y")
                                      | (col("p_channel_tv") == "Y")),
                [("ss_promo_sk", "p_promo_sk")])
        name = "promotions" if with_promo else "total"
        return base.agg(F.sum("ss_ext_sales_price").alias(name))

    return (slice_sales(True).crossJoin(slice_sales(False))
            .select("promotions", "total",
                    (col("promotions") / col("total") * 100.0)
                    .alias("promo_pct")))


def q71(t):
    dd = (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1999))
          .select("d_date_sk"))

    def channel(sales, price, item_k, date_k, time_k):
        return (sales.join(dd, [(date_k, "d_date_sk")], "leftsemi")
                .select(col(price).alias("ext_price"),
                        col(item_k).alias("sold_item_sk"),
                        col(time_k).alias("time_sk")))

    u = (channel(t["web_sales"], "ws_ext_sales_price", "ws_item_sk",
                 "ws_sold_date_sk", "ws_sold_time_sk")
         .union(channel(t["catalog_sales"], "cs_ext_sales_price",
                        "cs_item_sk", "cs_sold_date_sk", "cs_sold_time_sk"))
         .union(channel(t["store_sales"], "ss_ext_sales_price", "ss_item_sk",
                        "ss_sold_date_sk", "ss_sold_time_sk")))
    return (u.join(t["item"].filter(col("i_manager_id") == 1),
                   [("sold_item_sk", "i_item_sk")])
            .join(t["time_dim"].filter(col("t_meal_time")
                                       .isin("breakfast", "dinner")),
                  [("time_sk", "t_time_sk")])
            .groupBy("i_brand", "i_brand_id", "t_hour", "t_minute")
            .agg(F.sum("ext_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "t_hour", "t_minute",
                    "ext_price")
            .sort(col("ext_price").desc(), "brand_id"))


def q82(t):
    lo = datetime.date(2000, 5, 25)
    hi = lo + datetime.timedelta(days=60)
    # price 62..92 overlaps the generator's planted 68-98 band; manufact list
    # 129/270/821/423 -> the planted ids 8/33/58/83 (like q37)
    items = t["item"].filter(
        (col("i_current_price") >= 62) & (col("i_current_price") <= 92)
        & col("i_manufact_id").isin(8, 33, 58, 83))
    inv = (t["inventory"]
           .filter((col("inv_quantity_on_hand") >= 100)
                   & (col("inv_quantity_on_hand") <= 500))
           .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                      & (col("d_date") <= lit(hi))),
                 [("inv_date_sk", "d_date_sk")]))
    return (items.join(inv, [("i_item_sk", "inv_item_sk")])
            .join(t["store_sales"], [("i_item_sk", "ss_item_sk")], "leftsemi")
            .select("i_item_id", "i_item_desc", "i_current_price")
            .dropDuplicates()
            .sort("i_item_id").limit(100))


def q87(t):
    dd = (t["date_dim"].filter((col("d_month_seq") >= 1200)
                               & (col("d_month_seq") <= 1211))
          .select("d_date_sk", "d_date"))

    def bought(sales, cust_k, date_k, names=("c_last_name", "c_first_name",
                                             "d_date")):
        return (sales.join(dd, [(date_k, "d_date_sk")])
                .join(t["customer"], [(cust_k, "c_customer_sk")])
                .select(col("c_last_name").alias(names[0]),
                        col("c_first_name").alias(names[1]),
                        col("d_date").alias(names[2])).distinct())

    store = bought(t["store_sales"], "ss_customer_sk", "ss_sold_date_sk")
    catalog = bought(t["catalog_sales"], "cs_bill_customer_sk",
                     "cs_sold_date_sk", ("ln", "fn", "dt"))
    web = bought(t["web_sales"], "ws_bill_customer_sk", "ws_sold_date_sk",
                 ("ln", "fn", "dt"))
    keys = [("c_last_name", "ln"), ("c_first_name", "fn"), ("d_date", "dt")]
    return (store.join(catalog, keys, "leftanti")
            .join(web, keys, "leftanti")
            .agg(F.count().alias("cnt")))


def q97(t):
    dd = (t["date_dim"].filter((col("d_month_seq") >= 1200)
                               & (col("d_month_seq") <= 1211))
          .select("d_date_sk"))
    ssci = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")],
                                  "leftsemi")
            .select(col("ss_customer_sk").alias("s_cust"),
                    col("ss_item_sk").alias("s_item")).distinct())
    csci = (t["catalog_sales"].join(dd, [("cs_sold_date_sk", "d_date_sk")],
                                    "leftsemi")
            .select(col("cs_bill_customer_sk").alias("c_cust"),
                    col("cs_item_sk").alias("c_item")).distinct())
    j = ssci.join(csci, [("s_cust", "c_cust"), ("s_item", "c_item")], "full")
    return j.agg(
        F.sum(when(col("s_item").isNotNull() & col("c_item").isNull(), 1)
              .otherwise(0)).alias("store_only"),
        F.sum(when(col("s_item").isNull() & col("c_item").isNotNull(), 1)
              .otherwise(0)).alias("catalog_only"),
        F.sum(when(col("s_item").isNotNull() & col("c_item").isNotNull(), 1)
              .otherwise(0)).alias("store_and_catalog"))


# QUERIES registry built at end of module (after all additions)


# ---------------------------------------------------------------------------
# round-2 additions: the remaining reference inventory (TpcdsLikeSpark.scala
# q1..q99). Like the first 58, each is the DataFrame translation of the spec
# text with constants adapted to the generator's pools/date range, noted
# inline. The "Like" simplifications mirror the reference's own Like variants
# (dropped literal zip lists, reduced repeated blocks) without changing the
# query's join/aggregation shape.
# ---------------------------------------------------------------------------
def q1(t):
    ctr = (t["store_returns"]
           .join(t["date_dim"].filter(col("d_year") == 2000),
                 [("sr_returned_date_sk", "d_date_sk")])
           .groupBy(col("sr_customer_sk").alias("ctr_customer_sk"),
                    col("sr_store_sk").alias("ctr_store_sk"))
           .agg(F.sum("sr_return_amt").alias("ctr_total_return")))
    avg_ctr = (ctr.groupBy(col("ctr_store_sk").alias("avg_store_sk"))
               .agg(F.avg("ctr_total_return").alias("avg_ret"))
               .select("avg_store_sk", (col("avg_ret") * 1.2).alias("thr")))
    return (ctr.join(avg_ctr, [("ctr_store_sk", "avg_store_sk")])
            .filter(col("ctr_total_return") > col("thr"))
            .join(t["store"].filter(col("s_state") == "TN"),
                  [("ctr_store_sk", "s_store_sk")])
            .join(t["customer"], [("ctr_customer_sk", "c_customer_sk")])
            .select("c_customer_id").sort("c_customer_id").limit(100))


def _weekly_sums(t, sales, date_col, price_col):
    d = t["date_dim"]
    j = sales.join(d, [(date_col, "d_date_sk")])
    day = lambda n: F.sum(when(col("d_day_name") == n, col(price_col))
                          .otherwise(0.0))
    return (j.groupBy("d_week_seq")
            .agg(day("Sunday").alias("sun"), day("Monday").alias("mon"),
                 day("Tuesday").alias("tue"), day("Wednesday").alias("wed"),
                 day("Thursday").alias("thu"), day("Friday").alias("fri"),
                 day("Saturday").alias("sat")))


def q2(t):
    wscs = (_weekly_sums(t, t["web_sales"], "ws_sold_date_sk",
                         "ws_ext_sales_price")
            .union(_weekly_sums(t, t["catalog_sales"], "cs_sold_date_sk",
                                "cs_ext_sales_price"))
            .groupBy("d_week_seq")
            .agg(*[F.sum(c).alias(c) for c in
                   ("sun", "mon", "tue", "wed", "thu", "fri", "sat")]))
    weeks1 = (t["date_dim"].filter(col("d_year") == 1999)
              .select("d_week_seq").distinct())
    weeks2 = (t["date_dim"].filter(col("d_year") == 2000)
              .select(col("d_week_seq").alias("w2")).distinct())
    y = (wscs.join(weeks1, "d_week_seq", "leftsemi")
         .select(col("d_week_seq").alias("wk1"),
                 *[col(c).alias(c + "1")
                   for c in ("sun", "mon", "tue", "wed", "thu", "fri",
                             "sat")]))
    z = (wscs.join(weeks2.withColumnRenamed("w2", "d_week_seq"),
                   "d_week_seq", "leftsemi")
         .select((col("d_week_seq") - 53).alias("wk2"),
                 *[col(c).alias(c + "2")
                   for c in ("sun", "mon", "tue", "wed", "thu", "fri",
                             "sat")]))
    j = y.join(z, [("wk1", "wk2")])
    sel = [col("wk1").alias("d_week_seq")]
    for c in ("sun", "mon", "tue", "wed", "thu", "fri", "sat"):
        sel.append(F.round(when(col(c + "2") != 0,
                                col(c + "1") / col(c + "2"))
                           .otherwise(None), 2).alias("r_" + c))
    return j.select(*sel).sort("d_week_seq")


def _year_total(t, sales, cust_k, date_k, amount, year, tag):
    """q4/q11/q74 CTE: per-customer yearly totals for one channel."""
    return (sales
            .join(t["date_dim"].filter(col("d_year") == year),
                  [(date_k, "d_date_sk")])
            .join(t["customer"], [(cust_k, "c_customer_sk")])
            .groupBy(col("c_customer_id").alias(f"{tag}_id"))
            .agg(F.sum(amount).alias(f"{tag}_total"),
                 F.first(col("c_preferred_cust_flag"))
                 .alias(f"{tag}_flag")))


def q11(t):
    ss_amt = col("ss_ext_list_price") - col("ss_ext_discount_amt")
    ws_amt = col("ws_ext_list_price") - col("ws_ext_discount_amt")
    s1 = _year_total(t, t["store_sales"], "ss_customer_sk",
                     "ss_sold_date_sk", ss_amt, 1999, "s1")
    s2 = _year_total(t, t["store_sales"], "ss_customer_sk",
                     "ss_sold_date_sk", ss_amt, 2000, "s2")
    w1 = _year_total(t, t["web_sales"], "ws_bill_customer_sk",
                     "ws_sold_date_sk", ws_amt, 1999, "w1")
    w2 = _year_total(t, t["web_sales"], "ws_bill_customer_sk",
                     "ws_sold_date_sk", ws_amt, 2000, "w2")
    j = (s1.filter(col("s1_total") > 0)
         .join(s2, [("s1_id", "s2_id")])
         .join(w1.filter(col("w1_total") > 0), [("s1_id", "w1_id")])
         .join(w2, [("s1_id", "w2_id")])
         .filter((col("w2_total") / col("w1_total"))
                 > (col("s2_total") / col("s1_total"))))
    return (j.select(col("s1_id").alias("customer_id"),
                     col("s2_flag").alias("customer_preferred_cust_flag"))
            .sort("customer_id").limit(100))


def q4(t):
    ss_amt = ((col("ss_ext_list_price") - col("ss_ext_wholesale_cost")
               - col("ss_ext_discount_amt") + col("ss_ext_sales_price")) / 2)
    cs_amt = ((col("cs_ext_list_price") - col("cs_ext_wholesale_cost")
               - col("cs_ext_discount_amt") + col("cs_ext_sales_price")) / 2)
    ws_amt = ((col("ws_ext_list_price") - col("ws_ext_wholesale_cost")
               - col("ws_ext_discount_amt") + col("ws_ext_sales_price")) / 2)
    s1 = _year_total(t, t["store_sales"], "ss_customer_sk",
                     "ss_sold_date_sk", ss_amt, 1999, "s1")
    s2 = _year_total(t, t["store_sales"], "ss_customer_sk",
                     "ss_sold_date_sk", ss_amt, 2000, "s2")
    c1 = _year_total(t, t["catalog_sales"], "cs_bill_customer_sk",
                     "cs_sold_date_sk", cs_amt, 1999, "c1")
    c2 = _year_total(t, t["catalog_sales"], "cs_bill_customer_sk",
                     "cs_sold_date_sk", cs_amt, 2000, "c2")
    w1 = _year_total(t, t["web_sales"], "ws_bill_customer_sk",
                     "ws_sold_date_sk", ws_amt, 1999, "w1")
    w2 = _year_total(t, t["web_sales"], "ws_bill_customer_sk",
                     "ws_sold_date_sk", ws_amt, 2000, "w2")
    j = (s1.filter(col("s1_total") > 0)
         .join(s2, [("s1_id", "s2_id")])
         .join(c1.filter(col("c1_total") > 0), [("s1_id", "c1_id")])
         .join(c2, [("s1_id", "c2_id")])
         .join(w1.filter(col("w1_total") > 0), [("s1_id", "w1_id")])
         .join(w2, [("s1_id", "w2_id")])
         .filter(((col("c2_total") / col("c1_total"))
                  > (col("s2_total") / col("s1_total")))
                 & ((col("c2_total") / col("c1_total"))
                    > (col("w2_total") / col("w1_total")))))
    return (j.select(col("s1_id").alias("customer_id"),
                     col("s2_flag").alias("customer_preferred_cust_flag"))
            .sort("customer_id").limit(100))


def q74(t):
    s1 = _year_total(t, t["store_sales"], "ss_customer_sk",
                     "ss_sold_date_sk", col("ss_net_paid"), 1999, "s1")
    s2 = _year_total(t, t["store_sales"], "ss_customer_sk",
                     "ss_sold_date_sk", col("ss_net_paid"), 2000, "s2")
    w1 = _year_total(t, t["web_sales"], "ws_bill_customer_sk",
                     "ws_sold_date_sk", col("ws_net_paid"), 1999, "w1")
    w2 = _year_total(t, t["web_sales"], "ws_bill_customer_sk",
                     "ws_sold_date_sk", col("ws_net_paid"), 2000, "w2")
    j = (s1.filter(col("s1_total") > 0)
         .join(s2, [("s1_id", "s2_id")])
         .join(w1.filter(col("w1_total") > 0), [("s1_id", "w1_id")])
         .join(w2, [("s1_id", "w2_id")])
         .filter((col("w2_total") / col("w1_total"))
                 > (col("s2_total") / col("s1_total"))))
    return j.select(col("s1_id").alias("customer_id")).sort(
        "customer_id").limit(100)


def _channel_profit(t, sales, returns, date_k, ret_date_k, id_k, ret_id_k,
                    sales_price, sales_profit, ret_amt, ret_loss, id_name,
                    lo, hi):
    d = t["date_dim"].filter((col("d_date") >= lit(lo))
                             & (col("d_date") <= lit(hi)))
    s = (sales.join(d, [(date_k, "d_date_sk")])
         .groupBy(col(id_k).alias(id_name))
         .agg(F.sum(sales_price).alias("sales"),
              F.sum(sales_profit).alias("profit")))
    r = (returns.join(d, [(ret_date_k, "d_date_sk")])
         .groupBy(col(ret_id_k).alias(id_name + "_r"))
         .agg(F.sum(ret_amt).alias("returns_amt"),
              F.sum(ret_loss).alias("net_loss")))
    return (s.join(r, [(id_name, id_name + "_r")], "left")
            .select(col(id_name),
                    col("sales"),
                    F.coalesce(col("returns_amt"), lit(0.0)).alias("returns_amt"),
                    (col("profit") - F.coalesce(col("net_loss"), lit(0.0)))
                    .alias("profit")))


def q5(t):
    lo, hi = datetime.date(2000, 8, 1), datetime.date(2000, 8, 14)
    ssr = _channel_profit(
        t, t["store_sales"], t["store_returns"], "ss_sold_date_sk",
        "sr_returned_date_sk", "ss_store_sk", "sr_store_sk",
        col("ss_ext_sales_price"), col("ss_net_profit"),
        col("sr_return_amt"), col("sr_net_loss"), "sid", lo, hi)
    csr = _channel_profit(
        t, t["catalog_sales"], t["catalog_returns"], "cs_sold_date_sk",
        "cr_returned_date_sk", "cs_catalog_page_sk", "cr_catalog_page_sk",
        col("cs_ext_sales_price"), col("cs_net_profit"),
        col("cr_return_amount"), col("cr_net_loss"), "sid", lo, hi)
    wsr = _channel_profit(
        t, t["web_sales"], t["web_returns"], "ws_sold_date_sk",
        "wr_returned_date_sk", "ws_web_site_sk", "wr_web_page_sk",
        col("ws_ext_sales_price"), col("ws_net_profit"),
        col("wr_return_amt"), col("wr_net_loss"), "sid", lo, hi)
    u = (ssr.withColumn("channel", lit("store channel"))
         .union(csr.withColumn("channel", lit("catalog channel")))
         .union(wsr.withColumn("channel", lit("web channel"))))
    return (u.rollup("channel", "sid")
            .agg(F.sum("sales").alias("sales"),
                 F.sum("returns_amt").alias("returns_amt"),
                 F.sum("profit").alias("profit"))
            .sort("channel", "sid").limit(100))


def q8(t):
    pref_zips = (t["customer"].filter(col("c_preferred_cust_flag") == "Y")
                 .join(t["customer_address"],
                       [("c_current_addr_sk", "ca_address_sk")])
                 .groupBy(F.substring("ca_zip", 1, 5).alias("zip5"))
                 .agg(F.count().alias("cnt"))
                 .filter(col("cnt") > 10)
                 .select("zip5"))
    return (t["store_sales"]
            .join(t["date_dim"].filter((col("d_qoy") == 2)
                                       & (col("d_year") == 1998)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .withColumn("s_zip5", F.substring("s_zip", 1, 5))
            .join(pref_zips, [("s_zip5", "zip5")], "leftsemi")
            .groupBy("s_store_name")
            .agg(F.sum("ss_net_profit").alias("net_profit"))
            .sort("s_store_name"))


def q9(t):
    ss = t["store_sales"]
    buckets = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    aggs = []
    for i, (lo, hi) in enumerate(buckets, 1):
        in_b = (col("ss_quantity") >= lo) & (col("ss_quantity") <= hi)
        aggs.append(F.sum(when(in_b, 1).otherwise(0)).alias(f"cnt{i}"))
        aggs.append(F.avg(when(in_b, col("ss_ext_discount_amt"))
                          .otherwise(None)).alias(f"disc{i}"))
        aggs.append(F.avg(when(in_b, col("ss_net_paid"))
                          .otherwise(None)).alias(f"paid{i}"))
    stats = ss.agg(*aggs)
    sel = []
    for i in range(1, 6):
        sel.append(when(col(f"cnt{i}") > 62316685 / 1000,
                        col(f"disc{i}")).otherwise(col(f"paid{i}"))
                   .alias(f"bucket{i}"))
    return (t["reason"].filter(col("r_reason_sk") == 1)
            .select("r_reason_sk").crossJoin(stats).select(*sel))


def q10(t):
    dd = (t["date_dim"].filter((col("d_year") == 2002)
                               & (col("d_moy") >= 1) & (col("d_moy") <= 4))
          .select("d_date_sk"))
    ss_c = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")],
                                  "leftsemi")
            .select(col("ss_customer_sk").alias("k")).distinct())
    ws_c = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")],
                                "leftsemi")
            .select(col("ws_bill_customer_sk").alias("k")).distinct())
    cs_c = (t["catalog_sales"].join(dd, [("cs_sold_date_sk", "d_date_sk")],
                                    "leftsemi")
            .select(col("cs_bill_customer_sk").alias("k")).distinct())
    other = ws_c.union(cs_c).distinct()
    cust = (t["customer"]
            .join(t["customer_address"].filter(
                col("ca_county").isin("Williamson County", "Walker County",
                                      "Ziebach County")),
                [("c_current_addr_sk", "ca_address_sk")])
            .join(ss_c, [("c_customer_sk", "k")], "leftsemi")
            .join(other, [("c_customer_sk", "k")], "leftsemi"))
    return (cust.join(t["customer_demographics"],
                      [("c_current_cdemo_sk", "cd_demo_sk")])
            .groupBy("cd_gender", "cd_marital_status", "cd_education_status",
                     "cd_purchase_estimate", "cd_credit_rating")
            .agg(F.count().alias("cnt"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating"))


def q12(t):
    # q98's shape over the web channel (reference stringizes the same text)
    base = (t["web_sales"]
            .join(t["item"].filter(col("i_category").isin(
                "Sports", "Books", "Home")), [("ws_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter(
                (col("d_date") >= lit(datetime.date(1999, 2, 22)))
                & (col("d_date") <= lit(datetime.date(1999, 3, 24)))),
                [("ws_sold_date_sk", "d_date_sk")])
            .groupBy("i_item_id", "i_item_desc", "i_category", "i_class",
                     "i_current_price")
            .agg(F.sum("ws_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (base.select("i_item_id", "i_item_desc", "i_category", "i_class",
                        "i_current_price", "itemrevenue",
                        (col("itemrevenue") * 100.0
                         / F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio")
            .limit(100))


def q14(t):
    # cross-channel items (the intersect CTE): brand/class/category sold in
    # all three channels during 1999-2000
    def ich(sales, item_k, date_k):
        return (sales
                .join(t["date_dim"].filter(col("d_year").isin(1999, 2000)),
                      [(date_k, "d_date_sk")])
                .join(t["item"], [(item_k, "i_item_sk")])
                .select("i_brand_id", "i_class_id_", "i_category_id")
                .distinct())
    # the generator has no i_class_id; class name stands in (noted adaption)
    items = t["item"].withColumn("i_class_id_", col("i_class"))
    tt = dict(t)
    tt["item"] = items

    def ich2(sales, item_k, date_k, tag):
        return (sales
                .join(t["date_dim"].filter(col("d_year").isin(1999, 2000)),
                      [(date_k, "d_date_sk")])
                .join(items, [(item_k, "i_item_sk")])
                .select(col("i_brand_id").alias(f"{tag}b"),
                        col("i_class_id_").alias(f"{tag}c"),
                        col("i_category_id").alias(f"{tag}g"))
                .distinct())
    ssi = ich2(t["store_sales"], "ss_item_sk", "ss_sold_date_sk", "s")
    csi = ich2(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk", "c")
    wsi = ich2(t["web_sales"], "ws_item_sk", "ws_sold_date_sk", "w")
    cross = (ssi.join(csi, [("sb", "cb"), ("sc", "cc"), ("sg", "cg")],
                      "leftsemi")
             .join(wsi, [("sb", "wb"), ("sc", "wc"), ("sg", "wg")],
                   "leftsemi"))
    cross_items = (items.join(
        cross, [("i_brand_id", "sb"), ("i_class_id_", "sc"),
                ("i_category_id", "sg")], "leftsemi")
        .select("i_item_sk"))
    # avg sales threshold over the three channels
    ss_q = (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year").isin(1999, 2000)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .select((col("ss_quantity") * col("ss_list_price")).alias("v")))
    cs_q = (t["catalog_sales"]
            .join(t["date_dim"].filter(col("d_year").isin(1999, 2000)),
                  [("cs_sold_date_sk", "d_date_sk")])
            .select((col("cs_quantity") * col("cs_list_price")).alias("v")))
    ws_q = (t["web_sales"]
            .join(t["date_dim"].filter(col("d_year").isin(1999, 2000)),
                  [("ws_sold_date_sk", "d_date_sk")])
            .select((col("ws_quantity") * col("ws_list_price")).alias("v")))
    avg_sales = ss_q.union(cs_q).union(ws_q).agg(F.avg("v").alias("avg_v"))
    dd = t["date_dim"].filter((col("d_year") == 2000) & (col("d_moy") == 11))
    ch = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")],
                                "leftsemi")
          .join(cross_items, [("ss_item_sk", "i_item_sk")], "leftsemi")
          .groupBy(col("ss_item_sk").alias("item"))
          .agg(F.sum(col("ss_quantity") * col("ss_list_price"))
               .alias("sales"), F.count().alias("number_sales")))
    return (ch.crossJoin(avg_sales).filter(col("sales") > col("avg_v"))
            .agg(F.sum("sales").alias("total_sales"),
                 F.sum("number_sales").alias("total_number")))




def q22(t):
    return (t["inventory"]
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("inv_date_sk", "d_date_sk")])
            .join(t["item"], [("inv_item_sk", "i_item_sk")])
            .rollup("i_product_name", "i_brand", "i_class", "i_category")
            .agg(F.avg("inv_quantity_on_hand").alias("qoh"))
            .sort("qoh", "i_product_name", "i_brand", "i_class",
                  "i_category")
            .limit(100))


def q23(t):
    dd4 = t["date_dim"].filter(col("d_year").isin(1998, 1999, 2000, 2001))
    # frequent items: sold on more than 4 distinct dates in 4 years
    freq = (t["store_sales"]
            .join(dd4, [("ss_sold_date_sk", "d_date_sk")])
            .groupBy(col("ss_item_sk").alias("item_sk"))
            .agg(F.countDistinct("d_date_sk").alias("cnt"))
            .filter(col("cnt") > 4).select("item_sk"))
    totals = (t["store_sales"]
              .groupBy(col("ss_customer_sk").alias("csk"))
              .agg(F.sum(col("ss_quantity") * col("ss_sales_price"))
                   .alias("csales")))
    mx = totals.agg(F.max("csales").alias("tpcds_cmax"))
    best = (totals.crossJoin(mx)
            .filter(col("csales") > 0.5 * col("tpcds_cmax"))
            .select("csk"))
    dd1 = t["date_dim"].filter((col("d_year") == 2000) & (col("d_moy") == 2))
    cs = (t["catalog_sales"]
          .join(dd1, [("cs_sold_date_sk", "d_date_sk")], "leftsemi")
          .join(freq, [("cs_item_sk", "item_sk")], "leftsemi")
          .join(best, [("cs_bill_customer_sk", "csk")], "leftsemi")
          .select((col("cs_quantity") * col("cs_list_price")).alias("v")))
    ws = (t["web_sales"]
          .join(dd1, [("ws_sold_date_sk", "d_date_sk")], "leftsemi")
          .join(freq, [("ws_item_sk", "item_sk")], "leftsemi")
          .join(best, [("ws_bill_customer_sk", "csk")], "leftsemi")
          .select((col("ws_quantity") * col("ws_list_price")).alias("v")))
    return cs.union(ws).agg(F.sum("v").alias("total"))


def q24(t):
    ssales = (t["store_sales"]
              .join(t["store_returns"], [("ss_ticket_number",
                                          "sr_ticket_number"),
                                         ("ss_item_sk", "sr_item_sk")])
              .join(t["store"], [("ss_store_sk", "s_store_sk")])
              .join(t["item"], [("ss_item_sk", "i_item_sk")])
              .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
              .groupBy("c_last_name", "c_first_name", "s_store_name",
                       "i_color")
              .agg(F.sum("ss_net_paid").alias("netpaid")))
    avg_np = (ssales.agg(F.avg("netpaid").alias("avg_np"))
              .select((col("avg_np") * 0.05).alias("thr")))
    return (ssales.filter(col("i_color") == "blue")
            .crossJoin(avg_np)
            .filter(col("netpaid") > col("thr"))
            .select("c_last_name", "c_first_name", "s_store_name", "netpaid")
            .sort("c_last_name", "c_first_name", "s_store_name"))


def q27(t):
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2002),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(col("s_state").isin("TN", "GA", "SD")),
                  [("ss_store_sk", "s_store_sk")])
            .join(cd, [("ss_cdemo_sk", "cd_demo_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .rollup("i_item_id", "s_state")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .sort("i_item_id", "s_state").limit(100))


def q30(t):
    ctr = (t["web_returns"]
           .join(t["date_dim"].filter(col("d_year") == 2000),
                 [("wr_returned_date_sk", "d_date_sk")])
           .join(t["customer"].select("c_customer_sk", "c_current_addr_sk"),
                 [("wr_returning_customer_sk", "c_customer_sk")])
           .join(t["customer_address"],
                 [("c_current_addr_sk", "ca_address_sk")])
           .groupBy(col("wr_returning_customer_sk").alias("ctr_cust"),
                    col("ca_state").alias("ctr_state"))
           .agg(F.sum("wr_return_amt").alias("ctr_total")))
    avg_ctr = (ctr.groupBy(col("ctr_state").alias("avg_state"))
               .agg(F.avg("ctr_total").alias("avg_ret"))
               .select("avg_state", (col("avg_ret") * 1.2).alias("thr")))
    return (ctr.join(avg_ctr, [("ctr_state", "avg_state")])
            .filter(col("ctr_total") > col("thr"))
            .join(t["customer"], [("ctr_cust", "c_customer_sk")])
            .join(t["customer_address"].filter(col("ca_state") == "GA")
                  .select(col("ca_address_sk").alias("home_addr")),
                  [("c_current_addr_sk", "home_addr")], "leftsemi")
            .select("c_customer_id", "c_salutation", "c_first_name",
                    "c_last_name", "ctr_total")
            .sort("c_customer_id", "c_salutation", "c_first_name",
                  "c_last_name", "ctr_total"))


def q31(t):
    def county_q(sales, date_k, addr_k, price, year, q, tag):
        return (sales
                .join(t["date_dim"].filter((col("d_year") == year)
                                           & (col("d_qoy") == q)),
                      [(date_k, "d_date_sk")])
                .join(t["customer_address"], [(addr_k, "ca_address_sk")])
                .groupBy(col("ca_county").alias(f"{tag}_county"))
                .agg(F.sum(price).alias(f"{tag}_sales")))
    ss1 = county_q(t["store_sales"], "ss_sold_date_sk", "ss_addr_sk",
                   col("ss_ext_sales_price"), 2000, 1, "ss1")
    ss2 = county_q(t["store_sales"], "ss_sold_date_sk", "ss_addr_sk",
                   col("ss_ext_sales_price"), 2000, 2, "ss2")
    ws1 = county_q(t["web_sales"], "ws_sold_date_sk", "ws_bill_addr_sk",
                   col("ws_ext_sales_price"), 2000, 1, "ws1")
    ws2 = county_q(t["web_sales"], "ws_sold_date_sk", "ws_bill_addr_sk",
                   col("ws_ext_sales_price"), 2000, 2, "ws2")
    j = (ss1.join(ss2, [("ss1_county", "ss2_county")])
         .join(ws1, [("ss1_county", "ws1_county")])
         .join(ws2, [("ss1_county", "ws2_county")])
         .filter((col("ws1_sales") > 0) & (col("ss1_sales") > 0))
         .filter((col("ws2_sales") / col("ws1_sales"))
                 > (col("ss2_sales") / col("ss1_sales"))))
    return (j.select(col("ss1_county").alias("county"),
                     (col("ws2_sales") / col("ws1_sales")).alias("web_g"),
                     (col("ss2_sales") / col("ss1_sales")).alias("store_g"))
            .sort("county"))


def q35(t):
    dd = (t["date_dim"].filter((col("d_year") == 2002) & (col("d_qoy") < 4))
          .select("d_date_sk"))
    ss_c = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")],
                                  "leftsemi")
            .select(col("ss_customer_sk").alias("k")).distinct())
    ws_c = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")],
                                "leftsemi")
            .select(col("ws_bill_customer_sk").alias("k")).distinct())
    cs_c = (t["catalog_sales"].join(dd, [("cs_sold_date_sk", "d_date_sk")],
                                    "leftsemi")
            .select(col("cs_bill_customer_sk").alias("k")).distinct())
    other = ws_c.union(cs_c).distinct()
    cust = (t["customer"]
            .join(ss_c, [("c_customer_sk", "k")], "leftsemi")
            .join(other, [("c_customer_sk", "k")], "leftsemi")
            .join(t["customer_address"],
                  [("c_current_addr_sk", "ca_address_sk")])
            .join(t["customer_demographics"],
                  [("c_current_cdemo_sk", "cd_demo_sk")]))
    return (cust.groupBy("ca_state", "cd_gender", "cd_marital_status",
                         "cd_dep_count")
            .agg(F.count().alias("cnt"),
                 F.min("cd_dep_count").alias("mn"),
                 F.max("cd_dep_count").alias("mx"),
                 F.avg("cd_dep_count").alias("av"))
            .sort("ca_state", "cd_gender", "cd_marital_status",
                  "cd_dep_count")
            .limit(100))


def q38(t):
    dd = (t["date_dim"].filter((col("d_month_seq") >= 1200)
                               & (col("d_month_seq") <= 1211))
          .select("d_date_sk"))

    def custs(sales, date_k, cust_k):
        return (sales.join(dd, [(date_k, "d_date_sk")], "leftsemi")
                .join(t["customer"], [(cust_k, "c_customer_sk")])
                .select("c_last_name", "c_first_name").distinct())
    s = custs(t["store_sales"], "ss_sold_date_sk", "ss_customer_sk")
    c = custs(t["catalog_sales"], "cs_sold_date_sk", "cs_bill_customer_sk")
    w = custs(t["web_sales"], "ws_sold_date_sk", "ws_bill_customer_sk")
    keys = [("c_last_name", "c_last_name"), ("c_first_name", "c_first_name")]
    return (s.join(c, keys, "leftsemi").join(w, keys, "leftsemi")
            .agg(F.count().alias("cnt")))


def q39(t):
    inv = (t["inventory"]
           .join(t["date_dim"].filter((col("d_year") == 2001)
                                      & col("d_moy").isin(1, 2)),
                 [("inv_date_sk", "d_date_sk")])
           .join(t["item"], [("inv_item_sk", "i_item_sk")])
           .join(t["warehouse"], [("inv_warehouse_sk", "w_warehouse_sk")])
           .groupBy("w_warehouse_sk", "i_item_sk", "d_moy")
           .agg(F.stddev("inv_quantity_on_hand").alias("stdev"),
                F.avg("inv_quantity_on_hand").alias("mean")))
    inv = (inv.filter(col("mean") != 0)
           .withColumn("cov", col("stdev") / col("mean"))
           .filter(col("cov") > 1.0))
    a = inv.filter(col("d_moy") == 1).select(
        col("w_warehouse_sk").alias("w1"), col("i_item_sk").alias("i1"),
        col("mean").alias("mean1"), col("cov").alias("cov1"))
    b = inv.filter(col("d_moy") == 2).select(
        col("w_warehouse_sk").alias("w2"), col("i_item_sk").alias("i2"),
        col("mean").alias("mean2"), col("cov").alias("cov2"))
    return (a.join(b, [("w1", "w2"), ("i1", "i2")])
            .select("w1", "i1", "mean1", "cov1", "mean2", "cov2")
            .sort("w1", "i1"))


def q49(t):
    def channel(sales, returns, qty, amt, skeys, rkeys, item_k, tag):
        s = (sales
             .join(t["date_dim"].filter((col("d_year") == 2000)
                                        & (col("d_moy") == 12)),
                   [(skeys, "d_date_sk")])
             .filter(col(amt) > 0))
        j = s.join(returns, rkeys, "left")
        g = (j.groupBy(col(item_k).alias("item"))
             .agg(F.sum(F.coalesce(col(tag + "_return_quantity"),
                                   lit(0)).cast("long")).alias("ret_q"),
                  F.sum(col(qty)).alias("sale_q"),
                  F.sum(F.coalesce(col(tag + ("_return_amt" if tag != "cr"
                                              else "_return_amount")),
                                   lit(0.0))).alias("ret_a"),
                  F.sum(col(amt)).alias("sale_a")))
        g = (g.filter(col("sale_q") > 0)
             .withColumn("return_ratio",
                         col("ret_q").cast("double") / col("sale_q"))
             .withColumn("currency_ratio", col("ret_a") / col("sale_a")))
        wr_ = Window.orderBy("return_ratio")
        wc_ = Window.orderBy("currency_ratio")
        g = g.select("item", "return_ratio", "currency_ratio",
                     F.rank().over(wr_).alias("return_rank"),
                     F.rank().over(wc_).alias("currency_rank"))
        return (g.filter((col("return_rank") <= 10)
                         | (col("currency_rank") <= 10))
                .withColumn("channel", lit(tag)))
    web = channel(t["web_sales"], t["web_returns"], "ws_quantity",
                  "ws_net_paid", "ws_sold_date_sk",
                  [("ws_order_number", "wr_order_number"),
                   ("ws_item_sk", "wr_item_sk")], "ws_item_sk", "wr")
    cat = channel(t["catalog_sales"], t["catalog_returns"], "cs_quantity",
                  "cs_net_paid", "cs_sold_date_sk",
                  [("cs_order_number", "cr_order_number"),
                   ("cs_item_sk", "cr_item_sk")], "cs_item_sk", "cr")
    st = channel(t["store_sales"], t["store_returns"], "ss_quantity",
                 "ss_net_paid", "ss_sold_date_sk",
                 [("ss_ticket_number", "sr_ticket_number"),
                  ("ss_item_sk", "sr_item_sk")], "ss_item_sk", "sr")
    cols = ["channel", "item", "return_ratio", "return_rank",
            "currency_rank"]
    return (web.select(*cols).union(cat.select(*cols)).union(st.select(*cols))
            .sort("channel", "return_rank", "currency_rank", "item")
            .limit(100))


def q51(t):
    dd = t["date_dim"].filter((col("d_month_seq") >= 1200)
                              & (col("d_month_seq") <= 1211))
    wss = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")])
           .groupBy(col("ws_item_sk").alias("item_sk"), "d_date")
           .agg(F.sum("ws_sales_price").alias("daily")))
    sss = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")])
           .groupBy(col("ss_item_sk").alias("item_sk"), "d_date")
           .agg(F.sum("ss_sales_price").alias("daily")))
    wcum = Window.partitionBy("item_sk").orderBy("d_date") \
        .rowsBetween(Window.unboundedPreceding, Window.currentRow)
    web = wss.select("item_sk", "d_date",
                     F.sum("daily").over(wcum).alias("web_cum"))
    store = sss.select(col("item_sk").alias("s_item"),
                       col("d_date").alias("s_date"),
                       F.sum("daily").over(wcum).alias("store_cum"))
    j = (web.join(store, [("item_sk", "s_item"), ("d_date", "s_date")])
         .filter(col("web_cum") > col("store_cum")))
    return (j.select("item_sk", "d_date", "web_cum", "store_cum")
            .sort("item_sk", "d_date").limit(100))


def q54(t):
    dd = t["date_dim"].filter((col("d_year") == 1999) & (col("d_moy") == 5))
    my_customers = (t["catalog_sales"]
                    .select(col("cs_sold_date_sk").alias("sold"),
                            col("cs_item_sk").alias("item"),
                            col("cs_bill_customer_sk").alias("cust"))
                    .union(t["web_sales"].select(
                        col("ws_sold_date_sk").alias("sold"),
                        col("ws_item_sk").alias("item"),
                        col("ws_bill_customer_sk").alias("cust")))
                    .join(dd, [("sold", "d_date_sk")], "leftsemi")
                    .join(t["item"].filter(
                        (col("i_category") == "Women")
                        & (col("i_class") == "dresses")),
                        [("item", "i_item_sk")], "leftsemi")
                    .select("cust").distinct())
    dd2 = t["date_dim"].filter((col("d_year") == 1999)
                               & col("d_moy").isin(6, 7, 8))
    rev = (t["store_sales"]
           .join(my_customers, [("ss_customer_sk", "cust")], "leftsemi")
           .join(dd2, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
           .groupBy(col("ss_customer_sk").alias("c"))
           .agg(F.sum("ss_ext_sales_price").alias("revenue")))
    seg = rev.select(F.floor(col("revenue") / 50).cast("int")
                     .alias("segment"))
    return (seg.groupBy("segment").agg(F.count().alias("num_customers"))
            .withColumn("segment_base", col("segment") * 50)
            .sort("segment", "num_customers").limit(100))




def _sales_by_item_channel(t, sales, item_k, date_k, price, months, year,
                           cat_filter):
    return (sales
            .join(t["date_dim"].filter((col("d_year") == year)
                                       & col("d_moy").isin(*months)),
                  [(date_k, "d_date_sk")])
            .join(t["item"].join(cat_filter, [("i_item_id", "f_item_id")],
                                 "leftsemi"),
                  [(item_k, "i_item_sk")])
            .groupBy("i_item_id")
            .agg(F.sum(price).alias("total_sales")))


def q56(t):
    # q33/q60 family: items in given colors, summed across the 3 channels
    ids = (t["item"].filter(col("i_color").isin("blue", "cyan", "green"))
           .select(col("i_item_id").alias("f_item_id")).distinct())
    s = _sales_by_item_channel(t, t["store_sales"], "ss_item_sk",
                               "ss_sold_date_sk", col("ss_ext_sales_price"),
                               (2,), 2001, ids)
    c = _sales_by_item_channel(t, t["catalog_sales"], "cs_item_sk",
                               "cs_sold_date_sk", col("cs_ext_sales_price"),
                               (2,), 2001, ids)
    w = _sales_by_item_channel(t, t["web_sales"], "ws_item_sk",
                               "ws_sold_date_sk", col("ws_ext_sales_price"),
                               (2,), 2001, ids)
    return (s.union(c).union(w)
            .groupBy("i_item_id")
            .agg(F.sum("total_sales").alias("total_sales"))
            .sort("total_sales", "i_item_id").limit(100))


def q57(t):
    # q47's deviation-from-average shape over the catalog channel
    v1 = (t["catalog_sales"]
          .join(t["item"], [("cs_item_sk", "i_item_sk")])
          .join(t["date_dim"].filter(
              (col("d_year") == 1999)
              | ((col("d_year") == 1998) & (col("d_moy") == 12))
              | ((col("d_year") == 2000) & (col("d_moy") == 1))),
              [("cs_sold_date_sk", "d_date_sk")])
          .join(t["call_center"], [("cs_call_center_sk", "cc_call_center_sk")])
          .groupBy("i_category", "i_brand", "cc_name", "d_year", "d_moy")
          .agg(F.sum("cs_sales_price").alias("sum_sales")))
    wavg = Window.partitionBy("i_category", "i_brand", "cc_name", "d_year")
    wrank = Window.partitionBy("i_category", "i_brand", "cc_name") \
        .orderBy("d_year", "d_moy")
    v1 = v1.select("i_category", "i_brand", "cc_name", "d_year", "d_moy",
                   "sum_sales",
                   F.avg("sum_sales").over(wavg).alias("avg_monthly_sales"),
                   F.rank().over(wrank).alias("rn"))
    prev = v1.select(col("i_category").alias("pc"), col("i_brand").alias("pb"),
                     col("cc_name").alias("pn"), col("rn").alias("prn"),
                     col("sum_sales").alias("psum"))
    nxt = v1.select(col("i_category").alias("nc"), col("i_brand").alias("nb"),
                    col("cc_name").alias("nn"), col("rn").alias("nrn"),
                    col("sum_sales").alias("nsum"))
    v2 = (v1.withColumn("rp", col("rn") - 1).withColumn("rx", col("rn") + 1)
          .join(prev, [("i_category", "pc"), ("i_brand", "pb"),
                       ("cc_name", "pn"), ("rp", "prn")])
          .join(nxt, [("i_category", "nc"), ("i_brand", "nb"),
                      ("cc_name", "nn"), ("rx", "nrn")]))
    dev = when(col("avg_monthly_sales") > 0,
               F.abs(col("sum_sales") - col("avg_monthly_sales"))
               / col("avg_monthly_sales")).otherwise(None)
    return (v2.filter((col("d_year") == 1999)
                      & (col("avg_monthly_sales") > 0)
                      & (dev > 0.1))
            .select("i_category", "i_brand", "cc_name", "d_year", "d_moy",
                    "avg_monthly_sales", "sum_sales", "psum", "nsum")
            .sort((col("sum_sales") - col("avg_monthly_sales")).asc(),
                  "cc_name")
            .limit(100))


def q58(t):
    week = (t["date_dim"].filter(col("d_date")
                                 == lit(datetime.date(2000, 1, 3)))
            .select(col("d_week_seq").alias("wseq")))
    dates = (t["date_dim"].join(week, [("d_week_seq", "wseq")], "leftsemi")
             .select("d_date_sk"))

    def rev(sales, item_k, date_k, price, tag):
        return (sales.join(dates, [(date_k, "d_date_sk")], "leftsemi")
                .join(t["item"], [(item_k, "i_item_sk")])
                .groupBy(col("i_item_id").alias(f"{tag}_item_id"))
                .agg(F.sum(price).alias(f"{tag}_rev")))
    ss = rev(t["store_sales"], "ss_item_sk", "ss_sold_date_sk",
             col("ss_ext_sales_price"), "ss")
    cs = rev(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk",
             col("cs_ext_sales_price"), "cs")
    ws = rev(t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
             col("ws_ext_sales_price"), "ws")
    j = (ss.join(cs, [("ss_item_id", "cs_item_id")])
         .join(ws, [("ss_item_id", "ws_item_id")]))
    between = lambda a, b: (col(a) >= 0.9 * col(b)) & (col(a) <= 1.1 * col(b))
    return (j.filter(between("ss_rev", "cs_rev") & between("ss_rev", "ws_rev")
                     & between("cs_rev", "ss_rev") & between("cs_rev", "ws_rev")
                     & between("ws_rev", "ss_rev") & between("ws_rev", "cs_rev"))
            .select(col("ss_item_id").alias("item_id"), "ss_rev", "cs_rev",
                    "ws_rev")
            .sort("item_id", "ss_rev").limit(100))


def q64(t):
    # cross_sales ("Like" reduction keeping the shape: store sales paired
    # with returns, catalog refund exclusion, two-year self-join)
    cs_ui = (t["catalog_sales"]
             .join(t["catalog_returns"],
                   [("cs_item_sk", "cr_item_sk"),
                    ("cs_order_number", "cr_order_number")])
             .groupBy(col("cs_item_sk").alias("ui_item"))
             .agg(F.sum(col("cs_ext_list_price")).alias("sale"),
                  F.sum(col("cr_refunded_cash") + col("cr_fee"))
                  .alias("refund"))
             .filter(col("sale") > 2 * col("refund"))
             .select("ui_item"))

    def cross_sales(year, tag):
        return (t["store_sales"]
                .join(t["store_returns"],
                      [("ss_item_sk", "sr_item_sk"),
                       ("ss_ticket_number", "sr_ticket_number")])
                .join(cs_ui, [("ss_item_sk", "ui_item")], "leftsemi")
                .join(t["date_dim"].filter(col("d_year") == year),
                      [("ss_sold_date_sk", "d_date_sk")])
                .join(t["store"], [("ss_store_sk", "s_store_sk")])
                .join(t["item"].filter(col("i_current_price").isNotNull()),
                      [("ss_item_sk", "i_item_sk")])
                .groupBy(col("i_product_name").alias(f"{tag}_pn"),
                         col("s_store_name").alias(f"{tag}_sn"),
                         col("s_zip").alias(f"{tag}_zip"))
                .agg(F.count().alias(f"{tag}_cnt"),
                     F.sum("ss_wholesale_cost").alias(f"{tag}_s1"),
                     F.sum("ss_list_price").alias(f"{tag}_s2"),
                     F.sum("ss_coupon_amt").alias(f"{tag}_s3")))
    y1 = cross_sales(1999, "y1")
    y2 = cross_sales(2000, "y2")
    return (y1.join(y2, [("y1_pn", "y2_pn"), ("y1_sn", "y2_sn"),
                         ("y1_zip", "y2_zip")])
            .filter(col("y2_cnt") <= col("y1_cnt"))
            .select("y1_pn", "y1_sn", "y1_zip", "y1_s1", "y1_s2", "y1_s3",
                    "y2_s1", "y2_s2", "y2_s3", "y2_cnt", "y1_cnt")
            .sort("y1_pn", "y1_sn", "y2_cnt").limit(100))


def q66(t):
    sm = t["ship_mode"].filter(col("sm_carrier").isin("DHL", "BARIAN"))

    def channel(sales, date_k, time_k, sm_k, wh_k, qty, price, tag):
        j = (sales
             .join(t["date_dim"].filter(col("d_year") == 2001),
                   [(date_k, "d_date_sk")])
             .join(t["time_dim"].filter((col("t_hour") >= 8)
                                        & (col("t_hour") <= 17)),
                   [(time_k, "t_time_sk")])
             .join(sm, [(sm_k, "sm_ship_mode_sk")], "leftsemi")
             .join(t["warehouse"], [(wh_k, "w_warehouse_sk")]))
        aggs = [F.sum(when(col("d_moy") == m, col(price) * col(qty))
                      .otherwise(0.0)).alias(f"{tag}_m{m}")
                for m in range(1, 13)]
        return (j.groupBy("w_warehouse_name", "w_warehouse_sq_ft", "w_city",
                          "w_county", "w_state", "w_country")
                .agg(*aggs))
    ws = channel(t["web_sales"], "ws_sold_date_sk", "ws_sold_time_sk",
                 "ws_ship_mode_sk", "ws_warehouse_sk", "ws_quantity",
                 "ws_ext_sales_price", "m")
    cs = channel(t["catalog_sales"], "cs_sold_date_sk", "cs_sold_time_sk",
                 "cs_ship_mode_sk", "cs_warehouse_sk", "cs_quantity",
                 "cs_ext_sales_price", "m")
    month_cols = [f"m_m{m}" for m in range(1, 13)]
    return (ws.union(cs)
            .groupBy("w_warehouse_name", "w_warehouse_sq_ft", "w_city",
                     "w_county", "w_state", "w_country")
            .agg(*[F.sum(c).alias(c) for c in month_cols])
            .sort("w_warehouse_name").limit(100))


def q67(t):
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .rollup("i_category", "i_class", "i_brand", "i_product_name",
                    "d_year", "d_qoy", "d_moy", "s_store_id")
            .agg(F.sum(F.coalesce(col("ss_sales_price") * col("ss_quantity"),
                                  lit(0.0))).alias("sumsales")))
    w = Window.partitionBy("i_category").orderBy(col("sumsales").desc())
    return (base.select("i_category", "i_class", "i_brand", "i_product_name",
                        "d_year", "d_qoy", "d_moy", "s_store_id", "sumsales",
                        F.rank().over(w).alias("rk"))
            .filter(col("rk") <= 100)
            .sort("i_category", col("sumsales").desc(), "rk")
            .limit(100))


def q70(t):
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")]))
    state_rank = (base.groupBy(col("s_state").alias("rank_state"))
                  .agg(F.sum("ss_net_profit").alias("sp")))
    wr = Window.orderBy(col("sp").desc())
    top_states = (state_rank.select("rank_state",
                                    F.rank().over(wr).alias("rnk"))
                  .filter(col("rnk") <= 5).select("rank_state"))
    return (base.join(top_states, [("s_state", "rank_state")], "leftsemi")
            .rollup("s_state", "s_county")
            .agg(F.sum("ss_net_profit").alias("total_sum"))
            .sort(col("total_sum").desc(), "s_state", "s_county")
            .limit(100))


def q72(t):
    return (t["catalog_sales"]
            .join(t["inventory"], [("cs_item_sk", "inv_item_sk")])
            .join(t["warehouse"], [("inv_warehouse_sk", "w_warehouse_sk")])
            .join(t["item"], [("cs_item_sk", "i_item_sk")])
            .join(t["customer_demographics"].filter(
                col("cd_marital_status") == "D"),
                [("cs_bill_cdemo_sk", "cd_demo_sk")])
            .join(t["household_demographics"].filter(
                col("hd_buy_potential") == ">10000"),
                [("cs_bill_hdemo_sk", "hd_demo_sk")])
            .join(t["date_dim"].filter(col("d_year") == 1999)
                  .select(col("d_date_sk").alias("sold_sk"),
                          col("d_week_seq").alias("sold_week")),
                  [("cs_sold_date_sk", "sold_sk")])
            .filter(col("inv_quantity_on_hand") < col("cs_quantity"))
            .groupBy("i_item_desc", "w_warehouse_name", "sold_week")
            .agg(F.count().alias("no_promo"))
            .sort(col("no_promo").desc(), "i_item_desc", "w_warehouse_name",
                  "sold_week")
            .limit(100))


def q75(t):
    def sales_yr(sales, item_k, date_k, qty, amt, ret, ret_keys, rq, ra):
        s = (sales
             .join(t["date_dim"].filter(col("d_year").isin(1999, 2000)),
                   [(date_k, "d_date_sk")])
             .join(t["item"].filter(col("i_category") == "Books"),
                   [(item_k, "i_item_sk")])
             .join(ret, ret_keys, "left"))
        return (s.groupBy("d_year", "i_brand_id", "i_category_id")
                .agg(F.sum(col(qty)).alias("_q"),
                     F.sum(F.coalesce(col(rq), lit(0)).cast("long"))
                     .alias("_rq"),
                     F.sum(col(amt)).alias("_a"),
                     F.sum(F.coalesce(col(ra), lit(0.0))).alias("_ra"))
                .select("d_year", "i_brand_id", "i_category_id",
                        (col("_q") - col("_rq")).alias("sales_cnt"),
                        (col("_a") - col("_ra")).alias("sales_amt")))
    ss = sales_yr(t["store_sales"], "ss_item_sk", "ss_sold_date_sk",
                  "ss_quantity", "ss_ext_sales_price", t["store_returns"],
                  [("ss_ticket_number", "sr_ticket_number"),
                   ("ss_item_sk", "sr_item_sk")],
                  "sr_return_quantity", "sr_return_amt")
    cs = sales_yr(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk",
                  "cs_quantity", "cs_ext_sales_price", t["catalog_returns"],
                  [("cs_order_number", "cr_order_number"),
                   ("cs_item_sk", "cr_item_sk")],
                  "cr_return_quantity", "cr_return_amount")
    ws = sales_yr(t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
                  "ws_quantity", "ws_ext_sales_price", t["web_returns"],
                  [("ws_order_number", "wr_order_number"),
                   ("ws_item_sk", "wr_item_sk")],
                  "wr_return_quantity", "wr_return_amt")
    all_y = (ss.union(cs).union(ws)
             .groupBy("d_year", "i_brand_id", "i_category_id")
             .agg(F.sum("sales_cnt").alias("sales_cnt"),
                  F.sum("sales_amt").alias("sales_amt")))
    prev = all_y.filter(col("d_year") == 1999).select(
        col("i_brand_id").alias("pb"), col("i_category_id").alias("pg"),
        col("sales_cnt").alias("prev_cnt"), col("sales_amt").alias("prev_amt"))
    curr = all_y.filter(col("d_year") == 2000)
    return (curr.join(prev, [("i_brand_id", "pb"), ("i_category_id", "pg")])
            .filter((col("prev_cnt") > 0)
                    & (col("sales_cnt").cast("double")
                       / col("prev_cnt") < 0.9))
            .select("i_brand_id", "i_category_id", "prev_cnt",
                    col("sales_cnt").alias("curr_cnt"),
                    (col("sales_cnt") - col("prev_cnt")).alias("delta_cnt"),
                    (col("sales_amt") - col("prev_amt")).alias("delta_amt"))
            .sort("delta_cnt", "i_brand_id", "i_category_id")
            .limit(100))


def q77(t):
    lo, hi = datetime.date(2000, 8, 1), datetime.date(2000, 8, 30)
    ssr = _channel_profit(
        t, t["store_sales"], t["store_returns"], "ss_sold_date_sk",
        "sr_returned_date_sk", "ss_store_sk", "sr_store_sk",
        col("ss_ext_sales_price"), col("ss_net_profit"),
        col("sr_return_amt"), col("sr_net_loss"), "sid", lo, hi)
    csr = _channel_profit(
        t, t["catalog_sales"], t["catalog_returns"], "cs_sold_date_sk",
        "cr_returned_date_sk", "cs_call_center_sk", "cr_call_center_sk",
        col("cs_ext_sales_price"), col("cs_net_profit"),
        col("cr_return_amount"), col("cr_net_loss"), "sid", lo, hi)
    wsr = _channel_profit(
        t, t["web_sales"], t["web_returns"], "ws_sold_date_sk",
        "wr_returned_date_sk", "ws_web_page_sk", "wr_web_page_sk",
        col("ws_ext_sales_price"), col("ws_net_profit"),
        col("wr_return_amt"), col("wr_net_loss"), "sid", lo, hi)
    u = (ssr.withColumn("channel", lit("store channel"))
         .union(csr.withColumn("channel", lit("catalog channel")))
         .union(wsr.withColumn("channel", lit("web channel"))))
    return (u.rollup("channel", "sid")
            .agg(F.sum("sales").alias("sales"),
                 F.sum("returns_amt").alias("returns_amt"),
                 F.sum("profit").alias("profit"))
            .sort("channel", "sid").limit(100))


def q78(t):
    def channel(sales, ret, skeys, item_k, cust_k, date_k, qty, wc, sp, tag):
        no_ret = sales.join(ret, skeys, "leftanti")
        return (no_ret
                .join(t["date_dim"].filter(col("d_year") == 2000),
                      [(date_k, "d_date_sk")])
                .groupBy(col(item_k).alias(f"{tag}_item"),
                         col(cust_k).alias(f"{tag}_cust"))
                .agg(F.sum(col(qty)).alias(f"{tag}_qty"),
                     F.sum(col(wc)).alias(f"{tag}_wc"),
                     F.sum(col(sp)).alias(f"{tag}_sp")))
    ss = channel(t["store_sales"], t["store_returns"],
                 [("ss_ticket_number", "sr_ticket_number"),
                  ("ss_item_sk", "sr_item_sk")],
                 "ss_item_sk", "ss_customer_sk", "ss_sold_date_sk",
                 "ss_quantity", "ss_wholesale_cost", "ss_sales_price", "ss")
    ws = channel(t["web_sales"], t["web_returns"],
                 [("ws_order_number", "wr_order_number"),
                  ("ws_item_sk", "wr_item_sk")],
                 "ws_item_sk", "ws_bill_customer_sk", "ws_sold_date_sk",
                 "ws_quantity", "ws_wholesale_cost", "ws_sales_price", "ws")
    cs = channel(t["catalog_sales"], t["catalog_returns"],
                 [("cs_order_number", "cr_order_number"),
                  ("cs_item_sk", "cr_item_sk")],
                 "cs_item_sk", "cs_bill_customer_sk", "cs_sold_date_sk",
                 "cs_quantity", "cs_wholesale_cost", "cs_sales_price", "cs")
    j = (ss.join(ws, [("ss_item", "ws_item"), ("ss_cust", "ws_cust")])
         .join(cs, [("ss_item", "cs_item"), ("ss_cust", "cs_cust")]))
    ratio = F.round(col("ss_qty").cast("double")
                    / (col("ws_qty") + col("cs_qty")), 2)
    return (j.filter((col("ws_qty") > 0) | (col("cs_qty") > 0))
            .select("ss_item", "ss_cust", "ss_qty", "ss_wc", "ss_sp",
                    ratio.alias("ratio"))
            .sort("ss_item", "ss_cust").limit(100))


def q80(t):
    lo, hi = datetime.date(2000, 8, 1), datetime.date(2000, 8, 30)
    promo = t["promotion"].filter(col("p_channel_tv") == "N")

    def channel(sales, ret, skeys, date_k, id_k, promo_k, price, profit,
                ramt, rloss, tag):
        s = (sales
             .join(t["date_dim"].filter(
                 (col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi))),
                 [(date_k, "d_date_sk")])
             .join(promo, [(promo_k, "p_promo_sk")], "leftsemi")
             .join(ret, skeys, "left"))
        return (s.groupBy(col(id_k).alias("id"))
                .agg(F.sum(col(price)).alias("sales"),
                     F.sum(F.coalesce(col(ramt), lit(0.0))).alias("returns_amt"),
                     F.sum(col(profit)).alias("_p"),
                     F.sum(F.coalesce(col(rloss), lit(0.0))).alias("_l"))
                .select("id", "sales", "returns_amt",
                        (col("_p") - col("_l")).alias("profit"))
                .withColumn("channel", lit(tag)))
    ss = channel(t["store_sales"], t["store_returns"],
                 [("ss_ticket_number", "sr_ticket_number"),
                  ("ss_item_sk", "sr_item_sk")],
                 "ss_sold_date_sk", "ss_store_sk", "ss_promo_sk",
                 "ss_ext_sales_price", "ss_net_profit", "sr_return_amt",
                 "sr_net_loss", "store channel")
    cs = channel(t["catalog_sales"], t["catalog_returns"],
                 [("cs_order_number", "cr_order_number"),
                  ("cs_item_sk", "cr_item_sk")],
                 "cs_sold_date_sk", "cs_catalog_page_sk", "cs_promo_sk",
                 "cs_ext_sales_price", "cs_net_profit", "cr_return_amount",
                 "cr_net_loss", "catalog channel")
    ws = channel(t["web_sales"], t["web_returns"],
                 [("ws_order_number", "wr_order_number"),
                  ("ws_item_sk", "wr_item_sk")],
                 "ws_sold_date_sk", "ws_web_site_sk", "ws_promo_sk",
                 "ws_ext_sales_price", "ws_net_profit", "wr_return_amt",
                 "wr_net_loss", "web channel")
    cols = ["channel", "id", "sales", "returns_amt", "profit"]
    return (ss.select(*cols).union(cs.select(*cols)).union(ws.select(*cols))
            .rollup("channel", "id")
            .agg(F.sum("sales").alias("sales"),
                 F.sum("returns_amt").alias("returns_amt"),
                 F.sum("profit").alias("profit"))
            .sort("channel", "id").limit(100))


def q81(t):
    ctr = (t["catalog_returns"]
           .join(t["date_dim"].filter(col("d_year") == 2000),
                 [("cr_returned_date_sk", "d_date_sk")])
           .join(t["customer"].select("c_customer_sk", "c_current_addr_sk"),
                 [("cr_returning_customer_sk", "c_customer_sk")])
           .join(t["customer_address"],
                 [("c_current_addr_sk", "ca_address_sk")])
           .groupBy(col("cr_returning_customer_sk").alias("ctr_cust"),
                    col("ca_state").alias("ctr_state"))
           .agg(F.sum("cr_return_amt_inc_tax").alias("ctr_total")))
    avg_ctr = (ctr.groupBy(col("ctr_state").alias("avg_state"))
               .agg(F.avg("ctr_total").alias("avg_ret"))
               .select("avg_state", (col("avg_ret") * 1.2).alias("thr")))
    return (ctr.join(avg_ctr, [("ctr_state", "avg_state")])
            .filter(col("ctr_total") > col("thr"))
            .join(t["customer"], [("ctr_cust", "c_customer_sk")])
            .join(t["customer_address"].filter(col("ca_state") == "GA"),
                  [("c_current_addr_sk", "ca_address_sk")])
            .select("c_customer_id", "c_salutation", "c_first_name",
                    "c_last_name", "ca_city", "ca_zip", "ctr_total")
            .sort("c_customer_id", "c_salutation", "c_first_name",
                  "c_last_name", "ca_city", "ca_zip")
            .limit(100))


def q83(t):
    week = (t["date_dim"]
            .filter(col("d_date").isin(datetime.date(2000, 6, 30),
                                       datetime.date(2000, 9, 27),
                                       datetime.date(2000, 11, 17)))
            .select(col("d_week_seq").alias("wseq")))
    dates = (t["date_dim"].join(week, [("d_week_seq", "wseq")], "leftsemi")
             .select("d_date_sk"))

    def rets(ret, item_k, date_k, qty, tag):
        return (ret.join(dates, [(date_k, "d_date_sk")], "leftsemi")
                .join(t["item"], [(item_k, "i_item_sk")])
                .groupBy(col("i_item_id").alias(f"{tag}_item_id"))
                .agg(F.sum(col(qty)).alias(f"{tag}_qty")))
    sr = rets(t["store_returns"], "sr_item_sk", "sr_returned_date_sk",
              "sr_return_quantity", "sr")
    cr = rets(t["catalog_returns"], "cr_item_sk", "cr_returned_date_sk",
              "cr_return_quantity", "cr")
    wr = rets(t["web_returns"], "wr_item_sk", "wr_returned_date_sk",
              "wr_return_quantity", "wr")
    j = (sr.join(cr, [("sr_item_id", "cr_item_id")])
         .join(wr, [("sr_item_id", "wr_item_id")]))
    total = (col("sr_qty") + col("cr_qty") + col("wr_qty")).cast("double")
    return (j.select(col("sr_item_id").alias("item_id"), "sr_qty",
                     (col("sr_qty") / total * 100).alias("sr_dev"),
                     "cr_qty", (col("cr_qty") / total * 100).alias("cr_dev"),
                     "wr_qty", (col("wr_qty") / total * 100).alias("wr_dev"),
                     (total / 3.0).alias("average"))
            .sort("item_id", "sr_qty").limit(100))


def q84(t):
    # adaption: the generator has no hd_income_band_sk path, so the income
    # band gate is dropped; the join shape (customer x address x demographics
    # x store_returns) is preserved
    return (t["customer"]
            .join(t["customer_address"].filter(col("ca_city") == "Fairview"),
                  [("c_current_addr_sk", "ca_address_sk")])
            .join(t["customer_demographics"],
                  [("c_current_cdemo_sk", "cd_demo_sk")])
            .join(t["store_returns"], [("cd_demo_sk", "sr_cdemo_sk")])
            .select(col("c_customer_id").alias("customer_id"),
                    col("c_last_name"), col("c_first_name"))
            .sort("customer_id").limit(100))


def q85(t):
    wr = (t["web_returns"]
          .join(t["web_sales"],
                [("wr_order_number", "ws_order_number"),
                 ("wr_item_sk", "ws_item_sk")])
          .join(t["date_dim"].filter(col("d_year") == 2000),
                [("ws_sold_date_sk", "d_date_sk")])
          .join(t["web_page"], [("ws_web_page_sk", "wp_web_page_sk")])
          .join(t["reason"], [("wr_reason_sk", "r_reason_sk")])
          .join(t["customer_demographics"],
                [("wr_refunded_cdemo_sk", "cd_demo_sk")])
          .filter(((col("cd_marital_status") == "M")
                   & (col("cd_education_status") == "Advanced Degree")
                   & (col("ws_sales_price") >= 100.0))
                  | ((col("cd_marital_status") == "S")
                     & (col("cd_education_status") == "College")
                     & (col("ws_sales_price") >= 50.0))
                  | ((col("cd_marital_status") == "W")
                     & (col("cd_education_status") == "2 yr Degree")
                     & (col("ws_sales_price") >= 0.0))))
    return (wr.groupBy("r_reason_desc")
            .agg(F.avg("ws_quantity").alias("avg_q"),
                 F.avg("wr_refunded_cash").alias("avg_cash"),
                 F.avg("wr_fee").alias("avg_fee"))
            .sort("r_reason_desc", "avg_q", "avg_cash", "avg_fee")
            .limit(100))


def q91(t):
    return (t["catalog_returns"]
            .join(t["date_dim"].filter((col("d_year") == 1998)
                                       & (col("d_moy") == 11)),
                  [("cr_returned_date_sk", "d_date_sk")])
            .join(t["call_center"], [("cr_call_center_sk",
                                      "cc_call_center_sk")])
            .join(t["customer"], [("cr_returning_customer_sk",
                                   "c_customer_sk")])
            .join(t["customer_demographics"].filter(
                ((col("cd_marital_status") == "M")
                 & (col("cd_education_status") == "Unknown"))
                | ((col("cd_marital_status") == "W")
                   & (col("cd_education_status") == "Advanced Degree"))),
                [("c_current_cdemo_sk", "cd_demo_sk")])
            .join(t["household_demographics"].filter(
                col("hd_buy_potential").like("Unknown%")),
                [("c_current_hdemo_sk", "hd_demo_sk")])
            .join(t["customer_address"].filter(col("ca_gmt_offset") == -7),
                  [("c_current_addr_sk", "ca_address_sk")])
            .groupBy("cc_call_center_id", "cc_name", "cc_manager",
                     "cd_marital_status", "cd_education_status")
            .agg(F.sum("cr_net_loss").alias("returns_loss"))
            .sort(col("returns_loss").desc())
            .limit(100))


def q95(t):
    ws1 = t["web_sales"].select(col("ws_order_number").alias("won"),
                                col("ws_warehouse_sk").alias("wwh"))
    ws2 = ws1.select(col("won").alias("won2"), col("wwh").alias("wwh2"))
    multi_wh = (ws1.join(ws2, [("won", "won2")])
                .filter(col("wwh") != col("wwh2"))
                .select("won").distinct())
    returned = t["web_returns"].select(
        col("wr_order_number").alias("rwon")).distinct()
    ws = (t["web_sales"]
          .join(t["date_dim"].filter(
              (col("d_date") >= lit(datetime.date(1999, 2, 1)))
              & (col("d_date") <= lit(datetime.date(1999, 4, 2)))),
              [("ws_ship_date_sk", "d_date_sk")])
          .join(t["customer_address"].filter(col("ca_state") == "GA"),
                [("ws_ship_addr_sk", "ca_address_sk")])
          .join(multi_wh, [("ws_order_number", "won")], "leftsemi")
          .join(returned, [("ws_order_number", "rwon")], "leftsemi"))
    return (ws.agg(F.countDistinct("ws_order_number").alias("order_count"),
                   F.sum("ws_ext_ship_cost").alias("total_shipping_cost"),
                   F.sum("ws_net_profit").alias("total_net_profit")))


QUERIES: Dict[str, object] = {
    name: fn for name, fn in list(globals().items())
    if name.startswith("q") and name[1:].isdigit() and callable(fn)}
