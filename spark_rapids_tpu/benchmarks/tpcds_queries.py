"""TPC-DS store-channel query subset over the DataFrame API.

Reference analog: TpcdsLikeSpark.scala (the reference ships ~100 "Like"
queries as raw SQL through Catalyst; this engine has no SQL frontend, so each
is the standard DataFrame translation of the same query text). The subset is
every query whose tables are store_sales + dimensions — the interactive
store-channel slice commonly benchmarked — with the same predicates, groupings
and orderings as the reference's text (one date-window constant shifted to
land inside the generator's 1998-2003 calendar, noted inline).
"""
from __future__ import annotations

import datetime
from typing import Dict

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

col, lit, when = F.col, F.lit, F.when


def q3(t):
    return (t["date_dim"].filter(col("d_moy") == 11)
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manufact_id") == 128),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "sum_agg")
            .sort("d_year", col("sum_agg").desc(), "brand_id")
            .limit(100))


def q7(t):
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].filter((col("p_channel_email") == "N")
                                  | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .join(cd, [("ss_cdemo_sk", "cd_demo_sk")])
            .join(promo, [("ss_promo_sk", "p_promo_sk")])
            .groupBy("i_item_id")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .sort("i_item_id").limit(100))


def q19(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1998))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 8),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")],
                  )
            .filter(F.substring("ca_zip", 1, 5) != F.substring("s_zip", 1, 5))
            .groupBy("i_brand", "i_brand_id", "i_manufact_id", "i_manufact")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "i_manufact_id",
                    "i_manufact", "ext_price")
            .sort(col("ext_price").desc(), "brand", "brand_id",
                  "i_manufact_id", "i_manufact")
            .limit(100))


def _ticket_counts(t, date_filter, hd_filter, store_filter):
    """Shared inner block of q34/q73: count items per (ticket, customer)."""
    return (t["store_sales"]
            .join(t["date_dim"].filter(date_filter),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(store_filter),
                  [("ss_store_sk", "s_store_sk")])
            .join(t["household_demographics"].filter(hd_filter),
                  [("ss_hdemo_sk", "hd_demo_sk")])
            .groupBy("ss_ticket_number", "ss_customer_sk")
            .agg(F.count().alias("cnt")))


def q34(t):
    dn = _ticket_counts(
        t,
        (((col("d_dom") >= 1) & (col("d_dom") <= 3))
         | ((col("d_dom") >= 25) & (col("d_dom") <= 28)))
        & col("d_year").isin(1999, 2000, 2001),
        (col("hd_buy_potential").isin(">10000", "unknown"))
        & (col("hd_vehicle_count") > 0)
        & (when(col("hd_vehicle_count") > 0,
                col("hd_dep_count") / col("hd_vehicle_count"))
           .otherwise(None) > 1.2),
        col("s_county") == "Williamson County")
    return (dn.filter((col("cnt") >= 15) & (col("cnt") <= 20))
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort("c_last_name", "c_first_name", "c_salutation",
                  col("c_preferred_cust_flag").desc(), "ss_ticket_number"))


def q42(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 2000))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 1),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("s"))
            .sort(col("s").desc(), "d_year", "i_category_id", "i_category")
            .limit(100))


def q46(t):
    dn = (t["store_sales"]
          .join(t["date_dim"].filter(col("d_dow").isin(5, 6)
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter(col("s_city").isin("Fairview", "Midway")),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                   col("ca_city").alias("bought_city"))
          .agg(F.sum("ss_coupon_amt").alias("amt"),
               F.sum("ss_net_profit").alias("profit")))
    return (dn.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "ca_city", "bought_city",
                  "ss_ticket_number")
            .limit(100))


def q52(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 2000))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 1),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price")
            .sort("d_year", col("ext_price").desc(), "brand_id")
            .limit(100))


def q55(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1999))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 28),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price")
            .sort(col("ext_price").desc(), "brand_id")
            .limit(100))


def _weekly_store_sales(t):
    day = lambda n: F.sum(when(col("d_day_name") == n,  # noqa: E731
                               col("ss_sales_price")).otherwise(None))
    return (t["store_sales"]
            .join(t["date_dim"], [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("d_week_seq", "ss_store_sk")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales")))


def q59(t):
    wss = _weekly_store_sales(t)
    weeks = (t["date_dim"].select("d_week_seq", "d_month_seq").distinct())

    def year_slice(lo, hi, suffix):
        cols = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
        sel = [col("s_store_name").alias(f"s_store_name{suffix}"),
               col("d_week_seq").alias(f"d_week_seq{suffix}"),
               col("s_store_id").alias(f"s_store_id{suffix}")]
        sel += [col(f"{c}_sales").alias(f"{c}_sales{suffix}") for c in cols]
        return (wss
                .join(weeks.filter((col("d_month_seq") >= lo)
                                   & (col("d_month_seq") <= hi)),
                      [("d_week_seq", "d_week_seq")])
                .join(t["store"], [("ss_store_sk", "s_store_sk")])
                .select(*sel))

    y = year_slice(1212, 1223, "1")
    x = year_slice(1224, 1235, "2")
    joined = y.join(x, [("s_store_id1", "s_store_id2")]).filter(
        col("d_week_seq1") == col("d_week_seq2") - 52)
    ratio = lambda c: (col(f"{c}_sales1") / col(f"{c}_sales2")).alias(f"{c}_r")  # noqa: E731
    return (joined.select("s_store_name1", "s_store_id1", "d_week_seq1",
                          *[ratio(c) for c in
                            ("sun", "mon", "tue", "wed", "thu", "fri", "sat")])
            .sort("s_store_name1", "s_store_id1", "d_week_seq1")
            .limit(100))


def q65(t):
    # d_month_seq window shifted into the generator calendar (reference uses
    # 1176..1187, which predates the 1998 epoch here)
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("ss_store_sk", "ss_item_sk")
            .agg(F.sum("ss_sales_price").alias("revenue")))
    avg_rev = (base.groupBy(col("ss_store_sk").alias("sb_store_sk"))
               .agg(F.avg("revenue").alias("ave")))
    return (base.join(avg_rev, [("ss_store_sk", "sb_store_sk")])
            .filter(col("revenue") <= col("ave") * 0.1)
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .select("s_store_name", "i_item_desc", "revenue",
                    "i_current_price", "i_wholesale_cost", "i_brand")
            .sort("s_store_name", "i_item_desc")
            .limit(100))


def q68(t):
    dn = (t["store_sales"]
          .join(t["date_dim"].filter(((col("d_dom") >= 1) & (col("d_dom") <= 2))
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter(col("s_city").isin("Midway", "Fairview")),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                   col("ca_city").alias("bought_city"))
          .agg(F.sum("ss_ext_sales_price").alias("extended_price"),
               F.sum("ss_ext_list_price").alias("list_price"),
               F.sum("ss_ext_tax").alias("extended_tax")))
    return (dn.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "extended_price", "extended_tax",
                    "list_price")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(t):
    dn = _ticket_counts(
        t,
        ((col("d_dom") >= 1) & (col("d_dom") <= 2))
        & col("d_year").isin(1999, 2000, 2001),
        (col("hd_buy_potential").isin(">10000", "unknown"))
        & (col("hd_vehicle_count") > 0)
        & (when(col("hd_vehicle_count") > 0,
                col("hd_dep_count") / col("hd_vehicle_count"))
           .otherwise(None) > 1),
        col("s_county").isin("Williamson County", "Franklin Parish",
                             "Bronx County", "Orange County"))
    return (dn.filter((col("cnt") >= 1) & (col("cnt") <= 5))
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort(col("cnt").desc(), "c_last_name"))


def q79(t):
    ms = (t["store_sales"]
          .join(t["date_dim"].filter((col("d_dow") == 1)
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter((col("s_number_employees") >= 200)
                                  & (col("s_number_employees") <= 295)),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "s_city")
          .agg(F.sum("ss_coupon_amt").alias("amt"),
               F.sum("ss_net_profit").alias("profit")))
    return (ms.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name",
                    F.substring("s_city", 1, 30).alias("city"),
                    "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "city", col("profit").desc())
            .limit(100))


def q89(t):
    cls_match = (
        (col("i_category").isin("Books", "Electronics", "Sports")
         & col("i_class").isin("computers", "stereo", "football"))
        | (col("i_category").isin("Men", "Jewelry", "Women")
           & col("i_class").isin("shirts", "birdal", "dresses")))
    base = (t["store_sales"]
            .join(t["item"].filter(cls_match), [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter(col("d_year") == 1999),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy("i_category", "i_class", "i_brand", "s_store_name",
                     "s_company_name", "d_moy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    w = Window.partitionBy("i_category", "i_brand", "s_store_name",
                           "s_company_name")
    tmp = base.select("i_category", "i_class", "i_brand", "s_store_name",
                      "s_company_name", "d_moy", "sum_sales",
                      F.avg("sum_sales").over(w).alias("avg_monthly_sales"))
    dev = when(col("avg_monthly_sales") != 0.0,
               F.abs(col("sum_sales") - col("avg_monthly_sales"))
               / col("avg_monthly_sales")).otherwise(None)
    return (tmp.filter(dev > 0.1)
            .select("i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy", "sum_sales",
                    "avg_monthly_sales",
                    (col("sum_sales") - col("avg_monthly_sales")).alias("_d"))
            .sort("_d", "s_store_name")
            .drop("_d")
            .limit(100))


def q96(t):
    return (t["store_sales"]
            .join(t["time_dim"].filter((col("t_hour") == 20)
                                       & (col("t_minute") >= 30)),
                  [("ss_sold_time_sk", "t_time_sk")])
            .join(t["household_demographics"].filter(col("hd_dep_count") == 7),
                  [("ss_hdemo_sk", "hd_demo_sk")])
            .join(t["store"].filter(col("s_store_name") == "ese"),
                  [("ss_store_sk", "s_store_sk")])
            .agg(F.count().alias("cnt")))


def q98(t):
    lo = datetime.date(1999, 2, 22)
    hi = lo + datetime.timedelta(days=30)
    base = (t["store_sales"]
            .join(t["item"].filter(col("i_category").isin("Sports", "Books",
                                                          "Home")),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("i_item_id", "i_item_desc", "i_category", "i_class",
                     "i_current_price")
            .agg(F.sum("ss_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (base.select("i_item_desc", "i_category", "i_class",
                        "i_current_price", "itemrevenue", "i_item_id",
                        (col("itemrevenue") * 100.0
                         / F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio")
            .drop("i_item_id"))


def q43(t):
    day = lambda n: F.sum(when(col("d_day_name") == n,  # noqa: E731
                               col("ss_sales_price")).otherwise(None))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                  [("ss_store_sk", "s_store_sk")])
            .groupBy("s_store_name", "s_store_id")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


QUERIES: Dict[str, object] = {
    name: fn for name, fn in list(globals().items())
    if name.startswith("q") and name[1:].isdigit() and callable(fn)}
