"""TPC-DS query suite over the DataFrame API: 50 queries spanning the store,
catalog and web channels, returns, and inventory.

Reference analog: TpcdsLikeSpark.scala (the reference ships ~100 "Like"
queries as raw SQL through Catalyst; this engine has no SQL frontend, so each
is the standard DataFrame translation of the same query text), keeping the
same predicates, groupings and orderings. Constants are adapted to the
generator where its pools differ from dsdgen's (date windows shifted into the
1998-2003 calendar, state/manufact/brand lists drawn from the generated
pools), noted inline per query.
"""
from __future__ import annotations

import datetime
from typing import Dict

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

col, lit, when = F.col, F.lit, F.when


def q3(t):
    return (t["date_dim"].filter(col("d_moy") == 11)
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manufact_id") == 128),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "sum_agg")
            .sort("d_year", col("sum_agg").desc(), "brand_id")
            .limit(100))


def q7(t):
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].filter((col("p_channel_email") == "N")
                                  | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .join(cd, [("ss_cdemo_sk", "cd_demo_sk")])
            .join(promo, [("ss_promo_sk", "p_promo_sk")])
            .groupBy("i_item_id")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .sort("i_item_id").limit(100))


def q19(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1998))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 8),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")],
                  )
            .filter(F.substring("ca_zip", 1, 5) != F.substring("s_zip", 1, 5))
            .groupBy("i_brand", "i_brand_id", "i_manufact_id", "i_manufact")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "i_manufact_id",
                    "i_manufact", "ext_price")
            .sort(col("ext_price").desc(), "brand", "brand_id",
                  "i_manufact_id", "i_manufact")
            .limit(100))


def _ticket_counts(t, date_filter, hd_filter, store_filter):
    """Shared inner block of q34/q73: count items per (ticket, customer)."""
    return (t["store_sales"]
            .join(t["date_dim"].filter(date_filter),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(store_filter),
                  [("ss_store_sk", "s_store_sk")])
            .join(t["household_demographics"].filter(hd_filter),
                  [("ss_hdemo_sk", "hd_demo_sk")])
            .groupBy("ss_ticket_number", "ss_customer_sk")
            .agg(F.count().alias("cnt")))


def q34(t):
    dn = _ticket_counts(
        t,
        (((col("d_dom") >= 1) & (col("d_dom") <= 3))
         | ((col("d_dom") >= 25) & (col("d_dom") <= 28)))
        & col("d_year").isin(1999, 2000, 2001),
        (col("hd_buy_potential").isin(">10000", "unknown"))
        & (col("hd_vehicle_count") > 0)
        & (when(col("hd_vehicle_count") > 0,
                col("hd_dep_count") / col("hd_vehicle_count"))
           .otherwise(None) > 1.2),
        col("s_county") == "Williamson County")
    return (dn.filter((col("cnt") >= 15) & (col("cnt") <= 20))
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort("c_last_name", "c_first_name", "c_salutation",
                  col("c_preferred_cust_flag").desc(), "ss_ticket_number"))


def q42(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 2000))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 1),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("s"))
            .sort(col("s").desc(), "d_year", "i_category_id", "i_category")
            .limit(100))


def q46(t):
    dn = (t["store_sales"]
          .join(t["date_dim"].filter(col("d_dow").isin(5, 6)
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter(col("s_city").isin("Fairview", "Midway")),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                   col("ca_city").alias("bought_city"))
          .agg(F.sum("ss_coupon_amt").alias("amt"),
               F.sum("ss_net_profit").alias("profit")))
    return (dn.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "ca_city", "bought_city",
                  "ss_ticket_number")
            .limit(100))


def q52(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 2000))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 1),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select("d_year", col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price")
            .sort("d_year", col("ext_price").desc(), "brand_id")
            .limit(100))


def q55(t):
    return (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1999))
            .join(t["store_sales"], [("d_date_sk", "ss_sold_date_sk")])
            .join(t["item"].filter(col("i_manager_id") == 28),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy("i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "ext_price")
            .sort(col("ext_price").desc(), "brand_id")
            .limit(100))


def _weekly_store_sales(t):
    day = lambda n: F.sum(when(col("d_day_name") == n,  # noqa: E731
                               col("ss_sales_price")).otherwise(None))
    return (t["store_sales"]
            .join(t["date_dim"], [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("d_week_seq", "ss_store_sk")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales")))


def q59(t):
    wss = _weekly_store_sales(t)
    weeks = (t["date_dim"].select("d_week_seq", "d_month_seq").distinct())

    def year_slice(lo, hi, suffix):
        cols = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
        sel = [col("s_store_name").alias(f"s_store_name{suffix}"),
               col("d_week_seq").alias(f"d_week_seq{suffix}"),
               col("s_store_id").alias(f"s_store_id{suffix}")]
        sel += [col(f"{c}_sales").alias(f"{c}_sales{suffix}") for c in cols]
        return (wss
                .join(weeks.filter((col("d_month_seq") >= lo)
                                   & (col("d_month_seq") <= hi)),
                      [("d_week_seq", "d_week_seq")])
                .join(t["store"], [("ss_store_sk", "s_store_sk")])
                .select(*sel))

    y = year_slice(1212, 1223, "1")
    x = year_slice(1224, 1235, "2")
    joined = y.join(x, [("s_store_id1", "s_store_id2")]).filter(
        col("d_week_seq1") == col("d_week_seq2") - 52)
    ratio = lambda c: (col(f"{c}_sales1") / col(f"{c}_sales2")).alias(f"{c}_r")  # noqa: E731
    return (joined.select("s_store_name1", "s_store_id1", "d_week_seq1",
                          *[ratio(c) for c in
                            ("sun", "mon", "tue", "wed", "thu", "fri", "sat")])
            .sort("s_store_name1", "s_store_id1", "d_week_seq1")
            .limit(100))


def q65(t):
    # d_month_seq window shifted into the generator calendar (reference uses
    # 1176..1187, which predates the 1998 epoch here)
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("ss_store_sk", "ss_item_sk")
            .agg(F.sum("ss_sales_price").alias("revenue")))
    avg_rev = (base.groupBy(col("ss_store_sk").alias("sb_store_sk"))
               .agg(F.avg("revenue").alias("ave")))
    return (base.join(avg_rev, [("ss_store_sk", "sb_store_sk")])
            .filter(col("revenue") <= col("ave") * 0.1)
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["item"], [("ss_item_sk", "i_item_sk")])
            .select("s_store_name", "i_item_desc", "revenue",
                    "i_current_price", "i_wholesale_cost", "i_brand")
            .sort("s_store_name", "i_item_desc")
            .limit(100))


def q68(t):
    dn = (t["store_sales"]
          .join(t["date_dim"].filter(((col("d_dom") >= 1) & (col("d_dom") <= 2))
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter(col("s_city").isin("Midway", "Fairview")),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                   col("ca_city").alias("bought_city"))
          .agg(F.sum("ss_ext_sales_price").alias("extended_price"),
               F.sum("ss_ext_list_price").alias("list_price"),
               F.sum("ss_ext_tax").alias("extended_tax")))
    return (dn.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk", "ca_address_sk")])
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "extended_price", "extended_tax",
                    "list_price")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(t):
    dn = _ticket_counts(
        t,
        ((col("d_dom") >= 1) & (col("d_dom") <= 2))
        & col("d_year").isin(1999, 2000, 2001),
        (col("hd_buy_potential").isin(">10000", "unknown"))
        & (col("hd_vehicle_count") > 0)
        & (when(col("hd_vehicle_count") > 0,
                col("hd_dep_count") / col("hd_vehicle_count"))
           .otherwise(None) > 1),
        col("s_county").isin("Williamson County", "Franklin Parish",
                             "Bronx County", "Orange County"))
    return (dn.filter((col("cnt") >= 1) & (col("cnt") <= 5))
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort(col("cnt").desc(), "c_last_name"))


def q79(t):
    ms = (t["store_sales"]
          .join(t["date_dim"].filter((col("d_dow") == 1)
                                     & col("d_year").isin(1999, 2000, 2001)),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"].filter((col("s_number_employees") >= 200)
                                  & (col("s_number_employees") <= 295)),
                [("ss_store_sk", "s_store_sk")])
          .join(t["household_demographics"].filter(
                (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2)),
                [("ss_hdemo_sk", "hd_demo_sk")])
          .groupBy("ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "s_city")
          .agg(F.sum("ss_coupon_amt").alias("amt"),
               F.sum("ss_net_profit").alias("profit")))
    return (ms.join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .select("c_last_name", "c_first_name",
                    F.substring("s_city", 1, 30).alias("city"),
                    "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "city", col("profit").desc())
            .limit(100))


def q89(t):
    cls_match = (
        (col("i_category").isin("Books", "Electronics", "Sports")
         & col("i_class").isin("computers", "stereo", "football"))
        | (col("i_category").isin("Men", "Jewelry", "Women")
           & col("i_class").isin("shirts", "birdal", "dresses")))
    base = (t["store_sales"]
            .join(t["item"].filter(cls_match), [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter(col("d_year") == 1999),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy("i_category", "i_class", "i_brand", "s_store_name",
                     "s_company_name", "d_moy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    w = Window.partitionBy("i_category", "i_brand", "s_store_name",
                           "s_company_name")
    tmp = base.select("i_category", "i_class", "i_brand", "s_store_name",
                      "s_company_name", "d_moy", "sum_sales",
                      F.avg("sum_sales").over(w).alias("avg_monthly_sales"))
    dev = when(col("avg_monthly_sales") != 0.0,
               F.abs(col("sum_sales") - col("avg_monthly_sales"))
               / col("avg_monthly_sales")).otherwise(None)
    return (tmp.filter(dev > 0.1)
            .select("i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy", "sum_sales",
                    "avg_monthly_sales",
                    (col("sum_sales") - col("avg_monthly_sales")).alias("_d"))
            .sort("_d", "s_store_name")
            .drop("_d")
            .limit(100))


def q96(t):
    return (t["store_sales"]
            .join(t["time_dim"].filter((col("t_hour") == 20)
                                       & (col("t_minute") >= 30)),
                  [("ss_sold_time_sk", "t_time_sk")])
            .join(t["household_demographics"].filter(col("hd_dep_count") == 7),
                  [("ss_hdemo_sk", "hd_demo_sk")])
            .join(t["store"].filter(col("s_store_name") == "ese"),
                  [("ss_store_sk", "s_store_sk")])
            .agg(F.count().alias("cnt")))


def q98(t):
    lo = datetime.date(1999, 2, 22)
    hi = lo + datetime.timedelta(days=30)
    base = (t["store_sales"]
            .join(t["item"].filter(col("i_category").isin("Sports", "Books",
                                                          "Home")),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [("ss_sold_date_sk", "d_date_sk")])
            .groupBy("i_item_id", "i_item_desc", "i_category", "i_class",
                     "i_current_price")
            .agg(F.sum("ss_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (base.select("i_item_desc", "i_category", "i_class",
                        "i_current_price", "itemrevenue", "i_item_id",
                        (col("itemrevenue") * 100.0
                         / F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio")
            .drop("i_item_id"))


def q43(t):
    day = lambda n: F.sum(when(col("d_day_name") == n,  # noqa: E731
                               col("ss_sales_price")).otherwise(None))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                  [("ss_store_sk", "s_store_sk")])
            .groupBy("s_store_name", "s_store_id")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


# ---------------------------------------------------------------------------
# catalog / web channel queries (generator constants adapted to the pools:
# state lists -> the generator's state pool, manufact ids -> the 1..n_item
# cycle, reason desc -> the generated reason strings; noted per query)
# ---------------------------------------------------------------------------

def q15(t):
    zips = ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792"]
    cond = (F.substring("ca_zip", 1, 5).isin(*zips)
            | col("ca_state").isin("CA", "WA", "GA")
            | (col("cs_sales_price") > 500))
    return (t["catalog_sales"]
            .join(t["customer"], [("cs_bill_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk",
                                           "ca_address_sk")])
            .join(t["date_dim"].filter((col("d_qoy") == 2)
                                       & (col("d_year") == 2001)),
                  [("cs_sold_date_sk", "d_date_sk")])
            .filter(cond)
            .groupBy("ca_zip")
            .agg(F.sum("cs_sales_price").alias("sum_sales_price"))
            .sort("ca_zip").limit(100))


def _shipping_report(sales, returns, prefix, t, extra_join, state):
    """Shared q16/q94 shape: distinct orders shipping to a state within 60
    days, from orders spanning >1 warehouse (exists), never returned
    (not exists)."""
    p = prefix
    lo = datetime.date(2002, 2, 1) if p == "cs" else datetime.date(1999, 2, 1)
    hi = lo + datetime.timedelta(days=60)
    multi_wh = (sales
                .select(col(f"{p}_order_number").alias("o2"),
                        col(f"{p}_warehouse_sk").alias("w2"))
                .filter(col("w2").isNotNull())
                .groupBy("o2").agg(F.countDistinct("w2").alias("nw"))
                .filter(col("nw") >= 2).select("o2"))
    base = (sales
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [(f"{p}_ship_date_sk", "d_date_sk")])
            .join(t["customer_address"].filter(col("ca_state") == state),
                  [(f"{p}_ship_addr_sk", "ca_address_sk")])
            .join(extra_join[0], [extra_join[1]])
            .join(multi_wh, [(f"{p}_order_number", "o2")], "leftsemi")
            .join(returns, [(f"{p}_order_number", "ro")], "leftanti"))
    return (base.agg(
        F.countDistinct(f"{p}_order_number").alias("order_count"),
        F.sum(f"{p}_ext_ship_cost").alias("total_shipping_cost"),
        F.sum(f"{p}_net_profit").alias("total_net_profit")))


def q16(t):
    cc = t["call_center"].filter(col("cc_county") == "Williamson County")
    wr = t["catalog_returns"].select(col("cr_order_number").alias("ro"))
    return _shipping_report(t["catalog_sales"], wr, "cs", t,
                            (cc, ("cs_call_center_sk", "cc_call_center_sk")),
                            "GA")


def q94(t):
    # state IL -> GA (generator state pool); web company 'pri' is in the pool
    ws = t["web_site"].filter(col("web_company_name") == "pri")
    wr = t["web_returns"].select(col("wr_order_number").alias("ro"))
    return _shipping_report(t["web_sales"], wr, "ws", t,
                            (ws, ("ws_web_site_sk", "web_site_sk")), "GA")


def q18(t):
    # birth months / state list adapted to the generator pools
    cd1 = t["customer_demographics"].filter(
        (col("cd_gender") == "F") & (col("cd_education_status") == "Unknown"))
    cust = t["customer"].filter(col("c_birth_month").isin(1, 6, 8, 9, 12, 2))
    return (t["catalog_sales"]
            .join(t["date_dim"].filter(col("d_year") == 1998),
                  [("cs_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("cs_item_sk", "i_item_sk")])
            .join(cd1.select(col("cd_demo_sk").alias("cd1_sk"),
                             col("cd_dep_count").alias("cd1_dep_count")),
                  [("cs_bill_cdemo_sk", "cd1_sk")])
            .join(cust, [("cs_bill_customer_sk", "c_customer_sk")])
            .join(t["customer_demographics"].select(
                col("cd_demo_sk").alias("cd2_sk")),
                [("c_current_cdemo_sk", "cd2_sk")])
            .join(t["customer_address"].filter(
                col("ca_state").isin("TN", "IN", "SD", "OH", "TX", "GA")),
                [("c_current_addr_sk", "ca_address_sk")])
            .rollup("i_item_id", "ca_country", "ca_state", "ca_county")
            .agg(F.avg("cs_quantity").alias("agg1"),
                 F.avg("cs_list_price").alias("agg2"),
                 F.avg("cs_coupon_amt").alias("agg3"),
                 F.avg("cs_sales_price").alias("agg4"),
                 F.avg("cs_net_profit").alias("agg5"),
                 F.avg("c_birth_year").alias("agg6"),
                 F.avg("cd1_dep_count").alias("agg7"))
            .sort("ca_country", "ca_state", "ca_county", "i_item_id")
            .limit(100))


def q20(t):
    lo = datetime.date(1999, 2, 22)
    hi = lo + datetime.timedelta(days=30)
    base = (t["catalog_sales"]
            .join(t["item"].filter(col("i_category").isin("Sports", "Books",
                                                          "Home")),
                  [("cs_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                       & (col("d_date") <= lit(hi))),
                  [("cs_sold_date_sk", "d_date_sk")])
            .groupBy("i_item_id", "i_item_desc", "i_category", "i_class",
                     "i_current_price")
            .agg(F.sum("cs_ext_sales_price").alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (base.select("i_item_id", "i_item_desc", "i_category", "i_class",
                        "i_current_price", "itemrevenue",
                        (col("itemrevenue") * 100.0
                         / F.sum("itemrevenue").over(w)).alias("revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio")
            .limit(100))


def q21(t):
    pivot = lit(datetime.date(2000, 3, 11))
    dd = t["date_dim"].filter(
        (F.datediff(col("d_date"), pivot) >= -30)
        & (F.datediff(col("d_date"), pivot) <= 30))
    base = (t["inventory"]
            .join(t["warehouse"], [("inv_warehouse_sk", "w_warehouse_sk")])
            .join(t["item"].filter((col("i_current_price") >= 0.99)
                                   & (col("i_current_price") <= 1.49)),
                  [("inv_item_sk", "i_item_sk")])
            .join(dd, [("inv_date_sk", "d_date_sk")])
            .groupBy("w_warehouse_name", "i_item_id")
            .agg(F.sum(when(col("d_date") < pivot,
                            col("inv_quantity_on_hand")).otherwise(0))
                 .alias("inv_before"),
                 F.sum(when(col("d_date") >= pivot,
                            col("inv_quantity_on_hand")).otherwise(0))
                 .alias("inv_after")))
    ratio = when(col("inv_before") > 0,
                 col("inv_after") / col("inv_before")).otherwise(None)
    return (base.filter((ratio >= 2.0 / 3.0) & (ratio <= 3.0 / 2.0))
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def _sold_returned_rebought(t, d1_filter, d2_filter, d3_filter, aggs):
    """Shared q25/q29 chain: store sale -> store return -> catalog re-buy by
    the same customer."""
    ss = (t["store_sales"]
          .join(t["date_dim"].filter(d1_filter).select("d_date_sk"),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["item"], [("ss_item_sk", "i_item_sk")])
          .join(t["store"], [("ss_store_sk", "s_store_sk")]))
    sr = (t["store_returns"]
          .join(t["date_dim"].filter(d2_filter).select(
              col("d_date_sk").alias("d2_sk")),
              [("sr_returned_date_sk", "d2_sk")]))
    cs = (t["catalog_sales"]
          .join(t["date_dim"].filter(d3_filter).select(
              col("d_date_sk").alias("d3_sk")),
              [("cs_sold_date_sk", "d3_sk")]))
    return (ss.join(sr, [("ss_customer_sk", "sr_customer_sk"),
                         ("ss_item_sk", "sr_item_sk"),
                         ("ss_ticket_number", "sr_ticket_number")])
            .join(cs, [("sr_customer_sk", "cs_bill_customer_sk"),
                       ("sr_item_sk", "cs_item_sk")])
            .groupBy("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .agg(*aggs)
            .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .limit(100))


def q25(t):
    return _sold_returned_rebought(
        t,
        (col("d_moy") == 4) & (col("d_year") == 2001),
        (col("d_moy") >= 4) & (col("d_moy") <= 10) & (col("d_year") == 2001),
        (col("d_moy") >= 4) & (col("d_moy") <= 10) & (col("d_year") == 2001),
        [F.sum("ss_net_profit").alias("store_sales_profit"),
         F.sum("sr_net_loss").alias("store_returns_loss"),
         F.sum("cs_net_profit").alias("catalog_sales_profit")])


def q29(t):
    return _sold_returned_rebought(
        t,
        (col("d_moy") == 9) & (col("d_year") == 1999),
        (col("d_moy") >= 9) & (col("d_moy") <= 12) & (col("d_year") == 1999),
        col("d_year").isin(1999, 2000, 2001),
        [F.sum("ss_quantity").alias("store_sales_quantity"),
         F.sum("sr_return_quantity").alias("store_returns_quantity"),
         F.sum("cs_quantity").alias("catalog_sales_quantity")])


def q26(t):
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    promo = t["promotion"].filter((col("p_channel_email") == "N")
                                  | (col("p_channel_event") == "N"))
    return (t["catalog_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("cs_sold_date_sk", "d_date_sk")])
            .join(t["item"], [("cs_item_sk", "i_item_sk")])
            .join(cd, [("cs_bill_cdemo_sk", "cd_demo_sk")])
            .join(promo, [("cs_promo_sk", "p_promo_sk")])
            .groupBy("i_item_id")
            .agg(F.avg("cs_quantity").alias("agg1"),
                 F.avg("cs_list_price").alias("agg2"),
                 F.avg("cs_coupon_amt").alias("agg3"),
                 F.avg("cs_sales_price").alias("agg4"))
            .sort("i_item_id").limit(100))


def _excess_discount(t, sales, prefix, manufact_id):
    """Shared q32/q92: discounts above 1.3x the item's window average."""
    p = prefix
    lo = datetime.date(2000, 1, 27)
    hi = lo + datetime.timedelta(days=90)
    dd = (t["date_dim"]
          .filter((col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
          .select("d_date_sk"))
    windowed = sales.join(dd, [(f"{p}_sold_date_sk", "d_date_sk")])
    thresholds = (windowed
                  .groupBy(col(f"{p}_item_sk").alias("th_item"))
                  .agg(F.avg(f"{p}_ext_discount_amt").alias("th_avg"))
                  .select("th_item",
                          (col("th_avg") * 1.3).alias("threshold")))
    return (windowed
            .join(t["item"].filter(col("i_manufact_id") == manufact_id),
                  [(f"{p}_item_sk", "i_item_sk")])
            .join(thresholds, [(f"{p}_item_sk", "th_item")])
            .filter(col(f"{p}_ext_discount_amt") > col("threshold"))
            .agg(F.sum(f"{p}_ext_discount_amt")
                 .alias("excess_discount_amount")))


def q32(t):
    # manufact 977 -> 77 (the generator cycles manufact ids over 1..n_item)
    return _excess_discount(t, t["catalog_sales"], "cs", 77)


def q92(t):
    # manufact 350 -> 50
    return _excess_discount(t, t["web_sales"], "ws", 50)


def q37(t):
    lo = datetime.date(2000, 2, 1)
    hi = lo + datetime.timedelta(days=60)
    # manufact list 677/940/694/808 -> 8/33/58/83 (the generator's planted
    # mid-price band: manufact id == item sk cycle, plants at sk%25==8)
    items = t["item"].filter(
        (col("i_current_price") >= 68) & (col("i_current_price") <= 98)
        & col("i_manufact_id").isin(8, 33, 58, 83))
    inv = (t["inventory"]
           .filter((col("inv_quantity_on_hand") >= 100)
                   & (col("inv_quantity_on_hand") <= 500))
           .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                      & (col("d_date") <= lit(hi))),
                 [("inv_date_sk", "d_date_sk")]))
    return (items.join(inv, [("i_item_sk", "inv_item_sk")])
            .join(t["catalog_sales"], [("i_item_sk", "cs_item_sk")],
                  "leftsemi")
            .select("i_item_id", "i_item_desc", "i_current_price")
            .dropDuplicates()
            .sort("i_item_id").limit(100))


def q40(t):
    pivot = datetime.date(2000, 3, 11)
    dd = t["date_dim"].filter(
        (F.datediff(col("d_date"), lit(pivot)) >= -30)
        & (F.datediff(col("d_date"), lit(pivot)) <= 30))
    net = col("cs_sales_price") - F.coalesce(col("cr_refunded_cash"),
                                             lit(0.0))
    return (t["catalog_sales"]
            .join(t["catalog_returns"],
                  [("cs_order_number", "cr_order_number"),
                   ("cs_item_sk", "cr_item_sk")], "left")
            .join(t["warehouse"], [("cs_warehouse_sk", "w_warehouse_sk")])
            .join(t["item"].filter((col("i_current_price") >= 0.99)
                                   & (col("i_current_price") <= 1.49)),
                  [("cs_item_sk", "i_item_sk")])
            .join(dd, [("cs_sold_date_sk", "d_date_sk")])
            .groupBy("w_state", "i_item_id")
            .agg(F.sum(when(col("d_date") < lit(pivot), net).otherwise(0.0))
                 .alias("sales_before"),
                 F.sum(when(col("d_date") >= lit(pivot), net).otherwise(0.0))
                 .alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q45(t):
    zips = ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792"]
    marked = (t["item"]
              .filter(col("i_item_sk").isin(2, 3, 5, 7, 11, 13, 17, 19,
                                            23, 29))
              .select(col("i_item_id").alias("m_id"))
              .withColumn("m_flag", lit(1)))
    return (t["web_sales"]
            .join(t["customer"], [("ws_bill_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk",
                                           "ca_address_sk")])
            .join(t["item"], [("ws_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_qoy") == 2)
                                       & (col("d_year") == 2001)),
                  [("ws_sold_date_sk", "d_date_sk")])
            .join(marked.dropDuplicates(), [("i_item_id", "m_id")], "left")
            .filter(F.substring("ca_zip", 1, 5).isin(*zips)
                    | col("m_flag").isNotNull())
            .groupBy("ca_zip", "ca_city")
            .agg(F.sum("ws_sales_price").alias("sum_ws_sales_price"))
            .sort("ca_zip", "ca_city").limit(100))


def _ship_day_buckets(t, sales, prefix, dim, dim_key, dim_name):
    p = prefix
    days = col(f"{p}_ship_date_sk") - col(f"{p}_sold_date_sk")
    bucket = lambda lo, hi: F.sum(  # noqa: E731
        when(((days > lo) if lo is not None else lit(True))
             & ((days <= hi) if hi is not None else lit(True)), 1)
        .otherwise(0))
    return (sales
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [(f"{p}_ship_date_sk", "d_date_sk")])
            .join(t["warehouse"], [(f"{p}_warehouse_sk", "w_warehouse_sk")])
            .join(t["ship_mode"], [(f"{p}_ship_mode_sk", "sm_ship_mode_sk")])
            .join(dim, [dim_key])
            .groupBy(F.substring("w_warehouse_name", 1, 20).alias("wname"),
                     "sm_type", dim_name)
            .agg(bucket(None, 30).alias("d30"),
                 bucket(30, 60).alias("d31_60"),
                 bucket(60, 90).alias("d61_90"),
                 bucket(90, 120).alias("d91_120"),
                 bucket(120, None).alias("d_over_120"))
            .sort("wname", "sm_type", dim_name)
            .limit(100))


def q62(t):
    return _ship_day_buckets(t, t["web_sales"], "ws", t["web_site"],
                             ("ws_web_site_sk", "web_site_sk"), "web_name")


def q99(t):
    return _ship_day_buckets(t, t["catalog_sales"], "cs", t["call_center"],
                             ("cs_call_center_sk", "cc_call_center_sk"),
                             "cc_name")


def q90(t):
    def slot(h_lo):
        return (t["web_sales"]
                .join(t["household_demographics"]
                      .filter(col("hd_dep_count") == 6),
                      [("ws_ship_hdemo_sk", "hd_demo_sk")])
                .join(t["time_dim"].filter((col("t_hour") >= h_lo)
                                           & (col("t_hour") <= h_lo + 1)),
                      [("ws_sold_time_sk", "t_time_sk")])
                .join(t["web_page"].filter((col("wp_char_count") >= 5000)
                                           & (col("wp_char_count") <= 5200)),
                      [("ws_web_page_sk", "wp_web_page_sk")])
                .agg(F.count().alias("amc" if h_lo == 8 else "pmc")))

    return (slot(8).crossJoin(slot(19))
            .select((col("amc") / col("pmc")).alias("am_pm_ratio")))


def q93(t):
    # reason desc adapted to the generated reason table
    act = when(col("sr_return_quantity").isNotNull(),
               (col("ss_quantity") - col("sr_return_quantity"))
               * col("ss_sales_price")).otherwise(
        col("ss_quantity") * col("ss_sales_price"))
    return (t["store_sales"]
            .join(t["store_returns"],
                  [("ss_item_sk", "sr_item_sk"),
                   ("ss_ticket_number", "sr_ticket_number")], "left")
            .join(t["reason"].filter(
                col("r_reason_desc") == "Package was damaged"),
                [("sr_reason_sk", "r_reason_sk")])
            .select("ss_customer_sk", act.alias("act_sales"))
            .groupBy("ss_customer_sk")
            .agg(F.sum("act_sales").alias("sumsales"))
            .sort("sumsales", "ss_customer_sk")
            .limit(100))


# ---------------------------------------------------------------------------
# multi-channel, window and scalar-subquery queries
# ---------------------------------------------------------------------------

def q6(t):
    month = (t["date_dim"]
             .filter((col("d_year") == 2001) & (col("d_moy") == 1))
             .select("d_month_seq").distinct()
             .withColumnRenamed("d_month_seq", "m_seq"))
    cat_avg = (t["item"].groupBy(col("i_category").alias("cat"))
               .agg(F.avg("i_current_price").alias("cat_avg")))
    pricey = (t["item"].join(cat_avg, [("i_category", "cat")])
              .filter(col("i_current_price") > 1.2 * col("cat_avg"))
              .select("i_item_sk"))
    return (t["store_sales"]
            .join(t["date_dim"].join(month, [("d_month_seq", "m_seq")],
                                     "leftsemi"),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(pricey, [("ss_item_sk", "i_item_sk")], "leftsemi")
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"], [("c_current_addr_sk",
                                           "ca_address_sk")])
            .groupBy(col("ca_state").alias("state"))
            .agg(F.count().alias("cnt"))
            .filter(col("cnt") >= 10)
            .sort("cnt").limit(100))


def q13(t):
    # state triplets adapted to the generator pool
    demo_ok = (((col("cd_marital_status") == "M")
                & (col("cd_education_status") == "Advanced Degree")
                & (col("ss_sales_price") >= 100.0)
                & (col("ss_sales_price") <= 150.0)
                & (col("hd_dep_count") == 3))
               | ((col("cd_marital_status") == "S")
                  & (col("cd_education_status") == "College")
                  & (col("ss_sales_price") >= 50.0)
                  & (col("ss_sales_price") <= 100.0)
                  & (col("hd_dep_count") == 1))
               | ((col("cd_marital_status") == "W")
                  & (col("cd_education_status") == "2 yr Degree")
                  & (col("ss_sales_price") >= 150.0)
                  & (col("ss_sales_price") <= 200.0)
                  & (col("hd_dep_count") == 1)))
    geo_ok = (((col("ca_country") == "United States")
               & col("ca_state").isin("TX", "OH", "GA")
               & (col("ss_net_profit") >= 100)
               & (col("ss_net_profit") <= 200))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("TN", "IN", "SD")
                 & (col("ss_net_profit") >= 150)
                 & (col("ss_net_profit") <= 300))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("LA", "MI", "SC")
                 & (col("ss_net_profit") >= 50)
                 & (col("ss_net_profit") <= 250)))
    return (t["store_sales"]
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["date_dim"].filter(col("d_year") == 2001),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["customer_demographics"], [("ss_cdemo_sk", "cd_demo_sk")])
            .join(t["household_demographics"], [("ss_hdemo_sk", "hd_demo_sk")])
            .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
            .filter(demo_ok & geo_ok)
            .agg(F.avg("ss_quantity").alias("avg_quantity"),
                 F.avg("ss_ext_sales_price").alias("avg_ext_sales_price"),
                 F.avg("ss_ext_wholesale_cost").alias("avg_ext_wholesale"),
                 F.sum("ss_ext_wholesale_cost").alias("sum_ext_wholesale")))


def q17(t):
    ss = (t["store_sales"]
          .join(t["date_dim"].filter(col("d_quarter_name") == "2001Q1")
                .select("d_date_sk"),
                [("ss_sold_date_sk", "d_date_sk")])
          .join(t["item"], [("ss_item_sk", "i_item_sk")])
          .join(t["store"], [("ss_store_sk", "s_store_sk")]))
    q123 = ("2001Q1", "2001Q2", "2001Q3")
    sr = (t["store_returns"]
          .join(t["date_dim"].filter(col("d_quarter_name").isin(*q123))
                .select(col("d_date_sk").alias("d2_sk")),
                [("sr_returned_date_sk", "d2_sk")]))
    cs = (t["catalog_sales"]
          .join(t["date_dim"].filter(col("d_quarter_name").isin(*q123))
                .select(col("d_date_sk").alias("d3_sk")),
                [("cs_sold_date_sk", "d3_sk")]))
    cov = lambda c: F.stddev(c) / F.avg(c)  # noqa: E731
    return (ss.join(sr, [("ss_customer_sk", "sr_customer_sk"),
                         ("ss_item_sk", "sr_item_sk"),
                         ("ss_ticket_number", "sr_ticket_number")])
            .join(cs, [("sr_customer_sk", "cs_bill_customer_sk"),
                       ("sr_item_sk", "cs_item_sk")])
            .groupBy("i_item_id", "i_item_desc", "s_state")
            .agg(F.count("ss_quantity").alias("store_sales_quantitycount"),
                 F.avg("ss_quantity").alias("store_sales_quantityave"),
                 F.stddev("ss_quantity").alias("store_sales_quantitystdev"),
                 F.count("sr_return_quantity")
                 .alias("store_returns_quantitycount"),
                 F.avg("sr_return_quantity")
                 .alias("store_returns_quantityave"),
                 F.stddev("sr_return_quantity")
                 .alias("store_returns_quantitystdev"),
                 F.count("cs_quantity").alias("catalog_sales_quantitycount"),
                 F.avg("cs_quantity").alias("catalog_sales_quantityave"),
                 F.stddev("cs_quantity").alias("catalog_sales_quantitystdev"))
            .withColumn("store_sales_quantitycov",
                        col("store_sales_quantitystdev")
                        / col("store_sales_quantityave"))
            .withColumn("store_returns_quantitycov",
                        col("store_returns_quantitystdev")
                        / col("store_returns_quantityave"))
            .withColumn("catalog_sales_quantitycov",
                        col("catalog_sales_quantitystdev")
                        / col("catalog_sales_quantityave"))
            .sort("i_item_id", "i_item_desc", "s_state")
            .limit(100))


def q28(t):
    buckets = [
        # (qty_lo, qty_hi, lp_lo, coupon_lo, cost_lo, name)
        (0, 5, 8, 459, 57, "b1"),
        (6, 10, 90, 2323, 31, "b2"),
        (11, 15, 142, 12214, 79, "b3"),
        (16, 20, 135, 6071, 38, "b4"),
        (21, 25, 122, 836, 17, "b5"),
        (26, 30, 154, 7326, 7, "b6"),
    ]

    def bucket(qlo, qhi, lp, cp, wc, name):
        return (t["store_sales"]
                .filter((col("ss_quantity") >= qlo)
                        & (col("ss_quantity") <= qhi)
                        & (((col("ss_list_price") >= lp)
                            & (col("ss_list_price") <= lp + 10))
                           | ((col("ss_coupon_amt") >= cp)
                              & (col("ss_coupon_amt") <= cp + 1000))
                           | ((col("ss_wholesale_cost") >= wc)
                              & (col("ss_wholesale_cost") <= wc + 20))))
                .agg(F.avg("ss_list_price").alias(f"{name}_lp"),
                     F.count("ss_list_price").alias(f"{name}_cnt"),
                     F.countDistinct("ss_list_price").alias(f"{name}_cntd")))

    out = bucket(*buckets[0])
    for b in buckets[1:]:
        out = out.crossJoin(bucket(*b))
    return out.limit(100)


def _channel_union_by(t, key_out, item_filter_col, item_filter_vals,
                      year, moy):
    """Shared q33/q60 shape: per-channel revenue for an item subset, unioned
    and re-aggregated. key_out is 'i_manufact_id' or 'i_item_id'."""
    subset = (t["item"]
              .filter(col(item_filter_col).isin(*item_filter_vals))
              .select(col(key_out).alias("sub_key")).distinct())
    dd = (t["date_dim"]
          .filter((col("d_year") == year) & (col("d_moy") == moy))
          .select("d_date_sk"))
    addr = (t["customer_address"].filter(col("ca_gmt_offset") == -5.0)
            .select("ca_address_sk"))

    def channel(sales, item_k, date_k, addr_k, amount):
        return (sales
                .join(dd, [(date_k, "d_date_sk")], "leftsemi")
                .join(addr, [(addr_k, "ca_address_sk")], "leftsemi")
                .join(t["item"], [(item_k, "i_item_sk")])
                .join(subset, [(key_out, "sub_key")], "leftsemi")
                .groupBy(key_out)
                .agg(F.sum(amount).alias("total_sales")))

    u = (channel(t["store_sales"], "ss_item_sk", "ss_sold_date_sk",
                 "ss_addr_sk", "ss_ext_sales_price")
         .union(channel(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk",
                        "cs_bill_addr_sk", "cs_ext_sales_price"))
         .union(channel(t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
                        "ws_bill_addr_sk", "ws_ext_sales_price")))
    return u.groupBy(key_out).agg(F.sum("total_sales").alias("total_sales"))


def q33(t):
    return (_channel_union_by(t, "i_manufact_id", "i_category",
                              ["Electronics"], 1998, 5)
            .sort("total_sales").limit(100))


def q60(t):
    return (_channel_union_by(t, "i_item_id", "i_category", ["Music"],
                              1998, 9)
            .sort("i_item_id", "total_sales").limit(100))


def _rollup_rank(t, sales, item_k, date_k, value, date_filter, extra_joins):
    """Shared q36/q86 shape: rollup over (category, class) with a rank within
    each hierarchy level. grouping() is derived from the rolled-up nulls
    (generated categories/classes are never null)."""
    base = sales.join(t["date_dim"].filter(date_filter),
                      [(date_k, "d_date_sk")])
    for frame, key in extra_joins:
        base = base.join(frame, [key])
    base = base.join(t["item"], [(item_k, "i_item_sk")])
    rolled = (base.rollup("i_category", "i_class")
              .agg(F.sum(value[0]).alias("_num"),
                   *([F.sum(value[1]).alias("_den")] if value[1] else [])))
    measure = (col("_num") / col("_den")) if value[1] else col("_num")
    lochierarchy = (when(col("i_category").isNull(), 1).otherwise(0)
                    + when(col("i_class").isNull(), 1).otherwise(0))
    tmp = rolled.select(
        measure.alias("total_sum"), "i_category", "i_class",
        lochierarchy.alias("lochierarchy"),
        when(col("i_class").isNotNull(), col("i_category"))
        .otherwise(None).alias("_parent"))
    w = (Window.partitionBy("lochierarchy", "_parent")
         .orderBy(col("total_sum").desc() if value[1] is None
                  else col("total_sum").asc()))
    return (tmp.select("total_sum", "i_category", "i_class", "lochierarchy",
                       F.rank().over(w).alias("rank_within_parent"))
            .sort(col("lochierarchy").desc(),
                  when(col("lochierarchy") == 0, col("i_category"))
                  .otherwise(None),
                  "rank_within_parent")
            .limit(100))


def q36(t):
    return _rollup_rank(
        t, t["store_sales"], "ss_item_sk", "ss_sold_date_sk",
        ("ss_net_profit", "ss_ext_sales_price"),
        col("d_year") == 2001,
        [(t["store"].filter(col("s_state") == "TN"),
          ("ss_store_sk", "s_store_sk"))])


def q86(t):
    return _rollup_rank(
        t, t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
        ("ws_net_paid", None),
        (col("d_month_seq") >= 1200) & (col("d_month_seq") <= 1211),
        [])


def q44(t):
    # store 4 -> the generator's 6-store pool includes it
    base = (t["store_sales"].filter(col("ss_store_sk") == 4)
            .groupBy(col("ss_item_sk").alias("item_sk"))
            .agg(F.avg("ss_net_profit").alias("rank_col")))
    floor_ = (t["store_sales"]
              .filter((col("ss_store_sk") == 4) & col("ss_addr_sk").isNull())
              .groupBy("ss_store_sk")
              .agg(F.avg("ss_net_profit").alias("f_avg"))
              .select((col("f_avg") * 0.9).alias("floor_val")))
    qualified = (base.crossJoin(floor_)
                 .filter(col("rank_col") > col("floor_val")))
    asc = (qualified.select(
        "item_sk", F.rank().over(Window.orderBy(col("rank_col").asc()))
        .alias("rnk")).filter(col("rnk") < 11))
    desc = (qualified.select(
        col("item_sk").alias("item_sk_d"),
        F.rank().over(Window.orderBy(col("rank_col").desc()))
        .alias("rnk_d")).filter(col("rnk_d") < 11))
    return (asc.join(desc, [("rnk", "rnk_d")])
            .join(t["item"].select(col("i_item_sk").alias("i1_sk"),
                                   col("i_product_name").alias(
                                       "best_performing")),
                  [("item_sk", "i1_sk")])
            .join(t["item"].select(col("i_item_sk").alias("i2_sk"),
                                   col("i_product_name").alias(
                                       "worst_performing")),
                  [("item_sk_d", "i2_sk")])
            .select("rnk", "best_performing", "worst_performing")
            .sort("rnk").limit(100))


def q47(t):
    v1 = (t["store_sales"]
          .join(t["item"], [("ss_item_sk", "i_item_sk")])
          .join(t["date_dim"].filter(
              (col("d_year") == 1999)
              | ((col("d_year") == 1998) & (col("d_moy") == 12))
              | ((col("d_year") == 2000) & (col("d_moy") == 1))),
              [("ss_sold_date_sk", "d_date_sk")])
          .join(t["store"], [("ss_store_sk", "s_store_sk")])
          .groupBy("i_category", "i_brand", "s_store_name", "s_company_name",
                   "d_year", "d_moy")
          .agg(F.sum("ss_sales_price").alias("sum_sales")))
    wavg = Window.partitionBy("i_category", "i_brand", "s_store_name",
                              "s_company_name", "d_year")
    wrank = (Window.partitionBy("i_category", "i_brand", "s_store_name",
                                "s_company_name")
             .orderBy("d_year", "d_moy"))
    v1 = v1.select("i_category", "i_brand", "s_store_name", "s_company_name",
                   "d_year", "d_moy", "sum_sales",
                   F.avg("sum_sales").over(wavg).alias("avg_monthly_sales"),
                   F.rank().over(wrank).alias("rn"))
    lagf = v1.select(col("i_category").alias("lc"), col("i_brand").alias("lb"),
                     col("s_store_name").alias("lsn"),
                     col("s_company_name").alias("lcn"),
                     col("rn").alias("lrn"),
                     col("sum_sales").alias("psum"))
    leadf = v1.select(col("i_category").alias("dc"),
                      col("i_brand").alias("db"),
                      col("s_store_name").alias("dsn"),
                      col("s_company_name").alias("dcn"),
                      col("rn").alias("drn"),
                      col("sum_sales").alias("nsum"))
    v2 = (v1.withColumn("rn_prev", col("rn") - 1)
          .withColumn("rn_next", col("rn") + 1)
          .join(lagf, [("i_category", "lc"), ("i_brand", "lb"),
                       ("s_store_name", "lsn"), ("s_company_name", "lcn"),
                       ("rn_prev", "lrn")])
          .join(leadf, [("i_category", "dc"), ("i_brand", "db"),
                        ("s_store_name", "dsn"), ("s_company_name", "dcn"),
                        ("rn_next", "drn")]))
    dev = when(col("avg_monthly_sales") > 0,
               F.abs(col("sum_sales") - col("avg_monthly_sales"))
               / col("avg_monthly_sales")).otherwise(None)
    return (v2.filter((col("d_year") == 1999)
                      & (col("avg_monthly_sales") > 0) & (dev > 0.1))
            .select("i_category", "i_brand", "s_store_name", "s_company_name",
                    "d_year", "d_moy", "avg_monthly_sales", "sum_sales",
                    "psum", "nsum",
                    (col("sum_sales") - col("avg_monthly_sales")).alias("_d"))
            .sort("_d", "s_store_name").drop("_d")
            .limit(100))


def _manager_monthly_deviation(t, group_key, time_key):
    """Shared q53/q63 shape."""
    cls_a = (col("i_category").isin("Books", "Children", "Electronics")
             & col("i_class").isin("personal", "portable", "reference",
                                   "self-help")
             & col("i_brand").isin("scholaramalgamalg #14",
                                   "scholaramalgamalg #7",
                                   "exportiunivamalg #9",
                                   "scholaramalgamalg #9"))
    cls_b = (col("i_category").isin("Women", "Music", "Men")
             & col("i_class").isin("accessories", "classical", "fragrances",
                                   "pants")
             & col("i_brand").isin("amalgimporto #1", "edu packscholar #1",
                                   "exportiimporto #1", "importoamalg #1"))
    base = (t["store_sales"]
            .join(t["item"].filter(cls_a | cls_b),
                  [("ss_item_sk", "i_item_sk")])
            .join(t["date_dim"].filter((col("d_month_seq") >= 1200)
                                       & (col("d_month_seq") <= 1211)),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy(group_key, time_key)
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    w = Window.partitionBy(group_key)
    tmp = base.select(group_key, "sum_sales",
                      F.avg("sum_sales").over(w).alias("avg_sales"))
    dev = when(col("avg_sales") > 0,
               F.abs(col("sum_sales") - col("avg_sales"))
               / col("avg_sales")).otherwise(None)
    return tmp.filter(dev > 0.1)


def q53(t):
    return (_manager_monthly_deviation(t, "i_manufact_id", "d_qoy")
            .withColumnRenamed("avg_sales", "avg_quarterly_sales")
            .sort("avg_quarterly_sales", "sum_sales", "i_manufact_id")
            .limit(100))


def q63(t):
    return (_manager_monthly_deviation(t, "i_manager_id", "d_moy")
            .withColumnRenamed("avg_sales", "avg_monthly_sales")
            .sort("i_manager_id", "avg_monthly_sales", "sum_sales")
            .limit(100))


def q69(t):
    dd = (t["date_dim"]
          .filter((col("d_year") == 2001) & (col("d_moy") >= 4)
                  & (col("d_moy") <= 6))
          .select("d_date_sk"))
    bought_store = (t["store_sales"]
                    .join(dd, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
                    .select(col("ss_customer_sk").alias("b_sk")))
    bought_web = (t["web_sales"]
                  .join(dd, [("ws_sold_date_sk", "d_date_sk")], "leftsemi")
                  .select(col("ws_bill_customer_sk").alias("b_sk")))
    bought_cat = (t["catalog_sales"]
                  .join(dd, [("cs_sold_date_sk", "d_date_sk")], "leftsemi")
                  .select(col("cs_ship_customer_sk").alias("b_sk")))
    return (t["customer"]
            .join(t["customer_address"].filter(
                col("ca_state").isin("TN", "GA", "SD")),
                [("c_current_addr_sk", "ca_address_sk")])
            .join(t["customer_demographics"],
                  [("c_current_cdemo_sk", "cd_demo_sk")])
            .join(bought_store, [("c_customer_sk", "b_sk")], "leftsemi")
            .join(bought_web, [("c_customer_sk", "b_sk")], "leftanti")
            .join(bought_cat, [("c_customer_sk", "b_sk")], "leftanti")
            .groupBy("cd_gender", "cd_marital_status", "cd_education_status",
                     "cd_purchase_estimate", "cd_credit_rating")
            .agg(F.count().alias("cnt1"))
            .select("cd_gender", "cd_marital_status", "cd_education_status",
                    "cnt1", "cd_purchase_estimate",
                    col("cnt1").alias("cnt2"), "cd_credit_rating",
                    col("cnt1").alias("cnt3"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status",
                  "cd_purchase_estimate", "cd_credit_rating")
            .limit(100))


def q76(t):
    def channel(sales, null_col, item_k, date_k, price, name):
        return (sales.filter(col(null_col).isNull())
                .join(t["item"], [(item_k, "i_item_sk")])
                .join(t["date_dim"], [(date_k, "d_date_sk")])
                .select(lit(name).alias("channel"),
                        lit(null_col).alias("col_name"), "d_year", "d_qoy",
                        "i_category", col(price).alias("ext_sales_price")))

    u = (channel(t["store_sales"], "ss_store_sk", "ss_item_sk",
                 "ss_sold_date_sk", "ss_ext_sales_price", "store")
         .union(channel(t["web_sales"], "ws_ship_customer_sk", "ws_item_sk",
                        "ws_sold_date_sk", "ws_ext_sales_price", "web"))
         .union(channel(t["catalog_sales"], "cs_ship_addr_sk", "cs_item_sk",
                        "cs_sold_date_sk", "cs_ext_sales_price", "catalog")))
    return (u.groupBy("channel", "col_name", "d_year", "d_qoy", "i_category")
            .agg(F.count().alias("sales_cnt"),
                 F.sum("ext_sales_price").alias("sales_amt"))
            .sort("channel", "col_name", "d_year", "d_qoy", "i_category")
            .limit(100))


def q88(t):
    hd = t["household_demographics"].filter(
        ((col("hd_dep_count") == 4) & (col("hd_vehicle_count") <= 6))
        | ((col("hd_dep_count") == 2) & (col("hd_vehicle_count") <= 4))
        | ((col("hd_dep_count") == 0) & (col("hd_vehicle_count") <= 2)))
    store = t["store"].filter(col("s_store_name") == "ese")

    def half_hour(hour, first_half, name):
        td = t["time_dim"].filter(
            (col("t_hour") == hour)
            & ((col("t_minute") < 30) if first_half
               else (col("t_minute") >= 30)))
        return (t["store_sales"]
                .join(td, [("ss_sold_time_sk", "t_time_sk")], "leftsemi")
                .join(hd, [("ss_hdemo_sk", "hd_demo_sk")], "leftsemi")
                .join(store, [("ss_store_sk", "s_store_sk")], "leftsemi")
                .agg(F.count().alias(name)))

    slots = [(8, False, "h8_30_to_9"), (9, True, "h9_to_9_30"),
             (9, False, "h9_30_to_10"), (10, True, "h10_to_10_30"),
             (10, False, "h10_30_to_11"), (11, True, "h11_to_11_30"),
             (11, False, "h11_30_to_12"), (12, True, "h12_to_12_30")]
    out = half_hour(*slots[0])
    for s in slots[1:]:
        out = out.crossJoin(half_hour(*s))
    return out


def q41(t):
    """Manufacturers with qualifying item variants (correlated count(*)>0 as
    a semi-join on i_manufact). Manufact-id window 738..778 -> 38..78 (the
    generator cycles ids over 1..n_item)."""
    combo = lambda cat, colors, units, sizes: (  # noqa: E731
        (col("i_category") == cat) & col("i_color").isin(*colors)
        & col("i_units").isin(*units) & col("i_size").isin(*sizes))
    variants = (combo("Women", ("powder", "khaki"), ("Ounce", "Oz"),
                      ("medium", "extra large"))
                | combo("Women", ("brown", "honeydew"), ("Bunch", "Ton"),
                        ("N/A", "small"))
                | combo("Men", ("floral", "deep"), ("N/A", "Dozen"),
                        ("petite", "large"))
                | combo("Men", ("light", "cornflower"), ("Box", "Pound"),
                        ("medium", "extra large"))
                | combo("Women", ("midnight", "snow"), ("Pallet", "Gross"),
                        ("medium", "extra large"))
                | combo("Women", ("cyan", "papaya"), ("Cup", "Dram"),
                        ("N/A", "small"))
                | combo("Men", ("orange", "frosted"), ("Each", "Tbl"),
                        ("petite", "large"))
                | combo("Men", ("forest", "ghost"), ("Lb", "Bundle"),
                        ("medium", "extra large")))
    qualifying = (t["item"].filter(variants)
                  .select(col("i_manufact").alias("qm")).distinct())
    return (t["item"]
            .filter((col("i_manufact_id") >= 38)
                    & (col("i_manufact_id") <= 78))
            .join(qualifying, [("i_manufact", "qm")], "leftsemi")
            .select("i_product_name").distinct()
            .sort("i_product_name").limit(100))


def q48(t):
    # state triplets adapted to the generator pool
    demo_ok = (((col("cd_marital_status") == "M")
                & (col("cd_education_status") == "4 yr Degree")
                & (col("ss_sales_price") >= 100.0)
                & (col("ss_sales_price") <= 150.0))
               | ((col("cd_marital_status") == "D")
                  & (col("cd_education_status") == "2 yr Degree")
                  & (col("ss_sales_price") >= 50.0)
                  & (col("ss_sales_price") <= 100.0))
               | ((col("cd_marital_status") == "S")
                  & (col("cd_education_status") == "College")
                  & (col("ss_sales_price") >= 150.0)
                  & (col("ss_sales_price") <= 200.0)))
    geo_ok = (((col("ca_country") == "United States")
               & col("ca_state").isin("TX", "OH", "GA")
               & (col("ss_net_profit") >= 0) & (col("ss_net_profit") <= 2000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("TN", "IN", "SD")
                 & (col("ss_net_profit") >= 150)
                 & (col("ss_net_profit") <= 3000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("LA", "MI", "CA")
                 & (col("ss_net_profit") >= 50)
                 & (col("ss_net_profit") <= 25000)))
    return (t["store_sales"]
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["date_dim"].filter(col("d_year") == 2000),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["customer_demographics"], [("ss_cdemo_sk", "cd_demo_sk")])
            .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
            .filter(demo_ok & geo_ok)
            .agg(F.sum("ss_quantity").alias("sum_quantity")))


def q50(t):
    days = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    bucket = lambda lo, hi: F.sum(  # noqa: E731
        when(((days > lo) if lo is not None else lit(True))
             & ((days <= hi) if hi is not None else lit(True)), 1)
        .otherwise(0))
    return (t["store_sales"]
            .join(t["store_returns"]
                  .join(t["date_dim"].filter((col("d_year") == 2001)
                                             & (col("d_moy") == 8))
                        .select(col("d_date_sk").alias("d2_sk")),
                        [("sr_returned_date_sk", "d2_sk")]),
                  [("ss_ticket_number", "sr_ticket_number"),
                   ("ss_item_sk", "sr_item_sk"),
                   ("ss_customer_sk", "sr_customer_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .groupBy("s_store_name", "s_company_id", "s_street_number",
                     "s_street_name", "s_street_type", "s_suite_number",
                     "s_city", "s_county", "s_state", "s_zip")
            .agg(bucket(None, 30).alias("d30"),
                 bucket(30, 60).alias("d31_60"),
                 bucket(60, 90).alias("d61_90"),
                 bucket(90, 120).alias("d91_120"),
                 bucket(120, None).alias("d_over_120"))
            .sort("s_store_name", "s_company_id", "s_street_number",
                  "s_street_name", "s_street_type", "s_suite_number",
                  "s_city", "s_county", "s_state", "s_zip")
            .limit(100))


def q61(t):
    def slice_sales(with_promo):
        base = (t["store_sales"]
                .join(t["date_dim"].filter((col("d_year") == 1998)
                                           & (col("d_moy") == 11)),
                      [("ss_sold_date_sk", "d_date_sk")])
                .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                      [("ss_store_sk", "s_store_sk")])
                .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
                .join(t["customer_address"]
                      .filter(col("ca_gmt_offset") == -5.0),
                      [("c_current_addr_sk", "ca_address_sk")])
                .join(t["item"].filter(col("i_category") == "Jewelry"),
                      [("ss_item_sk", "i_item_sk")]))
        if with_promo:
            base = base.join(
                t["promotion"].filter((col("p_channel_dmail") == "Y")
                                      | (col("p_channel_email") == "Y")
                                      | (col("p_channel_tv") == "Y")),
                [("ss_promo_sk", "p_promo_sk")])
        name = "promotions" if with_promo else "total"
        return base.agg(F.sum("ss_ext_sales_price").alias(name))

    return (slice_sales(True).crossJoin(slice_sales(False))
            .select("promotions", "total",
                    (col("promotions") / col("total") * 100.0)
                    .alias("promo_pct")))


def q71(t):
    dd = (t["date_dim"].filter((col("d_moy") == 11) & (col("d_year") == 1999))
          .select("d_date_sk"))

    def channel(sales, price, item_k, date_k, time_k):
        return (sales.join(dd, [(date_k, "d_date_sk")], "leftsemi")
                .select(col(price).alias("ext_price"),
                        col(item_k).alias("sold_item_sk"),
                        col(time_k).alias("time_sk")))

    u = (channel(t["web_sales"], "ws_ext_sales_price", "ws_item_sk",
                 "ws_sold_date_sk", "ws_sold_time_sk")
         .union(channel(t["catalog_sales"], "cs_ext_sales_price",
                        "cs_item_sk", "cs_sold_date_sk", "cs_sold_time_sk"))
         .union(channel(t["store_sales"], "ss_ext_sales_price", "ss_item_sk",
                        "ss_sold_date_sk", "ss_sold_time_sk")))
    return (u.join(t["item"].filter(col("i_manager_id") == 1),
                   [("sold_item_sk", "i_item_sk")])
            .join(t["time_dim"].filter(col("t_meal_time")
                                       .isin("breakfast", "dinner")),
                  [("time_sk", "t_time_sk")])
            .groupBy("i_brand", "i_brand_id", "t_hour", "t_minute")
            .agg(F.sum("ext_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), "t_hour", "t_minute",
                    "ext_price")
            .sort(col("ext_price").desc(), "brand_id"))


def q82(t):
    lo = datetime.date(2000, 5, 25)
    hi = lo + datetime.timedelta(days=60)
    # price 62..92 overlaps the generator's planted 68-98 band; manufact list
    # 129/270/821/423 -> the planted ids 8/33/58/83 (like q37)
    items = t["item"].filter(
        (col("i_current_price") >= 62) & (col("i_current_price") <= 92)
        & col("i_manufact_id").isin(8, 33, 58, 83))
    inv = (t["inventory"]
           .filter((col("inv_quantity_on_hand") >= 100)
                   & (col("inv_quantity_on_hand") <= 500))
           .join(t["date_dim"].filter((col("d_date") >= lit(lo))
                                      & (col("d_date") <= lit(hi))),
                 [("inv_date_sk", "d_date_sk")]))
    return (items.join(inv, [("i_item_sk", "inv_item_sk")])
            .join(t["store_sales"], [("i_item_sk", "ss_item_sk")], "leftsemi")
            .select("i_item_id", "i_item_desc", "i_current_price")
            .dropDuplicates()
            .sort("i_item_id").limit(100))


def q87(t):
    dd = (t["date_dim"].filter((col("d_month_seq") >= 1200)
                               & (col("d_month_seq") <= 1211))
          .select("d_date_sk", "d_date"))

    def bought(sales, cust_k, date_k, names=("c_last_name", "c_first_name",
                                             "d_date")):
        return (sales.join(dd, [(date_k, "d_date_sk")])
                .join(t["customer"], [(cust_k, "c_customer_sk")])
                .select(col("c_last_name").alias(names[0]),
                        col("c_first_name").alias(names[1]),
                        col("d_date").alias(names[2])).distinct())

    store = bought(t["store_sales"], "ss_customer_sk", "ss_sold_date_sk")
    catalog = bought(t["catalog_sales"], "cs_bill_customer_sk",
                     "cs_sold_date_sk", ("ln", "fn", "dt"))
    web = bought(t["web_sales"], "ws_bill_customer_sk", "ws_sold_date_sk",
                 ("ln", "fn", "dt"))
    keys = [("c_last_name", "ln"), ("c_first_name", "fn"), ("d_date", "dt")]
    return (store.join(catalog, keys, "leftanti")
            .join(web, keys, "leftanti")
            .agg(F.count().alias("cnt")))


def q97(t):
    dd = (t["date_dim"].filter((col("d_month_seq") >= 1200)
                               & (col("d_month_seq") <= 1211))
          .select("d_date_sk"))
    ssci = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")],
                                  "leftsemi")
            .select(col("ss_customer_sk").alias("s_cust"),
                    col("ss_item_sk").alias("s_item")).distinct())
    csci = (t["catalog_sales"].join(dd, [("cs_sold_date_sk", "d_date_sk")],
                                    "leftsemi")
            .select(col("cs_bill_customer_sk").alias("c_cust"),
                    col("cs_item_sk").alias("c_item")).distinct())
    j = ssci.join(csci, [("s_cust", "c_cust"), ("s_item", "c_item")], "full")
    return j.agg(
        F.sum(when(col("s_item").isNotNull() & col("c_item").isNull(), 1)
              .otherwise(0)).alias("store_only"),
        F.sum(when(col("s_item").isNull() & col("c_item").isNotNull(), 1)
              .otherwise(0)).alias("catalog_only"),
        F.sum(when(col("s_item").isNotNull() & col("c_item").isNotNull(), 1)
              .otherwise(0)).alias("store_and_catalog"))


QUERIES: Dict[str, object] = {
    name: fn for name, fn in list(globals().items())
    if name.startswith("q") and name[1:].isdigit() and callable(fn)}
