"""TPCx-BB ("BigBench") query suite over the DataFrame API.

Reference analog: TpcxbbLikeSpark.scala Q1Like..Q30Like
(integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala:785-2069). The reference
ships the 30 BigBench queries as raw SQL through Catalyst and marks 11 of them
unsupported (UDTF/UDF/python: q1-q4, q8, q10, q18, q19, q27, q29, q30); this
module carries the same 19 supported queries as their standard DataFrame
translations, with the same predicates, groupings and orderings.

Constant adaptations to the generator's 1998-2003 calendar and small-scale
dimensions are noted inline (the reference's constants assume vendor dsdgen
output); the query *shapes* are unchanged.
"""
from __future__ import annotations

import datetime
from typing import Dict

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.benchmarks.tpcxbb_data import date_sk

col, lit, when = F.col, F.lit, F.when


def q5(t):
    """Per-user click profile in category vs demographics (logistic-regression
    input vectors; TpcxbbLikeSpark.scala:809)."""
    clicks = (t["web_clickstreams"].filter(col("wcs_user_sk").isNotNull())
              .join(t["item"], [("wcs_item_sk", "i_item_sk")]))
    in_cat = lambda i: F.sum(  # noqa: E731
        when(col("i_category_id") == i, 1).otherwise(0)).alias(f"clicks_in_{i}")
    per_user = (clicks.groupBy("wcs_user_sk")
                .agg(F.sum(when(col("i_category") == "Books", 1).otherwise(0))
                     .alias("clicks_in_category"),
                     *[in_cat(i) for i in range(1, 8)]))
    return (per_user
            .join(t["customer"], [("wcs_user_sk", "c_customer_sk")])
            .join(t["customer_demographics"],
                  [("c_current_cdemo_sk", "cd_demo_sk")])
            .select("clicks_in_category",
                    when(col("cd_education_status").isin(
                        "Advanced Degree", "College", "4 yr Degree",
                        "2 yr Degree"), 1).otherwise(0)
                    .alias("college_education"),
                    when(col("cd_gender") == "M", 1).otherwise(0).alias("male"),
                    *[f"clicks_in_{i}" for i in range(1, 8)]))


def q6(t):
    """Customers shifting from store to web purchases
    (TpcxbbLikeSpark.scala:868)."""
    dd = t["date_dim"].filter((col("d_year") >= 2001) & (col("d_year") <= 2002))
    half = lambda p: (((col(f"{p}_ext_list_price")  # noqa: E731
                        - col(f"{p}_ext_wholesale_cost")
                        - col(f"{p}_ext_discount_amt"))
                       + col(f"{p}_ext_sales_price")) / 2)
    yr = lambda y, v: F.sum(when(col("d_year") == y, v).otherwise(0.0))  # noqa: E731
    store = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")])
             .groupBy(col("ss_customer_sk").alias("customer_sk"))
             .agg(yr(2001, half("ss")).alias("first_year_total"),
                  yr(2002, half("ss")).alias("second_year_total"))
             .filter(col("first_year_total") > 0))
    web = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")])
           .groupBy(col("ws_bill_customer_sk").alias("customer_sk"))
           .agg(yr(2001, half("ws")).alias("w_first_year_total"),
                yr(2002, half("ws")).alias("w_second_year_total"))
           .filter(col("w_first_year_total") > 0))
    ratio_w = col("w_second_year_total") / col("w_first_year_total")
    ratio_s = col("second_year_total") / col("first_year_total")
    return (store.join(web, [("customer_sk", "customer_sk")])
            .filter(ratio_w > ratio_s)
            .join(t["customer"], [("customer_sk", "c_customer_sk")])
            .select(ratio_w.alias("web_sales_increase_ratio"),
                    col("customer_sk").alias("c_customer_sk"),
                    "c_first_name", "c_last_name", "c_preferred_cust_flag",
                    "c_birth_country", "c_login", "c_email_address")
            .sort(col("web_sales_increase_ratio").desc(), "c_customer_sk",
                  "c_first_name", "c_last_name", "c_preferred_cust_flag",
                  "c_birth_country", "c_login")
            .limit(100))


def q7(t):
    """States with >=10 sales of items priced 20% above category average
    (TpcxbbLikeSpark.scala:949). Date window shifted to the generator
    calendar: 2001-07 (reference: 2004-07)."""
    avg_price = (t["item"].groupBy(col("i_category").alias("cat"))
                 .agg(F.avg("i_current_price").alias("cat_avg"))
                 .select("cat", (col("cat_avg") * 1.2).alias("avg_price")))
    high = (t["item"].join(avg_price, [("i_category", "cat")])
            .filter(col("i_current_price") > col("avg_price"))
            .select("i_item_sk"))
    dates = (t["date_dim"]
             .filter((col("d_year") == 2001) & (col("d_moy") == 7))
             .select("d_date_sk"))
    return (t["store_sales"]
            .join(high, [("ss_item_sk", "i_item_sk")], "leftsemi")
            .join(dates, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"].filter(col("ca_state").isNotNull()),
                  [("c_current_addr_sk", "ca_address_sk")])
            .groupBy("ca_state").agg(F.count().alias("cnt"))
            .filter(col("cnt") >= 10)
            .sort(col("cnt").desc(), "ca_state")
            .limit(10))


def q9(t):
    """Total quantity over demographic/price and state/profit band unions
    (TpcxbbLikeSpark.scala:1021). State triplets drawn from the generator's
    state pool (reference: KY/GA/NM, MT/OR/IN, WI/MO/WV)."""
    price_ok = (((col("cd_marital_status") == "M")
                 & (col("cd_education_status") == "4 yr Degree")
                 & (col("ss_sales_price") >= 100)
                 & (col("ss_sales_price") <= 150))
                | ((col("cd_marital_status") == "M")
                   & (col("cd_education_status") == "4 yr Degree")
                   & (col("ss_sales_price") >= 50)
                   & (col("ss_sales_price") <= 200))
                | ((col("cd_marital_status") == "M")
                   & (col("cd_education_status") == "4 yr Degree")
                   & (col("ss_sales_price") >= 150)
                   & (col("ss_sales_price") <= 200)))
    geo_ok = (((col("ca_country") == "United States")
               & col("ca_state").isin("GA", "TN", "SD")
               & (col("ss_net_profit") >= 0) & (col("ss_net_profit") <= 2000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("IN", "LA", "MI")
                 & (col("ss_net_profit") >= 150)
                 & (col("ss_net_profit") <= 3000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("SC", "OH", "TX")
                 & (col("ss_net_profit") >= 50)
                 & (col("ss_net_profit") <= 25000)))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2001),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["customer_demographics"], [("ss_cdemo_sk", "cd_demo_sk")])
            .filter(price_ok & geo_ok)
            .agg(F.sum("ss_quantity").alias("sum_quantity")))


def q11(t):
    """Correlation of review stats with monthly revenue
    (TpcxbbLikeSpark.scala:1103)."""
    lo, hi = datetime.date(2003, 1, 2), datetime.date(2003, 2, 2)
    reviews = (t["product_reviews"].filter(col("pr_item_sk").isNotNull())
               .groupBy(col("pr_item_sk").alias("pid"))
               .agg(F.count().alias("reviews_count"),
                    F.avg("pr_review_rating").alias("avg_rating")))
    dates = (t["date_dim"]
             .filter((col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
             .select("d_date_sk"))
    revenue = (t["web_sales"].filter(col("ws_item_sk").isNotNull())
               .join(dates, [("ws_sold_date_sk", "d_date_sk")], "leftsemi")
               .groupBy("ws_item_sk")
               .agg(F.sum("ws_net_paid").alias("revenue")))
    return (reviews.join(revenue, [("pid", "ws_item_sk")])
            .agg(F.corr("reviews_count", "avg_rating").alias("corr")))


def q12(t):
    """Customers who viewed a category online then bought in-store within 90
    days (TpcxbbLikeSpark.scala:1161). Click window start shifted into the
    generator calendar (reference: date_sk 37134)."""
    w0 = date_sk(datetime.date(2001, 10, 1))
    views = (t["web_clickstreams"]
             .filter((col("wcs_click_date_sk") >= w0)
                     & (col("wcs_click_date_sk") <= w0 + 30)
                     & col("wcs_user_sk").isNotNull()
                     & col("wcs_sales_sk").isNull())
             .join(t["item"].filter(col("i_category").isin("Books",
                                                           "Electronics")),
                   [("wcs_item_sk", "i_item_sk")])
             .select("wcs_user_sk", "wcs_click_date_sk"))
    buys = (t["store_sales"]
            .filter((col("ss_sold_date_sk") >= w0)
                    & (col("ss_sold_date_sk") <= w0 + 90)
                    & col("ss_customer_sk").isNotNull())
            .join(t["item"].filter(col("i_category").isin("Books",
                                                          "Electronics")),
                  [("ss_item_sk", "i_item_sk")])
            .select("ss_customer_sk", "ss_sold_date_sk"))
    return (views.join(buys, [("wcs_user_sk", "ss_customer_sk")])
            .filter(col("wcs_click_date_sk") < col("ss_sold_date_sk"))
            .select("wcs_user_sk").distinct()
            .sort("wcs_user_sk"))


def q13(t):
    """Customers whose web-sales growth beats their store-sales growth
    (TpcxbbLikeSpark.scala:1203)."""
    dd = (t["date_dim"].filter(col("d_year").isin(2001, 2002))
          .select("d_date_sk", "d_year"))
    yr = lambda y, c: F.sum(when(col("d_year") == y,  # noqa: E731
                                 col(c)).otherwise(0.0))
    store = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")])
             .groupBy(col("ss_customer_sk").alias("customer_sk"))
             .agg(yr(2001, "ss_net_paid").alias("first_year_total"),
                  yr(2002, "ss_net_paid").alias("second_year_total"))
             .filter(col("first_year_total") > 0))
    web = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")])
           .groupBy(col("ws_bill_customer_sk").alias("customer_sk"))
           .agg(yr(2001, "ws_net_paid").alias("w_first_year_total"),
                yr(2002, "ws_net_paid").alias("w_second_year_total"))
           .filter(col("w_first_year_total") > 0))
    ratio_w = col("w_second_year_total") / col("w_first_year_total")
    ratio_s = col("second_year_total") / col("first_year_total")
    return (store.join(web, [("customer_sk", "customer_sk")])
            .filter(ratio_w > ratio_s)
            .join(t["customer"], [("customer_sk", "c_customer_sk")])
            .select(col("customer_sk").alias("c_customer_sk"),
                    "c_first_name", "c_last_name",
                    ratio_s.alias("storeSalesIncreaseRatio"),
                    ratio_w.alias("webSalesIncreaseRatio"))
            .sort(col("webSalesIncreaseRatio").desc(), "c_customer_sk",
                  "c_first_name", "c_last_name")
            .limit(100))


def q14(t):
    """Morning-to-evening web sales ratio for high-content pages
    (TpcxbbLikeSpark.scala:1284)."""
    base = (t["web_sales"]
            .join(t["household_demographics"].filter(col("hd_dep_count") == 5),
                  [("ws_ship_hdemo_sk", "hd_demo_sk")])
            .join(t["web_page"].filter((col("wp_char_count") >= 5000)
                                       & (col("wp_char_count") <= 6000)),
                  [("ws_web_page_sk", "wp_web_page_sk")])
            .join(t["time_dim"].filter(col("t_hour").isin(7, 8, 19, 20)),
                  [("ws_sold_time_sk", "t_time_sk")])
            .groupBy("t_hour").agg(F.count().alias("cnt")))
    am = (col("t_hour") >= 7) & (col("t_hour") <= 8)
    pm = (col("t_hour") >= 19) & (col("t_hour") <= 20)
    return (base.agg(
        F.sum(when(am, col("cnt")).otherwise(0)).alias("amc"),
        F.sum(when(pm, col("cnt")).otherwise(0)).alias("pmc"))
        .select(when(col("pmc") > 0, col("amc") / col("pmc"))
                .otherwise(-1.00).alias("am_pm_ratio")))


def q15(t):
    """Categories with flat/declining store sales via least-squares slope
    (TpcxbbLikeSpark.scala:1313). Store 3 (reference: store 10; the generator
    floors at 6 stores)."""
    lo, hi = datetime.date(2001, 9, 2), datetime.date(2002, 9, 2)
    dates = (t["date_dim"]
             .filter((col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
             .select("d_date_sk"))
    daily = (t["store_sales"].filter(col("ss_store_sk") == 3)
             .join(dates, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
             .join(t["item"].filter(col("i_category_id").isNotNull()),
                   [("ss_item_sk", "i_item_sk")])
             .groupBy(col("i_category_id").alias("cat"),
                      col("ss_sold_date_sk").alias("x"))
             .agg(F.sum("ss_net_paid").alias("y")))
    per_cat = (daily
               .select("cat", "x", "y", (col("x") * col("y")).alias("xy"),
                       (col("x") * col("x")).alias("xx"))
               .groupBy("cat")
               .agg(F.count("x").alias("n"), F.sum("x").alias("sx"),
                    F.sum("y").alias("sy"), F.sum("xy").alias("sxy"),
                    F.sum("xx").alias("sxx")))
    slope = ((col("n") * col("sxy") - col("sx") * col("sy"))
             / (col("n") * col("sxx") - col("sx") * col("sx")))
    intercept = (col("sy") - slope * col("sx")) / col("n")
    return (per_cat.select("cat", slope.alias("slope"),
                           intercept.alias("intercept"))
            .filter(col("slope") <= 0.0)
            .sort("cat"))


def q16(t):
    """Web sales net of refunds around a price-change date
    (TpcxbbLikeSpark.scala:1377)."""
    pivot = datetime.date(2001, 3, 16)
    lo, hi = (pivot - datetime.timedelta(days=30),
              pivot + datetime.timedelta(days=30))
    sales = (t["web_sales"]
             .join(t["web_returns"],
                   [("ws_order_number", "wr_order_number"),
                    ("ws_item_sk", "wr_item_sk")], "left")
             .join(t["item"], [("ws_item_sk", "i_item_sk")])
             .join(t["warehouse"], [("ws_warehouse_sk", "w_warehouse_sk")])
             .join(t["date_dim"]
                   .filter((col("d_date") >= lit(lo))
                           & (col("d_date") <= lit(hi))),
                   [("ws_sold_date_sk", "d_date_sk")]))
    net = col("ws_sales_price") - F.coalesce(col("wr_refunded_cash"),
                                             lit(0.0))
    return (sales.groupBy("w_state", "i_item_id")
            .agg(F.sum(when(col("d_date") < lit(pivot), net).otherwise(0.0))
                 .alias("sales_before"),
                 F.sum(when(col("d_date") >= lit(pivot), net).otherwise(0.0))
                 .alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q17(t):
    """Promotional vs total sales ratio (TpcxbbLikeSpark.scala:1419)."""
    in_tz_cust = (t["customer"]
                  .join(t["customer_address"]
                        .filter(col("ca_gmt_offset") == -5.0),
                        [("c_current_addr_sk", "ca_address_sk")], "leftsemi")
                  .select("c_customer_sk"))
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_year") == 2001)
                                       & (col("d_moy") == 12)),
                  [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
            .join(t["item"].filter(col("i_category").isin("Books", "Music")),
                  [("ss_item_sk", "i_item_sk")], "leftsemi")
            .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                  [("ss_store_sk", "s_store_sk")], "leftsemi")
            .join(in_tz_cust, [("ss_customer_sk", "c_customer_sk")],
                  "leftsemi")
            .join(t["promotion"], [("ss_promo_sk", "p_promo_sk")]))
    promo_on = ((col("p_channel_dmail") == "Y") | (col("p_channel_email") == "Y")
                | (col("p_channel_tv") == "Y"))
    per_channel = (base.groupBy("p_channel_email", "p_channel_dmail",
                                "p_channel_tv")
                   .agg(F.sum("ss_ext_sales_price").alias("total"))
                   .select(when(promo_on, col("total")).otherwise(0.0)
                           .alias("promotional"), "total"))
    return (per_channel.agg(F.sum("promotional").alias("promotional"),
                            F.sum("total").alias("total"))
            .select("promotional", "total",
                    when(col("total") > 0,
                         100.0 * col("promotional") / col("total"))
                    .otherwise(0.0).alias("promo_percent")))


def q20(t):
    """Customer return-behavior segmentation vectors
    (TpcxbbLikeSpark.scala:1480)."""
    orders = (t["store_sales"]
              .groupBy("ss_customer_sk")
              .agg(F.countDistinct("ss_ticket_number").alias("orders_count"),
                   F.count("ss_item_sk").alias("orders_items"),
                   F.sum("ss_net_paid").alias("orders_money")))
    returns = (t["store_returns"]
               .groupBy("sr_customer_sk")
               .agg(F.countDistinct("sr_ticket_number").alias("returns_count"),
                    F.count("sr_item_sk").alias("returns_items"),
                    F.sum("sr_return_amt").alias("returns_money")))
    ratio = lambda a, b: F.round(  # noqa: E731
        when(col(a).isNull() | col(b).isNull() | (col(a) / col(b)).isNull(),
             0.0).otherwise(col(a) / col(b)), 7)
    return (orders.join(returns, [("ss_customer_sk", "sr_customer_sk")],
                        "left")
            .select(col("ss_customer_sk").alias("user_sk"),
                    ratio("returns_count", "orders_count").alias("orderRatio"),
                    ratio("returns_items", "orders_items").alias("itemsRatio"),
                    ratio("returns_money", "orders_money")
                    .alias("monetaryRatio"),
                    F.round(F.coalesce(col("returns_count"), lit(0)), 0)
                    .alias("frequency"))
            .sort("user_sk"))


def q21(t):
    """Items sold, returned within 6 months, re-bought on the web
    (TpcxbbLikeSpark.scala:1542)."""
    part_sr = (t["store_returns"]
               .join(t["date_dim"].filter((col("d_year") == 2003)
                                          & (col("d_moy") >= 1)
                                          & (col("d_moy") <= 7)),
                     [("sr_returned_date_sk", "d_date_sk")])
               .select("sr_item_sk", "sr_customer_sk", "sr_ticket_number",
                       "sr_return_quantity"))
    part_ws = (t["web_sales"]
               .join(t["date_dim"].filter((col("d_year") >= 2003)
                                          & (col("d_year") <= 2005)),
                     [("ws_sold_date_sk", "d_date_sk")])
               .select("ws_item_sk", "ws_bill_customer_sk", "ws_quantity"))
    part_ss = (t["store_sales"]
               .join(t["date_dim"].filter((col("d_year") == 2003)
                                          & (col("d_moy") == 1)),
                     [("ss_sold_date_sk", "d_date_sk")])
               .select("ss_item_sk", "ss_store_sk", "ss_customer_sk",
                       "ss_ticket_number", "ss_quantity"))
    return (part_sr
            .join(part_ws, [("sr_item_sk", "ws_item_sk"),
                            ("sr_customer_sk", "ws_bill_customer_sk")])
            .join(part_ss, [("sr_ticket_number", "ss_ticket_number"),
                            ("sr_item_sk", "ss_item_sk"),
                            ("sr_customer_sk", "ss_customer_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["item"], [("sr_item_sk", "i_item_sk")])
            .groupBy("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .agg(F.sum("ss_quantity").alias("store_sales_quantity"),
                 F.sum("sr_return_quantity").alias("store_returns_quantity"),
                 F.sum("ws_quantity").alias("web_sales_quantity"))
            .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .limit(100))


def q22(t):
    """Inventory change around a price-change date by warehouse
    (TpcxbbLikeSpark.scala:1630)."""
    pivot = lit(datetime.date(2001, 5, 8))
    dd = F.datediff(col("d_date"), pivot)
    base = (t["inventory"]
            .join(t["item"].filter((col("i_current_price") >= 0.98)
                                   & (col("i_current_price") <= 1.5)),
                  [("inv_item_sk", "i_item_sk")])
            .join(t["warehouse"], [("inv_warehouse_sk", "w_warehouse_sk")])
            .join(t["date_dim"], [("inv_date_sk", "d_date_sk")])
            .filter((dd >= -30) & (dd <= 30)))
    agg = (base.groupBy("w_warehouse_name", "i_item_id")
           .agg(F.sum(when(dd < 0, col("inv_quantity_on_hand")).otherwise(0))
                .alias("inv_before"),
                F.sum(when(dd >= 0, col("inv_quantity_on_hand")).otherwise(0))
                .alias("inv_after")))
    ratio = col("inv_after") / col("inv_before")
    return (agg.filter((col("inv_before") > 0)
                       & (ratio >= 2.0 / 3.0) & (ratio <= 3.0 / 2.0))
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def q23(t):
    """Items with high inventory coefficient-of-variation in consecutive
    months (TpcxbbLikeSpark.scala:1685)."""
    monthly = (t["inventory"]
               .join(t["date_dim"].filter((col("d_year") == 2001)
                                          & (col("d_moy") >= 1)
                                          & (col("d_moy") <= 2)),
                     [("inv_date_sk", "d_date_sk")])
               .groupBy("inv_warehouse_sk", "inv_item_sk", "d_moy")
               .agg(F.stddev("inv_quantity_on_hand").alias("stdev"),
                    F.avg("inv_quantity_on_hand").alias("mean")))
    cov_tab = (monthly.filter((col("mean") > 0)
                              & (col("stdev") / col("mean") >= 1.3))
               .select("inv_warehouse_sk", "inv_item_sk", "d_moy",
                       (col("stdev") / col("mean")).alias("cov")))
    inv1 = (cov_tab.filter(col("d_moy") == 1)
            .select(col("inv_warehouse_sk").alias("w1"),
                    col("inv_item_sk").alias("i1"),
                    col("d_moy").alias("d_moy"), col("cov").alias("cov")))
    inv2 = (cov_tab.filter(col("d_moy") == 2)
            .select(col("inv_warehouse_sk").alias("w2"),
                    col("inv_item_sk").alias("i2"),
                    col("d_moy").alias("d_moy2"), col("cov").alias("cov2")))
    return (inv1.join(inv2, [("w1", "w2"), ("i1", "i2")])
            .select(col("w1").alias("inv_warehouse_sk"),
                    col("i1").alias("inv_item_sk"), "d_moy", "cov",
                    "d_moy2", "cov2")
            .sort("inv_warehouse_sk", "inv_item_sk"))


def q24(t):
    """Cross-price elasticity of demand for one item
    (TpcxbbLikeSpark.scala:1761). Item 10 (reference: item 10000; the
    generator floors at 100 items)."""
    comp = (t["item"].filter(col("i_item_sk") == 10)
            .join(t["item_marketprices"], [("i_item_sk", "imp_item_sk")])
            .select("i_item_sk", "imp_sk",
                    ((col("imp_competitor_price") - col("i_current_price"))
                     / col("i_current_price")).alias("price_change"),
                    "imp_start_date",
                    (col("imp_end_date") - col("imp_start_date"))
                    .alias("no_days_comp_price")))
    during = lambda d: ((col(d) >= col("imp_start_date"))  # noqa: E731
                        & (col(d) < col("imp_start_date")
                           + col("no_days_comp_price")))
    before = lambda d: ((col(d) >= col("imp_start_date")  # noqa: E731
                         - col("no_days_comp_price"))
                        & (col(d) < col("imp_start_date")))
    ws = (t["web_sales"].join(comp, [("ws_item_sk", "i_item_sk")])
          .groupBy("ws_item_sk", "imp_sk", "price_change")
          .agg(F.sum(when(during("ws_sold_date_sk"), col("ws_quantity"))
                     .otherwise(0)).alias("current_ws_quant"),
               F.sum(when(before("ws_sold_date_sk"), col("ws_quantity"))
                     .otherwise(0)).alias("prev_ws_quant")))
    ss = (t["store_sales"].join(comp, [("ss_item_sk", "i_item_sk")])
          .groupBy("ss_item_sk", "imp_sk", "price_change")
          .agg(F.sum(when(during("ss_sold_date_sk"), col("ss_quantity"))
                     .otherwise(0)).alias("current_ss_quant"),
               F.sum(when(before("ss_sold_date_sk"), col("ss_quantity"))
                     .otherwise(0)).alias("prev_ss_quant")))
    elasticity = ((col("current_ss_quant") + col("current_ws_quant")
                   - col("prev_ss_quant") - col("prev_ws_quant"))
                  / ((col("prev_ss_quant") + col("prev_ws_quant"))
                     * col("price_change")))
    return (ws.join(ss, [("ws_item_sk", "ss_item_sk"), ("imp_sk", "imp_sk")])
            .groupBy("ws_item_sk")
            .agg(F.avg(elasticity).alias("cross_price_elasticity")))


def q25(t):
    """RFM segmentation inputs over both channels
    (TpcxbbLikeSpark.scala:1861). Recency pivot = date_sk(2003-01-02)
    (reference constant 37621 encodes the same date in dsdgen's epoch)."""
    cutoff = lit(datetime.date(2002, 1, 2))
    store = (t["store_sales"]
             .join(t["date_dim"].filter(col("d_date") > cutoff),
                   [("ss_sold_date_sk", "d_date_sk")])
             .filter(col("ss_customer_sk").isNotNull())
             .groupBy(col("ss_customer_sk").alias("cid"))
             .agg(F.countDistinct("ss_ticket_number").alias("frequency"),
                  F.max("ss_sold_date_sk").alias("most_recent_date"),
                  F.sum("ss_net_paid").alias("amount")))
    web = (t["web_sales"]
           .join(t["date_dim"].filter(col("d_date") > cutoff),
                 [("ws_sold_date_sk", "d_date_sk")])
           .filter(col("ws_bill_customer_sk").isNotNull())
           .groupBy(col("ws_bill_customer_sk").alias("cid"))
           .agg(F.countDistinct("ws_order_number").alias("frequency"),
                F.max("ws_sold_date_sk").alias("most_recent_date"),
                F.sum("ws_net_paid").alias("amount")))
    pivot = date_sk(datetime.date(2003, 1, 2))
    return (store.union(web)
            .groupBy("cid")
            .agg(F.max("most_recent_date").alias("mrd"),
                 F.sum("frequency").alias("frequency"),
                 F.sum("amount").alias("totalspend"))
            .select("cid",
                    when(lit(pivot) - col("mrd") < 60, 1.0).otherwise(0.0)
                    .alias("recency"),
                    "frequency", "totalspend")
            .sort("cid"))


def q26(t):
    """Book-buyer clustering vectors: per-customer counts by item class
    (TpcxbbLikeSpark.scala:1945)."""
    idc = lambda i: F.count(  # noqa: E731
        when(col("i_class_id") == i, 1).otherwise(None)).alias(f"id{i}")
    return (t["store_sales"].filter(col("ss_customer_sk").isNotNull())
            .join(t["item"].filter(col("i_category") == "Books"),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy(col("ss_customer_sk").alias("cid"))
            .agg(F.count("ss_item_sk").alias("item_count"),
                 *[idc(i) for i in range(1, 16)])
            .filter(col("item_count") > 5)
            .drop("item_count")
            .sort("cid"))


def q28(t):
    """Sentiment-classifier train/test split of product reviews
    (TpcxbbLikeSpark.scala:2004): 90% train (pmod(sk,10) in 1..9), 10% test."""
    return (t["product_reviews"]
            .select("pr_review_sk", col("pr_review_rating").alias("pr_rating"),
                    "pr_review_content")
            .withColumn("part", when(F.pmod(col("pr_review_sk"), 10) == 0,
                                     "test").otherwise("train"))
            .sort("pr_review_sk"))


QUERIES: Dict[str, object] = {
    name: fn for name, fn in list(globals().items())
    if name.startswith("q") and name[1:].isdigit() and callable(fn)}

#: queries the reference marks unsupported (UDTF/UDF/python)
UNSUPPORTED = ("q1", "q2", "q3", "q4", "q8", "q10", "q18", "q19", "q27",
               "q29", "q30")
