"""TPCx-BB ("BigBench") query suite over the DataFrame API.

Reference analog: TpcxbbLikeSpark.scala Q1Like..Q30Like
(integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala:785-2069). The reference
implements 19 of the 30 BigBench queries and REJECTS the other 11
(UnsupportedOperationException for UDTF/UDF/python: q1-q4, q8, q10, q18,
q19, q27, q29, q30). This module runs ALL 30: the reference's 19 as their
standard DataFrame translations with the same predicates, groupings and
orderings, and the 11 rejected ones re-expressed with engine primitives —
sessionization as a lag-gap cumulative-sum window, path analysis as lag
projections, sentiment/NER as word-list matching over split sentences.

Constant adaptations to the generator's 1998-2003 calendar and small-scale
dimensions are noted inline (the reference's constants assume vendor dsdgen
output); the query *shapes* are unchanged.
"""
from __future__ import annotations

import datetime
from typing import Dict

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.benchmarks.tpcxbb_data import date_sk

col, lit, when = F.col, F.lit, F.when


def q5(t):
    """Per-user click profile in category vs demographics (logistic-regression
    input vectors; TpcxbbLikeSpark.scala:809)."""
    clicks = (t["web_clickstreams"].filter(col("wcs_user_sk").isNotNull())
              .join(t["item"], [("wcs_item_sk", "i_item_sk")]))
    in_cat = lambda i: F.sum(  # noqa: E731
        when(col("i_category_id") == i, 1).otherwise(0)).alias(f"clicks_in_{i}")
    per_user = (clicks.groupBy("wcs_user_sk")
                .agg(F.sum(when(col("i_category") == "Books", 1).otherwise(0))
                     .alias("clicks_in_category"),
                     *[in_cat(i) for i in range(1, 8)]))
    return (per_user
            .join(t["customer"], [("wcs_user_sk", "c_customer_sk")])
            .join(t["customer_demographics"],
                  [("c_current_cdemo_sk", "cd_demo_sk")])
            .select("clicks_in_category",
                    when(col("cd_education_status").isin(
                        "Advanced Degree", "College", "4 yr Degree",
                        "2 yr Degree"), 1).otherwise(0)
                    .alias("college_education"),
                    when(col("cd_gender") == "M", 1).otherwise(0).alias("male"),
                    *[f"clicks_in_{i}" for i in range(1, 8)]))


def q6(t):
    """Customers shifting from store to web purchases
    (TpcxbbLikeSpark.scala:868)."""
    dd = t["date_dim"].filter((col("d_year") >= 2001) & (col("d_year") <= 2002))
    half = lambda p: (((col(f"{p}_ext_list_price")  # noqa: E731
                        - col(f"{p}_ext_wholesale_cost")
                        - col(f"{p}_ext_discount_amt"))
                       + col(f"{p}_ext_sales_price")) / 2)
    yr = lambda y, v: F.sum(when(col("d_year") == y, v).otherwise(0.0))  # noqa: E731
    store = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")])
             .groupBy(col("ss_customer_sk").alias("customer_sk"))
             .agg(yr(2001, half("ss")).alias("first_year_total"),
                  yr(2002, half("ss")).alias("second_year_total"))
             .filter(col("first_year_total") > 0))
    web = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")])
           .groupBy(col("ws_bill_customer_sk").alias("customer_sk"))
           .agg(yr(2001, half("ws")).alias("w_first_year_total"),
                yr(2002, half("ws")).alias("w_second_year_total"))
           .filter(col("w_first_year_total") > 0))
    ratio_w = col("w_second_year_total") / col("w_first_year_total")
    ratio_s = col("second_year_total") / col("first_year_total")
    return (store.join(web, [("customer_sk", "customer_sk")])
            .filter(ratio_w > ratio_s)
            .join(t["customer"], [("customer_sk", "c_customer_sk")])
            .select(ratio_w.alias("web_sales_increase_ratio"),
                    col("customer_sk").alias("c_customer_sk"),
                    "c_first_name", "c_last_name", "c_preferred_cust_flag",
                    "c_birth_country", "c_login", "c_email_address")
            .sort(col("web_sales_increase_ratio").desc(), "c_customer_sk",
                  "c_first_name", "c_last_name", "c_preferred_cust_flag",
                  "c_birth_country", "c_login")
            .limit(100))


def q7(t):
    """States with >=10 sales of items priced 20% above category average
    (TpcxbbLikeSpark.scala:949). Date window shifted to the generator
    calendar: 2001-07 (reference: 2004-07)."""
    avg_price = (t["item"].groupBy(col("i_category").alias("cat"))
                 .agg(F.avg("i_current_price").alias("cat_avg"))
                 .select("cat", (col("cat_avg") * 1.2).alias("avg_price")))
    high = (t["item"].join(avg_price, [("i_category", "cat")])
            .filter(col("i_current_price") > col("avg_price"))
            .select("i_item_sk"))
    dates = (t["date_dim"]
             .filter((col("d_year") == 2001) & (col("d_moy") == 7))
             .select("d_date_sk"))
    return (t["store_sales"]
            .join(high, [("ss_item_sk", "i_item_sk")], "leftsemi")
            .join(dates, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
            .join(t["customer"], [("ss_customer_sk", "c_customer_sk")])
            .join(t["customer_address"].filter(col("ca_state").isNotNull()),
                  [("c_current_addr_sk", "ca_address_sk")])
            .groupBy("ca_state").agg(F.count().alias("cnt"))
            .filter(col("cnt") >= 10)
            .sort(col("cnt").desc(), "ca_state")
            .limit(10))


def q9(t):
    """Total quantity over demographic/price and state/profit band unions
    (TpcxbbLikeSpark.scala:1021). State triplets drawn from the generator's
    state pool (reference: KY/GA/NM, MT/OR/IN, WI/MO/WV)."""
    price_ok = (((col("cd_marital_status") == "M")
                 & (col("cd_education_status") == "4 yr Degree")
                 & (col("ss_sales_price") >= 100)
                 & (col("ss_sales_price") <= 150))
                | ((col("cd_marital_status") == "M")
                   & (col("cd_education_status") == "4 yr Degree")
                   & (col("ss_sales_price") >= 50)
                   & (col("ss_sales_price") <= 200))
                | ((col("cd_marital_status") == "M")
                   & (col("cd_education_status") == "4 yr Degree")
                   & (col("ss_sales_price") >= 150)
                   & (col("ss_sales_price") <= 200)))
    geo_ok = (((col("ca_country") == "United States")
               & col("ca_state").isin("GA", "TN", "SD")
               & (col("ss_net_profit") >= 0) & (col("ss_net_profit") <= 2000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("IN", "LA", "MI")
                 & (col("ss_net_profit") >= 150)
                 & (col("ss_net_profit") <= 3000))
              | ((col("ca_country") == "United States")
                 & col("ca_state").isin("SC", "OH", "TX")
                 & (col("ss_net_profit") >= 50)
                 & (col("ss_net_profit") <= 25000)))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == 2001),
                  [("ss_sold_date_sk", "d_date_sk")])
            .join(t["customer_address"], [("ss_addr_sk", "ca_address_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["customer_demographics"], [("ss_cdemo_sk", "cd_demo_sk")])
            .filter(price_ok & geo_ok)
            .agg(F.sum("ss_quantity").alias("sum_quantity")))


def q11(t):
    """Correlation of review stats with monthly revenue
    (TpcxbbLikeSpark.scala:1103)."""
    lo, hi = datetime.date(2003, 1, 2), datetime.date(2003, 2, 2)
    reviews = (t["product_reviews"].filter(col("pr_item_sk").isNotNull())
               .groupBy(col("pr_item_sk").alias("pid"))
               .agg(F.count().alias("reviews_count"),
                    F.avg("pr_review_rating").alias("avg_rating")))
    dates = (t["date_dim"]
             .filter((col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
             .select("d_date_sk"))
    revenue = (t["web_sales"].filter(col("ws_item_sk").isNotNull())
               .join(dates, [("ws_sold_date_sk", "d_date_sk")], "leftsemi")
               .groupBy("ws_item_sk")
               .agg(F.sum("ws_net_paid").alias("revenue")))
    return (reviews.join(revenue, [("pid", "ws_item_sk")])
            .agg(F.corr("reviews_count", "avg_rating").alias("corr")))


def q12(t):
    """Customers who viewed a category online then bought in-store within 90
    days (TpcxbbLikeSpark.scala:1161). Click window start shifted into the
    generator calendar (reference: date_sk 37134)."""
    w0 = date_sk(datetime.date(2001, 10, 1))
    views = (t["web_clickstreams"]
             .filter((col("wcs_click_date_sk") >= w0)
                     & (col("wcs_click_date_sk") <= w0 + 30)
                     & col("wcs_user_sk").isNotNull()
                     & col("wcs_sales_sk").isNull())
             .join(t["item"].filter(col("i_category").isin("Books",
                                                           "Electronics")),
                   [("wcs_item_sk", "i_item_sk")])
             .select("wcs_user_sk", "wcs_click_date_sk"))
    buys = (t["store_sales"]
            .filter((col("ss_sold_date_sk") >= w0)
                    & (col("ss_sold_date_sk") <= w0 + 90)
                    & col("ss_customer_sk").isNotNull())
            .join(t["item"].filter(col("i_category").isin("Books",
                                                          "Electronics")),
                  [("ss_item_sk", "i_item_sk")])
            .select("ss_customer_sk", "ss_sold_date_sk"))
    return (views.join(buys, [("wcs_user_sk", "ss_customer_sk")])
            .filter(col("wcs_click_date_sk") < col("ss_sold_date_sk"))
            .select("wcs_user_sk").distinct()
            .sort("wcs_user_sk"))


def q13(t):
    """Customers whose web-sales growth beats their store-sales growth
    (TpcxbbLikeSpark.scala:1203)."""
    dd = (t["date_dim"].filter(col("d_year").isin(2001, 2002))
          .select("d_date_sk", "d_year"))
    yr = lambda y, c: F.sum(when(col("d_year") == y,  # noqa: E731
                                 col(c)).otherwise(0.0))
    store = (t["store_sales"].join(dd, [("ss_sold_date_sk", "d_date_sk")])
             .groupBy(col("ss_customer_sk").alias("customer_sk"))
             .agg(yr(2001, "ss_net_paid").alias("first_year_total"),
                  yr(2002, "ss_net_paid").alias("second_year_total"))
             .filter(col("first_year_total") > 0))
    web = (t["web_sales"].join(dd, [("ws_sold_date_sk", "d_date_sk")])
           .groupBy(col("ws_bill_customer_sk").alias("customer_sk"))
           .agg(yr(2001, "ws_net_paid").alias("w_first_year_total"),
                yr(2002, "ws_net_paid").alias("w_second_year_total"))
           .filter(col("w_first_year_total") > 0))
    ratio_w = col("w_second_year_total") / col("w_first_year_total")
    ratio_s = col("second_year_total") / col("first_year_total")
    return (store.join(web, [("customer_sk", "customer_sk")])
            .filter(ratio_w > ratio_s)
            .join(t["customer"], [("customer_sk", "c_customer_sk")])
            .select(col("customer_sk").alias("c_customer_sk"),
                    "c_first_name", "c_last_name",
                    ratio_s.alias("storeSalesIncreaseRatio"),
                    ratio_w.alias("webSalesIncreaseRatio"))
            .sort(col("webSalesIncreaseRatio").desc(), "c_customer_sk",
                  "c_first_name", "c_last_name")
            .limit(100))


def q14(t):
    """Morning-to-evening web sales ratio for high-content pages
    (TpcxbbLikeSpark.scala:1284)."""
    base = (t["web_sales"]
            .join(t["household_demographics"].filter(col("hd_dep_count") == 5),
                  [("ws_ship_hdemo_sk", "hd_demo_sk")])
            .join(t["web_page"].filter((col("wp_char_count") >= 5000)
                                       & (col("wp_char_count") <= 6000)),
                  [("ws_web_page_sk", "wp_web_page_sk")])
            .join(t["time_dim"].filter(col("t_hour").isin(7, 8, 19, 20)),
                  [("ws_sold_time_sk", "t_time_sk")])
            .groupBy("t_hour").agg(F.count().alias("cnt")))
    am = (col("t_hour") >= 7) & (col("t_hour") <= 8)
    pm = (col("t_hour") >= 19) & (col("t_hour") <= 20)
    return (base.agg(
        F.sum(when(am, col("cnt")).otherwise(0)).alias("amc"),
        F.sum(when(pm, col("cnt")).otherwise(0)).alias("pmc"))
        .select(when(col("pmc") > 0, col("amc") / col("pmc"))
                .otherwise(-1.00).alias("am_pm_ratio")))


def q15(t):
    """Categories with flat/declining store sales via least-squares slope
    (TpcxbbLikeSpark.scala:1313). Store 3 (reference: store 10; the generator
    floors at 6 stores)."""
    lo, hi = datetime.date(2001, 9, 2), datetime.date(2002, 9, 2)
    dates = (t["date_dim"]
             .filter((col("d_date") >= lit(lo)) & (col("d_date") <= lit(hi)))
             .select("d_date_sk"))
    daily = (t["store_sales"].filter(col("ss_store_sk") == 3)
             .join(dates, [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
             .join(t["item"].filter(col("i_category_id").isNotNull()),
                   [("ss_item_sk", "i_item_sk")])
             .groupBy(col("i_category_id").alias("cat"),
                      col("ss_sold_date_sk").alias("x"))
             .agg(F.sum("ss_net_paid").alias("y")))
    per_cat = (daily
               .select("cat", "x", "y", (col("x") * col("y")).alias("xy"),
                       (col("x") * col("x")).alias("xx"))
               .groupBy("cat")
               .agg(F.count("x").alias("n"), F.sum("x").alias("sx"),
                    F.sum("y").alias("sy"), F.sum("xy").alias("sxy"),
                    F.sum("xx").alias("sxx")))
    slope = ((col("n") * col("sxy") - col("sx") * col("sy"))
             / (col("n") * col("sxx") - col("sx") * col("sx")))
    intercept = (col("sy") - slope * col("sx")) / col("n")
    return (per_cat.select("cat", slope.alias("slope"),
                           intercept.alias("intercept"))
            .filter(col("slope") <= 0.0)
            .sort("cat"))


def q16(t):
    """Web sales net of refunds around a price-change date
    (TpcxbbLikeSpark.scala:1377)."""
    pivot = datetime.date(2001, 3, 16)
    lo, hi = (pivot - datetime.timedelta(days=30),
              pivot + datetime.timedelta(days=30))
    sales = (t["web_sales"]
             .join(t["web_returns"],
                   [("ws_order_number", "wr_order_number"),
                    ("ws_item_sk", "wr_item_sk")], "left")
             .join(t["item"], [("ws_item_sk", "i_item_sk")])
             .join(t["warehouse"], [("ws_warehouse_sk", "w_warehouse_sk")])
             .join(t["date_dim"]
                   .filter((col("d_date") >= lit(lo))
                           & (col("d_date") <= lit(hi))),
                   [("ws_sold_date_sk", "d_date_sk")]))
    net = col("ws_sales_price") - F.coalesce(col("wr_refunded_cash"),
                                             lit(0.0))
    return (sales.groupBy("w_state", "i_item_id")
            .agg(F.sum(when(col("d_date") < lit(pivot), net).otherwise(0.0))
                 .alias("sales_before"),
                 F.sum(when(col("d_date") >= lit(pivot), net).otherwise(0.0))
                 .alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q17(t):
    """Promotional vs total sales ratio (TpcxbbLikeSpark.scala:1419)."""
    in_tz_cust = (t["customer"]
                  .join(t["customer_address"]
                        .filter(col("ca_gmt_offset") == -5.0),
                        [("c_current_addr_sk", "ca_address_sk")], "leftsemi")
                  .select("c_customer_sk"))
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_year") == 2001)
                                       & (col("d_moy") == 12)),
                  [("ss_sold_date_sk", "d_date_sk")], "leftsemi")
            .join(t["item"].filter(col("i_category").isin("Books", "Music")),
                  [("ss_item_sk", "i_item_sk")], "leftsemi")
            .join(t["store"].filter(col("s_gmt_offset") == -5.0),
                  [("ss_store_sk", "s_store_sk")], "leftsemi")
            .join(in_tz_cust, [("ss_customer_sk", "c_customer_sk")],
                  "leftsemi")
            .join(t["promotion"], [("ss_promo_sk", "p_promo_sk")]))
    promo_on = ((col("p_channel_dmail") == "Y") | (col("p_channel_email") == "Y")
                | (col("p_channel_tv") == "Y"))
    per_channel = (base.groupBy("p_channel_email", "p_channel_dmail",
                                "p_channel_tv")
                   .agg(F.sum("ss_ext_sales_price").alias("total"))
                   .select(when(promo_on, col("total")).otherwise(0.0)
                           .alias("promotional"), "total"))
    return (per_channel.agg(F.sum("promotional").alias("promotional"),
                            F.sum("total").alias("total"))
            .select("promotional", "total",
                    when(col("total") > 0,
                         100.0 * col("promotional") / col("total"))
                    .otherwise(0.0).alias("promo_percent")))


def q20(t):
    """Customer return-behavior segmentation vectors
    (TpcxbbLikeSpark.scala:1480)."""
    orders = (t["store_sales"]
              .groupBy("ss_customer_sk")
              .agg(F.countDistinct("ss_ticket_number").alias("orders_count"),
                   F.count("ss_item_sk").alias("orders_items"),
                   F.sum("ss_net_paid").alias("orders_money")))
    returns = (t["store_returns"]
               .groupBy("sr_customer_sk")
               .agg(F.countDistinct("sr_ticket_number").alias("returns_count"),
                    F.count("sr_item_sk").alias("returns_items"),
                    F.sum("sr_return_amt").alias("returns_money")))
    ratio = lambda a, b: F.round(  # noqa: E731
        when(col(a).isNull() | col(b).isNull() | (col(a) / col(b)).isNull(),
             0.0).otherwise(col(a) / col(b)), 7)
    return (orders.join(returns, [("ss_customer_sk", "sr_customer_sk")],
                        "left")
            .select(col("ss_customer_sk").alias("user_sk"),
                    ratio("returns_count", "orders_count").alias("orderRatio"),
                    ratio("returns_items", "orders_items").alias("itemsRatio"),
                    ratio("returns_money", "orders_money")
                    .alias("monetaryRatio"),
                    F.round(F.coalesce(col("returns_count"), lit(0)), 0)
                    .alias("frequency"))
            .sort("user_sk"))


def q21(t):
    """Items sold, returned within 6 months, re-bought on the web
    (TpcxbbLikeSpark.scala:1542)."""
    part_sr = (t["store_returns"]
               .join(t["date_dim"].filter((col("d_year") == 2003)
                                          & (col("d_moy") >= 1)
                                          & (col("d_moy") <= 7)),
                     [("sr_returned_date_sk", "d_date_sk")])
               .select("sr_item_sk", "sr_customer_sk", "sr_ticket_number",
                       "sr_return_quantity"))
    part_ws = (t["web_sales"]
               .join(t["date_dim"].filter((col("d_year") >= 2003)
                                          & (col("d_year") <= 2005)),
                     [("ws_sold_date_sk", "d_date_sk")])
               .select("ws_item_sk", "ws_bill_customer_sk", "ws_quantity"))
    part_ss = (t["store_sales"]
               .join(t["date_dim"].filter((col("d_year") == 2003)
                                          & (col("d_moy") == 1)),
                     [("ss_sold_date_sk", "d_date_sk")])
               .select("ss_item_sk", "ss_store_sk", "ss_customer_sk",
                       "ss_ticket_number", "ss_quantity"))
    return (part_sr
            .join(part_ws, [("sr_item_sk", "ws_item_sk"),
                            ("sr_customer_sk", "ws_bill_customer_sk")])
            .join(part_ss, [("sr_ticket_number", "ss_ticket_number"),
                            ("sr_item_sk", "ss_item_sk"),
                            ("sr_customer_sk", "ss_customer_sk")])
            .join(t["store"], [("ss_store_sk", "s_store_sk")])
            .join(t["item"], [("sr_item_sk", "i_item_sk")])
            .groupBy("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .agg(F.sum("ss_quantity").alias("store_sales_quantity"),
                 F.sum("sr_return_quantity").alias("store_returns_quantity"),
                 F.sum("ws_quantity").alias("web_sales_quantity"))
            .sort("i_item_id", "i_item_desc", "s_store_id", "s_store_name")
            .limit(100))


def q22(t):
    """Inventory change around a price-change date by warehouse
    (TpcxbbLikeSpark.scala:1630)."""
    pivot = lit(datetime.date(2001, 5, 8))
    dd = F.datediff(col("d_date"), pivot)
    base = (t["inventory"]
            .join(t["item"].filter((col("i_current_price") >= 0.98)
                                   & (col("i_current_price") <= 1.5)),
                  [("inv_item_sk", "i_item_sk")])
            .join(t["warehouse"], [("inv_warehouse_sk", "w_warehouse_sk")])
            .join(t["date_dim"], [("inv_date_sk", "d_date_sk")])
            .filter((dd >= -30) & (dd <= 30)))
    agg = (base.groupBy("w_warehouse_name", "i_item_id")
           .agg(F.sum(when(dd < 0, col("inv_quantity_on_hand")).otherwise(0))
                .alias("inv_before"),
                F.sum(when(dd >= 0, col("inv_quantity_on_hand")).otherwise(0))
                .alias("inv_after")))
    ratio = col("inv_after") / col("inv_before")
    return (agg.filter((col("inv_before") > 0)
                       & (ratio >= 2.0 / 3.0) & (ratio <= 3.0 / 2.0))
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def q23(t):
    """Items with high inventory coefficient-of-variation in consecutive
    months (TpcxbbLikeSpark.scala:1685)."""
    monthly = (t["inventory"]
               .join(t["date_dim"].filter((col("d_year") == 2001)
                                          & (col("d_moy") >= 1)
                                          & (col("d_moy") <= 2)),
                     [("inv_date_sk", "d_date_sk")])
               .groupBy("inv_warehouse_sk", "inv_item_sk", "d_moy")
               .agg(F.stddev("inv_quantity_on_hand").alias("stdev"),
                    F.avg("inv_quantity_on_hand").alias("mean")))
    cov_tab = (monthly.filter((col("mean") > 0)
                              & (col("stdev") / col("mean") >= 1.3))
               .select("inv_warehouse_sk", "inv_item_sk", "d_moy",
                       (col("stdev") / col("mean")).alias("cov")))
    inv1 = (cov_tab.filter(col("d_moy") == 1)
            .select(col("inv_warehouse_sk").alias("w1"),
                    col("inv_item_sk").alias("i1"),
                    col("d_moy").alias("d_moy"), col("cov").alias("cov")))
    inv2 = (cov_tab.filter(col("d_moy") == 2)
            .select(col("inv_warehouse_sk").alias("w2"),
                    col("inv_item_sk").alias("i2"),
                    col("d_moy").alias("d_moy2"), col("cov").alias("cov2")))
    return (inv1.join(inv2, [("w1", "w2"), ("i1", "i2")])
            .select(col("w1").alias("inv_warehouse_sk"),
                    col("i1").alias("inv_item_sk"), "d_moy", "cov",
                    "d_moy2", "cov2")
            .sort("inv_warehouse_sk", "inv_item_sk"))


def q24(t):
    """Cross-price elasticity of demand for one item
    (TpcxbbLikeSpark.scala:1761). Item 10 (reference: item 10000; the
    generator floors at 100 items)."""
    comp = (t["item"].filter(col("i_item_sk") == 10)
            .join(t["item_marketprices"], [("i_item_sk", "imp_item_sk")])
            .select("i_item_sk", "imp_sk",
                    ((col("imp_competitor_price") - col("i_current_price"))
                     / col("i_current_price")).alias("price_change"),
                    "imp_start_date",
                    (col("imp_end_date") - col("imp_start_date"))
                    .alias("no_days_comp_price")))
    during = lambda d: ((col(d) >= col("imp_start_date"))  # noqa: E731
                        & (col(d) < col("imp_start_date")
                           + col("no_days_comp_price")))
    before = lambda d: ((col(d) >= col("imp_start_date")  # noqa: E731
                         - col("no_days_comp_price"))
                        & (col(d) < col("imp_start_date")))
    ws = (t["web_sales"].join(comp, [("ws_item_sk", "i_item_sk")])
          .groupBy("ws_item_sk", "imp_sk", "price_change")
          .agg(F.sum(when(during("ws_sold_date_sk"), col("ws_quantity"))
                     .otherwise(0)).alias("current_ws_quant"),
               F.sum(when(before("ws_sold_date_sk"), col("ws_quantity"))
                     .otherwise(0)).alias("prev_ws_quant")))
    ss = (t["store_sales"].join(comp, [("ss_item_sk", "i_item_sk")])
          .groupBy("ss_item_sk", "imp_sk", "price_change")
          .agg(F.sum(when(during("ss_sold_date_sk"), col("ss_quantity"))
                     .otherwise(0)).alias("current_ss_quant"),
               F.sum(when(before("ss_sold_date_sk"), col("ss_quantity"))
                     .otherwise(0)).alias("prev_ss_quant")))
    elasticity = ((col("current_ss_quant") + col("current_ws_quant")
                   - col("prev_ss_quant") - col("prev_ws_quant"))
                  / ((col("prev_ss_quant") + col("prev_ws_quant"))
                     * col("price_change")))
    return (ws.join(ss, [("ws_item_sk", "ss_item_sk"), ("imp_sk", "imp_sk")])
            .groupBy("ws_item_sk")
            .agg(F.avg(elasticity).alias("cross_price_elasticity")))


def q25(t):
    """RFM segmentation inputs over both channels
    (TpcxbbLikeSpark.scala:1861). Recency pivot = date_sk(2003-01-02)
    (reference constant 37621 encodes the same date in dsdgen's epoch)."""
    cutoff = lit(datetime.date(2002, 1, 2))
    store = (t["store_sales"]
             .join(t["date_dim"].filter(col("d_date") > cutoff),
                   [("ss_sold_date_sk", "d_date_sk")])
             .filter(col("ss_customer_sk").isNotNull())
             .groupBy(col("ss_customer_sk").alias("cid"))
             .agg(F.countDistinct("ss_ticket_number").alias("frequency"),
                  F.max("ss_sold_date_sk").alias("most_recent_date"),
                  F.sum("ss_net_paid").alias("amount")))
    web = (t["web_sales"]
           .join(t["date_dim"].filter(col("d_date") > cutoff),
                 [("ws_sold_date_sk", "d_date_sk")])
           .filter(col("ws_bill_customer_sk").isNotNull())
           .groupBy(col("ws_bill_customer_sk").alias("cid"))
           .agg(F.countDistinct("ws_order_number").alias("frequency"),
                F.max("ws_sold_date_sk").alias("most_recent_date"),
                F.sum("ws_net_paid").alias("amount")))
    pivot = date_sk(datetime.date(2003, 1, 2))
    return (store.union(web)
            .groupBy("cid")
            .agg(F.max("most_recent_date").alias("mrd"),
                 F.sum("frequency").alias("frequency"),
                 F.sum("amount").alias("totalspend"))
            .select("cid",
                    when(lit(pivot) - col("mrd") < 60, 1.0).otherwise(0.0)
                    .alias("recency"),
                    "frequency", "totalspend")
            .sort("cid"))


def q26(t):
    """Book-buyer clustering vectors: per-customer counts by item class
    (TpcxbbLikeSpark.scala:1945)."""
    idc = lambda i: F.count(  # noqa: E731
        when(col("i_class_id") == i, 1).otherwise(None)).alias(f"id{i}")
    return (t["store_sales"].filter(col("ss_customer_sk").isNotNull())
            .join(t["item"].filter(col("i_category") == "Books"),
                  [("ss_item_sk", "i_item_sk")])
            .groupBy(col("ss_customer_sk").alias("cid"))
            .agg(F.count("ss_item_sk").alias("item_count"),
                 *[idc(i) for i in range(1, 16)])
            .filter(col("item_count") > 5)
            .drop("item_count")
            .sort("cid"))


def q28(t):
    """Sentiment-classifier train/test split of product reviews
    (TpcxbbLikeSpark.scala:2004): 90% train (pmod(sk,10) in 1..9), 10% test."""
    return (t["product_reviews"]
            .select("pr_review_sk", col("pr_review_rating").alias("pr_rating"),
                    "pr_review_content")
            .withColumn("part", when(F.pmod(col("pr_review_sk"), 10) == 0,
                                     "test").otherwise("train"))
            .sort("pr_review_sk"))


# ---------------------------------------------------------------------------
# The 11 queries the reference REJECTS (TpcxbbLikeSpark.scala:785-807,
# 1015-1019, 1097-1101, 1455-1478, 1993-2002, 2059-2069 all throw
# UnsupportedOperationException for UDTF/UDF/python). Here they run: the
# spec's UDTF sessionization is a lag-gap cumulative-sum window, its python
# path analysis is lag projections, and its sentiment/NER UDFs are word-list
# matching over split sentences (masked string kernels) — all riding the
# normal acceleration path. Constants adapt to the generator's scale as
# noted inline; the query *shapes* follow the public BigBench spec.
# ---------------------------------------------------------------------------

from spark_rapids_tpu.benchmarks.tpcxbb_data import (COMPETITOR_COMPANIES,
                                                     NEGATIVE_WORDS,
                                                     POSITIVE_WORDS)


def _sessionize(clicks):
    """Session ids over each user's ordered clickstream: a new session when
    >60 minutes pass between clicks (the spec's 'sessionize' UDTF role:
    lag gap flag -> running sum). Timestamps are minutes since the epoch
    (click_time_sk is minute-of-day in this generator)."""
    from spark_rapids_tpu.api import Window
    w = Window.partitionBy("wcs_user_sk").orderBy("ts")
    cum = w.rowsBetween(Window.unboundedPreceding, Window.currentRow)
    gap = col("ts") - F.lag("ts", 1).over(w)
    return (clicks.filter(col("wcs_user_sk").isNotNull())
            .withColumn("ts", col("wcs_click_date_sk") * 1440
                        + col("wcs_click_time_sk"))
            .withColumn("new_s",
                        when(gap.isNull() | (gap > 60), 1).otherwise(0))
            .withColumn("session_id", F.sum("new_s").over(cum)))


def _sentences(reviews):
    """One row per review sentence ('. '-separated; the fused split-part
    kernel feeds a created-array explode, so the array never materializes)."""
    def part(i):
        return F.split(col("pr_review_content"), "\\. ")[i]
    return (reviews
            .select("pr_review_sk", "pr_item_sk",
                    F.explode(F.array(part(0), part(1), part(2)))
                    .alias("sentence"))
            .filter(col("sentence").isNotNull() & (col("sentence") != "")))


def _pair_counts(df, basket_cols, item_col, out1, out2, min_cnt=0):
    """Co-occurrence pair counts shared by q1/q29/q30: distinct
    (basket, item) rows self-joined on the basket key(s), deduped with
    item1 < item2, counted, ordered count-desc with id tiebreaks."""
    df = df.select(*basket_cols, item_col).distinct()
    aliased = df.select(
        *[col(c).alias(f"_b{i}") for i, c in enumerate(basket_cols)],
        col(item_col).alias(out2))
    pairs = (df.join(aliased, [(c, f"_b{i}")
                               for i, c in enumerate(basket_cols)])
             .filter(col(item_col) < col(out2)))
    out = (pairs.groupBy(col(item_col).alias(out1), out2)
           .agg(F.count(lit(1)).alias("cnt")))
    if min_cnt > 1:
        out = out.filter(col("cnt") >= min_cnt)
    return out.sort(col("cnt").desc(), out1, out2).limit(100)


def _first_word(c, words):
    """First word of ``words`` contained in ``c`` ('' when none) — the
    sentiment-lexicon match as a masked when-chain, not NLP."""
    e = None
    for w_ in words:
        e = (when(c.contains(w_), w_) if e is None
             else e.when(c.contains(w_), w_))
    return e.otherwise("")


def q1(t):
    """Top items sold together in one store basket (spec: self-join on
    ss_ticket_number over category-filtered items; pair-count floor lowered
    from the spec's 50 to 3 for generator scales)."""
    cat = (t["item"].filter(col("i_category_id").isin(1, 2, 3))
           .select("i_item_sk"))
    ss = (t["store_sales"].filter(col("ss_store_sk").isNotNull())
          .join(cat, [("ss_item_sk", "i_item_sk")]))
    return _pair_counts(ss, ["ss_ticket_number"], "ss_item_sk",
                        "item_sk_1", "item_sk_2", min_cnt=3)


def q2(t):
    """Top 30 items viewed in the same online session as a target item
    (spec: sessionize UDTF + pair expansion; target item adapted to the
    generator's dense small item domain)."""
    target = 5
    s = (_sessionize(t["web_clickstreams"])
         .filter(col("wcs_item_sk").isNotNull())
         .select("wcs_user_sk", "session_id", "wcs_item_sk").distinct())
    hit = (s.filter(col("wcs_item_sk") == target)
           .select(col("wcs_user_sk").alias("u"),
                   col("session_id").alias("sid")).distinct())
    return (s.join(hit, [("wcs_user_sk", "u"), ("session_id", "sid")])
            .filter(col("wcs_item_sk") != target)
            .groupBy(col("wcs_item_sk").alias("item_sk"))
            .agg(F.count(lit(1)).alias("cnt"))
            .sort(col("cnt").desc(), "item_sk").limit(30))


def q3(t):
    """Items viewed within the 5 preceding clicks (and 10 days) before a
    purchase of an item in categories 2/3 (the spec's python path-analysis
    as lag projections over the user-ordered stream)."""
    from spark_rapids_tpu.api import Window
    w = Window.partitionBy("wcs_user_sk").orderBy("ts")
    c = (t["web_clickstreams"]
         .filter(col("wcs_user_sk").isNotNull()
                 & col("wcs_item_sk").isNotNull())
         .withColumn("ts", col("wcs_click_date_sk") * 1440
                     + col("wcs_click_time_sk")))
    # all five lag pairs in ONE windowed projection (the window sort runs
    # once), then unpivoted by a union of narrow selects
    wide = c.select(
        "wcs_user_sk", "wcs_click_date_sk", "wcs_sales_sk", "wcs_item_sk",
        *[e for k in range(1, 6) for e in
          (F.lag("wcs_item_sk", k).over(w).alias(f"vi{k}"),
           F.lag("wcs_click_date_sk", k).over(w).alias(f"vd{k}"))])
    lags = None
    for k in range(1, 6):
        lk = wide.select(
            "wcs_user_sk", "wcs_click_date_sk", "wcs_sales_sk",
            "wcs_item_sk", col(f"vi{k}").alias("viewed_item"),
            col(f"vd{k}").alias("viewed_date"))
        lags = lk if lags is None else lags.union(lk)
    cat = (t["item"].filter(col("i_category_id").isin(2, 3))
           .select("i_item_sk"))
    return (lags.filter(col("wcs_sales_sk").isNotNull()
                        & col("viewed_item").isNotNull()
                        & (col("wcs_click_date_sk") - col("viewed_date")
                           <= 10))
            .join(cat, [("wcs_item_sk", "i_item_sk")])
            .groupBy(col("viewed_item").alias("lastviewed_item"))
            .agg(F.count(lit(1)).alias("cnt"))
            .sort(col("cnt").desc(), "lastviewed_item").limit(30))


def q4(t):
    """Shopping-cart abandonment: sessions that visited an 'order' page but
    no 'confirmation' page and recorded no purchase; average pages per
    abandoned session (spec: sessionize + python session filter)."""
    s = (_sessionize(t["web_clickstreams"])
         .join(t["web_page"], [("wcs_web_page_sk", "wp_web_page_sk")]))
    flag = lambda c: F.sum(when(c, 1).otherwise(0))  # noqa: E731
    per = (s.groupBy("wcs_user_sk", "session_id")
           .agg(flag(col("wp_type") == "order").alias("n_order"),
                flag(col("wp_type") == "confirmation").alias("n_conf"),
                flag(col("wcs_sales_sk").isNotNull()).alias("n_buy"),
                F.count(lit(1)).alias("pages")))
    return (per.filter((col("n_order") > 0) & (col("n_conf") == 0)
                       & (col("n_buy") == 0))
            .agg(F.sum(col("pages") * 1.0).alias("total_pages"),
                 F.count(lit(1)).alias("abandoned_sessions"))
            .select((col("total_pages") / col("abandoned_sessions"))
                    .alias("avg_pages_per_abandoned_session"),
                    "abandoned_sessions"))


def q8(t):
    """Sales impact of review reading: purchases in sessions where a
    'review' page view happened earlier vs all other purchases (spec:
    python session scan; here a session-level min-ts semi profile)."""
    s = (_sessionize(t["web_clickstreams"])
         .join(t["web_page"], [("wcs_web_page_sk", "wp_web_page_sk")]))
    first_review = (s.filter(col("wp_type") == "review")
                    .groupBy(col("wcs_user_sk").alias("u"),
                             col("session_id").alias("sid"))
                    .agg(F.min("ts").alias("first_review_ts")))
    buys = s.filter(col("wcs_sales_sk").isNotNull()
                    & col("wcs_item_sk").isNotNull())
    flagged = (buys.join(first_review,
                         [("wcs_user_sk", "u"), ("session_id", "sid")],
                         how="left")
               .withColumn("after_review",
                           when(col("first_review_ts").isNotNull()
                                & (col("ts") > col("first_review_ts")),
                                1).otherwise(0)))
    return (flagged.join(t["item"].select("i_item_sk", "i_current_price"),
                         [("wcs_item_sk", "i_item_sk")])
            .groupBy("after_review")
            .agg(F.count(lit(1)).alias("purchases"),
                 F.sum("i_current_price").alias("amount"))
            .sort("after_review"))


def q10(t):
    """Sentence-level review sentiment (the spec's sentiment UDF as
    word-list matching over split sentences)."""
    sent = _sentences(t["product_reviews"])
    pos = _first_word(col("sentence"), POSITIVE_WORDS)
    neg = _first_word(col("sentence"), NEGATIVE_WORDS)
    return (sent.withColumn("pos_w", pos).withColumn("neg_w", neg)
            .filter((col("pos_w") != "") | (col("neg_w") != ""))
            .select("pr_item_sk",
                    col("sentence").alias("review_sentence"),
                    when(col("pos_w") != "", "POS").otherwise("NEG")
                    .alias("sentiment"),
                    when(col("pos_w") != "", col("pos_w"))
                    .otherwise(col("neg_w")).alias("sentiment_word"))
            .sort("pr_item_sk", "review_sentence", "sentiment_word"))


def q18(t):
    """Stores with declining sales + negative review sentences naming them
    (spec: per-store linear regression, then sentence-level NER on the store
    name; the mention is extracted with the split-part kernel and
    equi-joined on s_store_name)."""
    daily = (t["store_sales"]
             .filter(col("ss_store_sk").isNotNull()
                     & col("ss_sold_date_sk").isNotNull())
             .groupBy("ss_store_sk", "ss_sold_date_sk")
             .agg(F.sum("ss_net_paid").alias("s")))
    x = col("ss_sold_date_sk") * 1.0
    reg = (daily.groupBy("ss_store_sk")
           .agg(F.count(lit(1)).alias("n"),
                F.sum(x).alias("sx"), F.sum("s").alias("sy"),
                F.sum(x * col("s")).alias("sxy"),
                F.sum(x * x).alias("sxx")))
    slope = ((col("n") * col("sxy") - col("sx") * col("sy"))
             / (col("n") * col("sxx") - col("sx") * col("sx")))
    declining = (reg.withColumn("slope", slope)
                 .filter(col("slope") < 0)
                 .join(t["store"], [("ss_store_sk", "s_store_sk")])
                 .select(col("s_store_name").alias("store_name")).distinct())
    sent = _sentences(t["product_reviews"])
    hits = (sent
            .withColumn("neg_word", _first_word(col("sentence"),
                                                NEGATIVE_WORDS))
            .filter((col("neg_word") != "")
                    & col("sentence").contains(" at store "))
            .withColumn("mention", F.substring_index(col("sentence"),
                                                     " at store ", -1)))
    return (hits.join(declining, [("mention", "store_name")])
            .select(col("mention").alias("store_name"), "pr_review_sk",
                    "sentence", "neg_word")
            .sort("store_name", "pr_review_sk", "sentence"))


def q19(t):
    """Negative review sentences for items with returns in BOTH channels
    (spec: return-heavy item selection + sentiment UDF; the week filter is
    dropped — the generator links returns uniformly over the year)."""
    sr = (t["store_returns"].groupBy(col("sr_item_sk").alias("item_sk"))
          .agg(F.sum("sr_return_quantity").alias("sr_qty")))
    wr = (t["web_returns"].groupBy(col("wr_item_sk").alias("item_sk2"))
          .agg(F.count(lit(1)).alias("wr_cnt")))
    heavy = (sr.join(wr, [("item_sk", "item_sk2")])
             .filter((col("sr_qty") >= 1) & (col("wr_cnt") >= 1))
             .select("item_sk"))
    sent = _sentences(t["product_reviews"])
    return (sent
            .withColumn("neg_word", _first_word(col("sentence"),
                                                NEGATIVE_WORDS))
            .filter(col("neg_word") != "")
            .join(heavy, [("pr_item_sk", "item_sk")])
            .select("pr_item_sk", "pr_review_sk", "sentence", "neg_word")
            .sort("pr_item_sk", "pr_review_sk", "sentence"))


def q27(t):
    """Competitor-company extraction from review sentences (the spec's NER
    UDF: extract the entity after 'compared to' and keep known companies)."""
    sent = _sentences(t["product_reviews"])
    return (sent.filter(col("sentence").contains(" compared to "))
            .withColumn("company", F.substring_index(col("sentence"),
                                                     " compared to ", -1))
            .filter(col("company").isin(*COMPETITOR_COMPANIES))
            .select("pr_review_sk", "pr_item_sk", "company", "sentence")
            .sort("pr_review_sk", "company", "sentence"))


def q29(t):
    """Top category pairs co-sold in one web order (spec: self-join on
    ws_order_number at category level)."""
    ws = (t["web_sales"]
          .join(t["item"].select("i_item_sk", "i_category_id"),
                [("ws_item_sk", "i_item_sk")]))
    return _pair_counts(ws, ["ws_order_number"], "i_category_id",
                        "category_id_1", "category_id_2")


def q30(t):
    """Top category pairs viewed in the same online session (q2's
    sessionization at category level — the spec's second UDTF use)."""
    s = (_sessionize(t["web_clickstreams"])
         .filter(col("wcs_item_sk").isNotNull())
         .join(t["item"].select("i_item_sk", "i_category_id"),
               [("wcs_item_sk", "i_item_sk")]))
    return _pair_counts(s, ["wcs_user_sk", "session_id"], "i_category_id",
                        "category_id_1", "category_id_2")


QUERIES: Dict[str, object] = {
    name: fn for name, fn in list(globals().items())
    if name.startswith("q") and name[1:].isdigit() and callable(fn)}

#: the reference rejects these 11 (UDTF/UDF/python,
#: TpcxbbLikeSpark.scala:785-2069); this engine runs all 30
UNSUPPORTED = ()
