"""Central jax runtime setup.

Every module that touches jax imports it through here so process-wide settings are
applied exactly once:

- ``jax_enable_x64``: Spark's LONG/DOUBLE semantics require true 64-bit arithmetic;
  jax's default 32-bit mode silently truncates. On TPU, int64 is natively supported
  and float64 is compiler-emulated — correctness first, with an opt-in
  ``variableFloatAgg``-style downgrade path for perf-critical double math later.
"""
from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402,F401


def default_device():
    return jax.devices()[0]


def device_count() -> int:
    return jax.device_count()
