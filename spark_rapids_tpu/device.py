"""Central jax runtime setup.

Every module that touches jax imports it through here so process-wide settings are
applied exactly once:

- ``jax_enable_x64``: Spark's LONG/DOUBLE semantics require true 64-bit arithmetic;
  jax's default 32-bit mode silently truncates. On TPU, int64 is natively supported
  and float64 is compiler-emulated — correctness first, with an opt-in
  ``variableFloatAgg``-style downgrade path for perf-critical double math later.
"""
from __future__ import annotations

import os
import sys

import jax

jax.config.update("jax_enable_x64", True)

# Deep traces (the fused shuffle kernel: jit -> pjit -> pallas, with x64
# promotion wrappers on every op) legitimately exceed CPython's default
# 1000-frame limit during tracing.
sys.setrecursionlimit(max(sys.getrecursionlimit(), 20000))

# Persistent XLA compilation cache: compiled executables survive process
# restarts (measured ~20x on repeated first-compiles over the remote-chip
# tunnel, where a single variadic-sort program can take minutes to build).
# Opt out with SPARK_RAPIDS_TPU_COMPILE_CACHE=off; relocate with =<dir>.
#
# The directory is keyed by a HOST-CPU signature: XLA:CPU entries embed AOT
# machine features, and deserializing one compiled under a different
# feature set (e.g. a remote compile helper) can SIGSEGV outright — a
# heterogeneous fleet must never share one cache directory.


def _host_cpu_sig() -> str:
    import hashlib
    import platform
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = line
                    break
    except OSError:
        pass
    return hashlib.sha1(
        (platform.machine() + flags).encode()).hexdigest()[:10]


_cache = os.environ.get("SPARK_RAPIDS_TPU_COMPILE_CACHE", "")
if _cache.lower() != "off":
    if not _cache:
        _cache = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), f".jax_cache-{_host_cpu_sig()}")
    try:
        os.makedirs(_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass

import jax.numpy as jnp  # noqa: E402,F401


def default_device():
    return jax.devices()[0]


def device_count() -> int:
    return jax.device_count()
