from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema, bucket_capacity
from spark_rapids_tpu.columnar.column import DeviceColumn, null_column
from spark_rapids_tpu.columnar.batch import DeviceBatch
