"""Encoded columnar forms that cross the host link instead of decoded bytes.

BENCH_r05 put 0.043 s of device compute under a 12.55 s H2D upload — the
link is the wall, so this module stops shipping decoded bytes over it
(ROADMAP item 1; "GPU Acceleration of SQL Analytics on Compressed Data"
measures order-of-magnitude effective-bandwidth gains from exactly this
shape). Three cooperating pieces:

- **Run-end-encoded staging** (`ree_staged`, `expand_ree_device`): a parquet
  column chunk whose index stream is RLE-dominant uploads as (run_ends,
  per-run values) pairs — often hundreds of bytes for millions of rows —
  and expands in HBM with a jitted searchsorted gather, the TPU analog of
  the reference's device-side decode (GpuParquetScan.scala:576). The host
  never materializes the decoded column.
- **DictEncoding** (`DictEncoding`, `EncSpec`, flatten helpers): a device
  batch column that arrived dictionary-encoded KEEPS its narrow index
  vector and small dictionary alongside the decoded data, so downstream
  operators can run filters, group-by keys and equi-join keys directly on
  the int32 index domain (late materialization; exprs/encoded.py).
- **DictionaryUnifier**: per-scan host-side remap of each row group's
  dictionary into one growing, prefix-compatible dictionary per column, so
  batches of one scan share a dictionary identity (``token``) and
  ``concat_device_batches`` can carry the encoding across batch boundaries
  instead of dropping it at the first coalesce.
"""
from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.dtypes import DType

#: pa.Field metadata key carrying the DictionaryUnifier token of a column
DICT_TOKEN_META = b"spark_rapids_tpu.dict_token"


# ---------------------------------------------------------------------------
# run-end-encoded host staging + device expansion
# ---------------------------------------------------------------------------
def ree_staged(arr: "pa.RunEndEncodedArray") -> Tuple[np.ndarray, pa.Array]:
    """Normalize a (possibly sliced) REE array to slice-relative
    ``(run_ends int32, values)``: run_ends are clipped to the slice and the
    values array keeps only the runs the slice touches. O(runs), not O(rows)
    — slicing stays cheap however long the runs are."""
    ends = np.asarray(arr.run_ends, dtype=np.int64)
    off, n = arr.offset, len(arr)
    if n == 0:
        return np.zeros(0, np.int32), arr.values.slice(0, 0)
    first = int(np.searchsorted(ends, off, side="right"))
    last = int(np.searchsorted(ends, off + n - 1, side="right"))
    rel = np.clip(ends[first:last + 1] - off, 0, n).astype(np.int32)
    rel[-1] = n
    return rel, arr.values.slice(first, last + 1 - first)


def ree_to_plain(arr: "pa.RunEndEncodedArray") -> pa.Array:
    """Expand an REE array on HOST (CPU-engine / fallback paths only; the
    device path expands in HBM via expand_ree_device)."""
    ends, vals = ree_staged(arr)
    if len(ends) == 0:
        return vals
    counts = np.diff(np.concatenate([[0], ends.astype(np.int64)]))
    take = np.repeat(np.arange(len(ends), dtype=np.int64), counts)
    return vals.take(pa.array(take))


def expand_ree_device(xp, run_ends, values, capacity: int):
    """Jitted device expansion: row i takes values[j] for the first run end
    > i (cumsum/searchsorted gather). Rows past the last run end (capacity
    padding) clamp to the final run; their garbage lands beyond the live
    prefix, which the batch's validity/alive mask already excludes."""
    idx = xp.searchsorted(run_ends, xp.arange(capacity, dtype=np.int32),
                          side="right")
    idx = xp.minimum(idx, len(values) - 1).astype(np.int32)
    return xp.take(values, idx, axis=0), idx


def ree_encoded_nbytes(num_runs: int, elem_size: int) -> int:
    """On-link bytes of the REE form: int32 run ends + one value per run."""
    return num_runs * (4 + elem_size)


# ---------------------------------------------------------------------------
# device-side dictionary encoding (late materialization)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class DictEncoding:
    """The encoded form of a device column, kept alongside the decoded data:
    ``data == take(values, indices)`` row-wise (strings: byte-matrix rows +
    lengths). ``token`` identifies the dictionary stream a batch came from
    (DictionaryUnifier): same token => dictionaries are prefix-compatible,
    so concatenation and encoded-domain joins need no remap.

    ``values`` is PADDED to a power-of-two bucket (device-side zeros, no
    link bytes): the padded size is what enters jit cache keys (EncSpec.k),
    so a unified dictionary growing by a few entries per row group does not
    recompile every encoded-domain program — the R001 discipline applied to
    dictionaries. ``k_real`` is the live entry count; indices never point
    past it, and value-sensitive kernels (the join remap) mask pad slots
    with it as a traced scalar."""

    indices: Any                      # int32[capacity] device array
    values: Any                       # [k] or [k, width] device array
    k_real: int                       # live dictionary entries (<= k)
    lengths: Optional[Any] = None     # int32[k], strings only
    token: Optional[str] = None

    @property
    def k(self) -> int:
        return int(self.values.shape[0])


def dict_bucket(k: int) -> int:
    """Power-of-two padding bucket for dictionary device arrays."""
    from spark_rapids_tpu.columnar.dtypes import bucket_capacity
    return bucket_capacity(k, minimum=8)


@dataclass(frozen=True)
class EncSpec:
    """Static shape of one column's DictEncoding — everything a jitted
    program needs to know at trace time (part of every jit cache key that
    involves encoded-domain execution)."""
    ordinal: int
    dtype: DType
    k: int
    width: int = 0                    # string matrix width; 0 otherwise

    @property
    def is_string(self) -> bool:
        return self.dtype is DType.STRING


class EncView:
    """Trace-time view of one encoding: the index vector plus the dictionary
    as a ColV over ``k`` (padded) rows — all-valid; parquet/unified
    dictionaries hold no nulls, nullness rides the index validity.
    ``k_real`` is the traced live-entry count (pad slots are garbage that
    indices never reference; only value-sensitive kernels mask with it)."""

    def __init__(self, xp, spec: EncSpec, indices, values, k_real,
                 lengths=None):
        from spark_rapids_tpu.exprs.core import ColV
        self.spec = spec
        self.indices = indices
        self.k_real = k_real
        self.values = ColV(spec.dtype, values,
                           xp.ones(spec.k, dtype=np.bool_), lengths)


def enc_specs_of(batch) -> Tuple[EncSpec, ...]:
    """EncSpecs for every column of ``batch`` that still carries its
    dictionary encoding (only useful encodings: k below the row capacity)."""
    specs = []
    for i, c in enumerate(batch.columns):
        e = c.encoding
        if e is None or e.k_real >= batch.capacity:
            continue
        width = int(e.values.shape[1]) if e.values.ndim > 1 else 0
        specs.append(EncSpec(i, c.dtype, e.k, width))
    return tuple(specs)


def flatten_encodings(batch, specs: Sequence[EncSpec]) -> List[Any]:
    """Device arrays of the named encodings in the fixed flat order
    [indices, values(, lengths), k_real] per spec — appended after the
    regular column flat args at jit boundaries. ``k_real`` rides as a
    TRACED scalar (like num_rows) so dictionary growth inside one padding
    bucket never recompiles."""
    flat: List[Any] = []
    for s in specs:
        e = batch.columns[s.ordinal].encoding
        flat.append(e.indices)
        flat.append(e.values)
        if e.lengths is not None:
            flat.append(e.lengths)
        flat.append(np.int32(e.k_real))
    return flat


def unflatten_encodings(xp, specs: Sequence[EncSpec], flat
                        ) -> Dict[int, EncView]:
    views: Dict[int, EncView] = {}
    i = 0
    for s in specs:
        if s.is_string:
            views[s.ordinal] = EncView(xp, s, flat[i], flat[i + 1],
                                       flat[i + 3], flat[i + 2])
            i += 4
        else:
            views[s.ordinal] = EncView(xp, s, flat[i], flat[i + 1],
                                       flat[i + 2])
            i += 3
    return views


def dictionary_is_unique(values: np.ndarray,
                         lengths: Optional[np.ndarray] = None) -> bool:
    """Encoded-domain execution equates rows by dictionary INDEX, which is
    only sound when dictionary values are pairwise distinct. Parquet and
    unifier dictionaries are; user-built pa.DictionaryArrays may not be —
    check before claiming the encoding (k is small, so this is cheap)."""
    if values.ndim > 1:
        rows = np.concatenate(
            [values, np.zeros((len(values), 1), values.dtype)
             if lengths is None else lengths[:, None].astype(values.dtype)],
            axis=1)
        return len(np.unique(rows, axis=0)) == len(rows)
    return len(np.unique(values)) == len(values)


def field_token(schema: pa.Schema, i: int) -> Optional[str]:
    meta = schema.field(i).metadata
    if meta and DICT_TOKEN_META in meta:
        return meta[DICT_TOKEN_META].decode()
    return None


# ---------------------------------------------------------------------------
# host-side dictionary unification (per scan)
# ---------------------------------------------------------------------------
class DictionaryUnifier:
    """Grow one dictionary per column across a scan's row groups / files.

    Each row group's local dictionary is remapped into the column's global
    dictionary (append-only, so earlier batches' indices stay valid — the
    dictionaries of any two batches with the same token are prefix-
    compatible). The remap is a tiny LUT gather: O(k) dictionary work plus
    one vectorized O(n) int gather per chunk, nothing like a decode.

    Float dictionaries dedupe by BIT PATTERN, not Python ``==``: -0.0 and
    0.0 are distinct entries (collapsing them would flip signs in decoded
    rows) and equal-bit NaNs dedupe instead of growing the dictionary per
    row group; values are stored as numpy scalars so reconstruction is
    bit-exact."""

    def __init__(self):
        self._cols: Dict[str, Tuple[str, Dict[Any, int], List[Any]]] = {}

    def _state(self, name: str):
        st = self._cols.get(name)
        if st is None:
            st = (uuid.uuid4().hex, {}, [])
            self._cols[name] = st
        return st

    def token_of(self, name: str) -> Optional[str]:
        st = self._cols.get(name)
        return st[0] if st else None

    def unify(self, name: str, arr: pa.DictionaryArray
              ) -> Tuple[pa.DictionaryArray, str]:
        """Remap one chunk's dictionary into the column's global dictionary;
        returns the remapped array + the column token."""
        token, index_of, values = self._state(name)
        dict_type = arr.dictionary.type
        bitwise = pa.types.is_floating(dict_type)
        np_t = dict_type.to_pandas_dtype() if bitwise else None
        if bitwise and arr.dictionary.null_count == 0:
            local = list(np.asarray(arr.dictionary))
            keys = [v.tobytes() for v in local]
        elif bitwise:
            # null dictionary entries (never produced by the page reader):
            # keep the byte-key domain so chunks of one column never mix
            # key kinds; python floats preserve -0.0 and the standard NaN
            local = [None if v is None else np.dtype(np_t).type(v)
                     for v in arr.dictionary.to_pylist()]
            keys = [None if v is None else v.tobytes() for v in local]
        else:
            local = arr.dictionary.to_pylist()
            keys = local
        lut = np.empty(len(local), dtype=np.int32)
        for j, (key, v) in enumerate(zip(keys, local)):
            gi = index_of.get(key)
            if gi is None:
                gi = len(values)
                index_of[key] = gi
                values.append(v)
            lut[j] = gi
        k = len(values)
        idx_t = (pa.int8() if k <= 127 else
                 pa.int16() if k <= 0x7FFF else pa.int32())
        local_idx = np.asarray(arr.indices.fill_null(0)).astype(np.int64)
        remapped = lut[local_idx].astype(idx_t.to_pandas_dtype())
        mask = (None if arr.indices.null_count == 0
                else np.asarray(arr.indices.is_null()))
        indices = pa.array(remapped, type=idx_t, mask=mask)
        if bitwise and all(v is not None for v in values):
            global_vals = pa.array(np.array(values, dtype=np_t))
        else:
            global_vals = pa.array(values, type=dict_type)
        return pa.DictionaryArray.from_arrays(indices, global_vals), token


def with_dict_tokens(table: pa.Table, tokens: Dict[str, str]) -> pa.Table:
    """Stamp dictionary tokens into the table's field metadata so they
    survive slicing/coalescing and reach DeviceBatch.from_arrow without a
    side channel."""
    if not tokens:
        return table
    fields = []
    for f in table.schema:
        if f.name in tokens:
            meta = dict(f.metadata or {})
            meta[DICT_TOKEN_META] = tokens[f.name].encode()
            fields.append(f.with_metadata(meta))
        else:
            fields.append(f)
    return pa.table(list(table.columns), schema=pa.schema(fields))
