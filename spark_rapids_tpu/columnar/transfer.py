"""Transfer pipeline: chunked overlapped uploads and asynchronous downloads.

BENCH_r05 put TPC-H Q1 at 0.043 s of device compute under a 12.55 s upload
and 1.16 s download — the engine is data-movement-bound, the regime Theseus
says a distributed accelerator query engine must engineer around and the
reference plugin covers with pinned-memory async H2D in
``HostToGpuCoalesceIterator``. This module makes the host link a pipeline
instead of a wall:

- **upload_table** splits large tables into row chunks so chunk N+1 stages on
  host (numpy staging is CPU work) while chunk N's asynchronous
  ``jax.device_put`` is in flight on the link, then reassembles the chunks on
  device through ``concat_device_batches`` (bits siblings included, so the
  result is bit-identical to a single-shot ``DeviceBatch.from_arrow``). At
  most ``max_inflight`` chunk uploads are outstanding — Sparkle's
  memory-hierarchy argument: bounded in-flight buffers, not unbounded queues.
- **start_download** begins a per-batch device->host copy
  (``copy_to_host_async``) the moment the producing program is dispatched, so
  D2H overlaps the remaining compute; ``PendingDownload.result()`` blocks only
  for that batch's buffers.

Counters land in the process-global ``TRANSFER_METRICS``
(utils/metrics.py); sessions expose the per-action delta plus link GB/s via
``session.last_metrics["transfer"]``.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import pyarrow as pa

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax

from spark_rapids_tpu.columnar.batch import (DEFAULT_STRING_MAX_BYTES,
                                             DeviceBatch, fetched_to_arrow)
from spark_rapids_tpu.utils import metrics as um
from spark_rapids_tpu.utils import tracing as _tracing


def _batch_arrays(batch: DeviceBatch) -> List[Any]:
    arrs = []
    for c in batch.columns:
        arrs.append(c.data)
        arrs.append(c.validity)
        if c.lengths is not None:
            arrs.append(c.lengths)
        if c.bits is not None:
            arrs.append(c.bits)
        if c.encoding is not None:
            arrs.append(c.encoding.indices)
            arrs.append(c.encoding.values)
            if c.encoding.lengths is not None:
                arrs.append(c.encoding.lengths)
    return arrs


def _wait_uploaded(batch: DeviceBatch) -> None:
    """Block until every buffer of the batch is resident on device."""
    jax.block_until_ready(_batch_arrays(batch))


def chunk_bounds(table: pa.Table, chunk_rows: int) -> List[int]:
    """Chunk start offsets, aligned to the table's record-batch boundaries
    (for parquet readers those are row-group/page boundaries, so chunk
    staging slices are zero-copy) while keeping every chunk under about
    chunk_rows rows. Oversized record batches are split at chunk_rows."""
    n = table.num_rows
    if chunk_rows <= 0 or n <= chunk_rows:
        return [0]
    edges = {0}
    off = 0
    for b in table.to_batches():
        off += b.num_rows
        if off < n:
            edges.add(off)
    bounds = [0]
    for edge in sorted(edges | {n}):
        while edge - bounds[-1] > chunk_rows:
            bounds.append(bounds[-1] + chunk_rows)
        # take a record-batch edge only when the chunk grew big enough;
        # tiny trailing slivers merge into the previous chunk
        if edge != n and edge - bounds[-1] >= chunk_rows // 2:
            bounds.append(edge)
    return bounds


def upload_table(table: pa.Table,
                 string_max_bytes: int = DEFAULT_STRING_MAX_BYTES,
                 chunk_rows: int = 0, max_inflight: int = 2,
                 device: Any = None,
                 stats: Optional[Dict[str, Any]] = None,
                 with_bits: bool = True) -> DeviceBatch:
    """Host arrow table -> DeviceBatch via the chunked overlapped pipeline.

    chunk_rows <= 0 (or a table at most one chunk big) takes the single-shot
    ``DeviceBatch.from_arrow`` path. ``stats``, when given, is filled with the
    per-chunk timing breakdown bench.py publishes (per_chunk_upload_s,
    stage_s, upload_overlap_efficiency, inflight_high_water).
    """
    m = um.TRANSFER_METRICS
    t_start = time.perf_counter()
    t_start_ns = time.perf_counter_ns()
    bounds = chunk_bounds(table, chunk_rows)
    if len(bounds) < 2:
        # args dicts build only when tracing is live — the per-upload
        # disabled cost stays one bool read (the <2% nightly bound)
        span = (_tracing.span("transfer.upload", "transfer",
                              {"rows": table.num_rows, "chunks": 1})
                if _tracing.TRACER.on else _tracing._NULL_SPAN)
        with span:
            batch = DeviceBatch.from_arrow(table, string_max_bytes,
                                           device=device,
                                           with_bits=with_bits)
        if stats is not None:
            # bench instrumentation wants the honest transfer wall; the
            # engine path must NOT sync — the async device_put overlapping
            # the consumer's work is the whole point on serial paths
            _wait_uploaded(batch)
        wall = time.perf_counter() - t_start
        m[um.TRANSFER_UPLOAD_BYTES].add(batch.device_size_bytes)
        m[um.TRANSFER_UPLOAD_SECONDS].add(wall)
        m[um.TRANSFER_UPLOAD_CHUNKS].add(1)
        m[um.TRANSFER_INFLIGHT_PEAK].set_max(1)
        if stats is not None:
            stats.update(chunks=1, wall_s=wall, stage_s=wall,
                         per_chunk_upload_s=[round(wall, 4)],
                         upload_overlap_efficiency=0.0,
                         inflight_high_water=1)
        return batch

    n = table.num_rows
    ends = bounds[1:] + [n]
    chunks: List[DeviceBatch] = []
    inflight: List[DeviceBatch] = []
    per_chunk: List[float] = []
    stage_total = 0.0
    peak = 0
    for start, end in zip(bounds, ends):
        t0 = time.perf_counter()
        # staging (numpy work) for THIS chunk happens while the previous
        # chunks' device_puts are still in flight — that's the overlap.
        # bucketed chunks: similar-sized chunks share one power-of-two
        # capacity, so the slice/concat programs of the assembly below hit
        # XLA's compile cache across tables instead of compiling per exact
        # chunk-size tuple (padding is built ON DEVICE — no link bytes)
        # (span timestamps are the staging call boundaries that already
        # exist — the async device_put is NOT awaited, per R002; the args
        # dict builds only when tracing is live)
        span = (_tracing.span("transfer.upload_chunk", "transfer",
                              {"rows": end - start, "offset": start,
                               "inflight": len(inflight)})
                if _tracing.TRACER.on else _tracing._NULL_SPAN)
        with span:
            b = DeviceBatch.from_arrow(table.slice(start, end - start),
                                       string_max_bytes, device=device,
                                       with_bits=with_bits)
        t1 = time.perf_counter()
        stage_total += t1 - t0
        per_chunk.append(round(t1 - t0, 4))
        chunks.append(b)
        inflight.append(b)
        peak = max(peak, len(inflight))
        while len(inflight) >= max_inflight:
            _wait_uploaded(inflight.pop(0))   # bounded: block on the OLDEST
    # device-side assembly: slice + concat + one capacity pad, the same
    # cached-program shape every coalesce uses (concat_device_batches).
    # No trailing sync: the assembly is enqueued behind the in-flight
    # transfers and the caller's first use of the result awaits it.
    from spark_rapids_tpu.execs.tpu_execs import concat_device_batches
    out = concat_device_batches(chunks, chunks[0].schema, string_max_bytes)
    if stats is not None:
        _wait_uploaded(out)     # bench: honest wall including assembly
    wall = time.perf_counter() - t_start
    m[um.TRANSFER_UPLOAD_BYTES].add(out.device_size_bytes)
    m[um.TRANSFER_UPLOAD_SECONDS].add(wall)
    m[um.TRANSFER_UPLOAD_CHUNKS].add(len(chunks))
    m[um.TRANSFER_INFLIGHT_PEAK].set_max(peak)
    if _tracing.TRACER.on:
        _tracing.record("transfer.upload", "transfer", t_start_ns,
                        time.perf_counter_ns() - t_start_ns,
                        {"rows": n, "chunks": len(chunks),
                         "inflight_peak": peak,
                         "bytes": out.device_size_bytes})
    if stats is not None:
        # fraction of the upload wall covered by productive host staging:
        # 1.0 = every transfer fully hidden behind staging; a serial
        # stage-then-wait loop scores stage/(stage+transfer)
        stats.update(chunks=len(chunks), wall_s=wall, stage_s=stage_total,
                     per_chunk_upload_s=per_chunk,
                     upload_overlap_efficiency=round(
                         min(1.0, stage_total / wall) if wall > 0 else 0.0, 4),
                     inflight_high_water=peak)
    return out


def upload_table_conf(table: pa.Table, string_max_bytes: int, conf,
                      device: Any = None,
                      with_bits: bool = True) -> DeviceBatch:
    """upload_table with chunking parameters read from a TpuConf."""
    from spark_rapids_tpu import config as cfg
    return upload_table(table, string_max_bytes,
                        chunk_rows=conf.get(cfg.TRANSFER_CHUNK_ROWS),
                        max_inflight=conf.get(cfg.TRANSFER_MAX_INFLIGHT),
                        device=device, with_bits=with_bits)


# ------------------------------------------------------------------ downloads
class PendingDownload:
    """One result batch's in-flight device->host download. Created at
    dispatch time (the device queue is in order, so the copy starts as soon
    as the producing program finishes); ``result()`` blocks only on this
    batch's buffers and converts to arrow."""

    def __init__(self, batch: DeviceBatch):
        self._schema = batch.schema
        self._num_rows = batch.num_rows
        self._sliced = batch.sliced_buffers()
        #: dispatch timestamp — the span start (an existing boundary: the
        #: copy_to_host_async enqueue; resolution stamps the end, R002)
        self._t_dispatch_ns = time.perf_counter_ns()
        nbytes = 0
        for data, validity, lengths in self._sliced:
            for arr in (data, validity, lengths):
                if arr is None:
                    continue
                nbytes += arr.size * arr.dtype.itemsize
                start = getattr(arr, "copy_to_host_async", None)
                if start is not None:
                    start()
        self.nbytes = nbytes

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def result(self) -> pa.Table:
        t0 = time.perf_counter()
        fetched = jax.device_get(self._sliced)
        self._sliced = fetched      # idempotent: device_get of host arrays
        dt = time.perf_counter() - t0
        m = um.TRANSFER_METRICS
        m[um.TRANSFER_DOWNLOAD_BYTES].add(self.nbytes)
        m[um.TRANSFER_DOWNLOAD_SECONDS].add(dt)
        # dispatch -> resolve window: the overlapped D2H the Perfetto view
        # shows riding under the remaining compute (streaming collect).
        # Per-batch path: the args dict builds only when tracing is live.
        if _tracing.TRACER.on:
            _tracing.record("transfer.download", "transfer",
                            self._t_dispatch_ns,
                            time.perf_counter_ns() - self._t_dispatch_ns,
                            {"bytes": self.nbytes, "rows": self._num_rows,
                             "resolve_ms": round(dt * 1e3, 3)})
        return fetched_to_arrow(self._schema, fetched, self._num_rows)


def start_download(batch: DeviceBatch) -> PendingDownload:
    return PendingDownload(batch)
