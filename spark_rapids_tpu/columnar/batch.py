"""Device batch and host<->device movement.

Reference analogs:
- ``ColumnarBatch`` of GpuColumnVectors (GpuColumnVector.java:40 area);
- ``GpuColumnarBatchBuilder`` (GpuColumnVector.java:41) which builds on host then
  uploads — here ``DeviceBatch.from_arrow`` stages through numpy and uploads once;
- ``HostColumnarToGpu.scala:222`` (host ColumnarBatch -> device) and
  ``GpuColumnarToRowExec.scala:35`` (device -> host rows) — ``to_arrow`` is the
  download path.

A DeviceBatch is columns padded to a common *capacity* (power-of-two bucket) with a
host-side ``num_rows``; padding rows are invalid. Static shapes are what lets XLA
reuse one compiled program per (schema, capacity) instead of recompiling per batch.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.column import DeviceColumn, null_column
from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema, bucket_capacity
from spark_rapids_tpu.utils import metrics as um

DEFAULT_STRING_MAX_BYTES = 256


@dataclass(frozen=True)
class DeviceBatch:
    schema: Schema
    columns: Tuple[DeviceColumn, ...]
    num_rows: int

    def __post_init__(self):
        caps = {c.capacity for c in self.columns}
        if len(caps) > 1:
            raise ValueError(f"mixed capacities in batch: {caps}")

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else bucket_capacity(self.num_rows)

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def device_size_bytes(self) -> int:
        return sum(c.device_size_bytes for c in self.columns)

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def column_by_name(self, name: str) -> DeviceColumn:
        return self.columns[self.schema.index_of(name)]

    def with_columns(self, schema: Schema, columns: Sequence[DeviceColumn],
                     num_rows: Optional[int] = None) -> "DeviceBatch":
        return DeviceBatch(schema, tuple(columns),
                           self.num_rows if num_rows is None else num_rows)

    # ------------------------------------------------------------------ arrow I/O
    @staticmethod
    def from_arrow(table: pa.Table, string_max_bytes: int = DEFAULT_STRING_MAX_BYTES,
                   bucketed: bool = True, device: Any = None,
                   with_bits: bool = True) -> "DeviceBatch":
        """Host arrow table -> device batch (single upload per buffer).

        Encoded columns never decode on host:

        - pa.DictionaryArray (the parquet page reader keeps the file's own
          dictionary encoding, io/parquet_pages.py) ships as narrow indices
          + the small dictionary and decodes ON DEVICE with a gather; the
          encoded form is RETAINED on the column (DeviceColumn.encoding) so
          downstream operators can work on the index domain.
        - pa.RunEndEncodedArray (RLE-dominant parquet chunks) ships as
          (run_ends, per-run values) and expands in HBM with a searchsorted
          gather (columnar/encoding.expand_ree_device).

        (Host-side re-encoding of plain columns was tried and cut: on the
        1-core bench rig np.unique staging cost exceeds the link saving.)"""
        from spark_rapids_tpu.columnar import encoding as ce
        table = table.combine_chunks()
        schema = Schema.from_pa(table.schema)
        n = table.num_rows
        cap = bucket_capacity(n, bucketed)
        # stage every column on host at its EXACT row count, then ship ONE
        # device_put tree (per-buffer transfers each pay a fixed host-link
        # round trip). Capacity padding and the validity masks of null-free
        # columns are built on device — no reason to move zeros over the link.
        staged = []
        encoded = {}     # column index -> "string" | "fixed" | "ree"
        enc_meta = {}    # column index -> (token, unique) for dict columns
        enc_bytes = 0    # bytes actually staged for the link
        dec_bytes = 0    # bytes the decoded forms would have staged

        def _nb(*arrs) -> int:
            return sum(a.nbytes for a in arrs if a is not None)

        for i, f in enumerate(schema):
            arr = table.column(i).combine_chunks()
            if isinstance(arr, pa.ChunkedArray):
                arr = (arr.chunk(0) if arr.num_chunks == 1
                       else pa.concat_arrays(arr.chunks))
            if (isinstance(arr, pa.Array)
                    and pa.types.is_run_end_encoded(arr.type)):
                ends, vals = ce.ree_staged(arr)
                if len(ends) == 0 or f.dtype is DType.STRING:
                    # empty slice / string REE (never produced by the scan):
                    # host-decode and take the plain path below
                    arr = ce.ree_to_plain(arr)
                else:
                    rvalid = (None if vals.null_count == 0
                              else _arrow_validity(vals))
                    vd, _, _ = _arrow_to_staged(f.dtype, vals,
                                                string_max_bytes)
                    vbits = (vd.view(np.uint64)
                             if f.dtype is DType.DOUBLE and with_bits
                             else None)
                    encoded[i] = "ree"
                    staged.append((ends, rvalid, vd, vbits))
                    enc_bytes += _nb(ends, rvalid, vd, vbits)
                    dec_bytes += (n * vd.dtype.itemsize
                                  + (n * 8 if vbits is not None else 0)
                                  + _nb(rvalid))
                    continue
            if (isinstance(arr, pa.DictionaryArray)
                    and len(arr.dictionary) > 0):
                # device-side decode (GpuParquetScan.scala:576 analog for
                # the dictionary encoding): ship the narrow index vector +
                # the small dictionary, gather on device — 2-8x fewer
                # bytes over the host link than the decoded column.
                # Strings gather their byte-matrix rows + lengths.
                idx = arr.indices
                validity = (None if idx.null_count == 0
                            else _arrow_validity(idx))
                k = len(arr.dictionary)
                np_idx = np.asarray(idx.fill_null(0)).astype(
                    np.uint8 if k <= 0xFF else
                    np.uint16 if k <= 0xFFFF else np.int32)
                if f.dtype is DType.STRING:
                    dmat, dlen = _strings_to_matrix(
                        arr.dictionary.cast(pa.string()), string_max_bytes)
                    encoded[i] = "string"
                    staged.append((np_idx, validity, dmat, dlen))
                    enc_bytes += _nb(np_idx, validity, dmat, dlen)
                    dec_bytes += (n * dmat.shape[1] + n * 4 + _nb(validity))
                    unique = ce.dictionary_is_unique(dmat, dlen)
                else:
                    dd, _, _ = _arrow_to_staged(f.dtype, arr.dictionary,
                                                string_max_bytes)
                    dbits = (dd.view(np.uint64)
                             if f.dtype is DType.DOUBLE and with_bits
                             else None)
                    encoded[i] = "fixed"
                    staged.append((np_idx, validity, dd, dbits))
                    enc_bytes += _nb(np_idx, validity, dd, dbits)
                    dec_bytes += (n * dd.dtype.itemsize
                                  + (n * 8 if dbits is not None else 0)
                                  + _nb(validity))
                    unique = ce.dictionary_is_unique(dd)
                enc_meta[i] = (ce.field_token(table.schema, i), unique)
                continue
            if isinstance(arr, pa.DictionaryArray):
                arr = arr.cast(arr.type.value_type)   # empty dict
            d, v, l = _arrow_to_staged(f.dtype, arr, string_max_bytes)
            # DOUBLE columns also ship their IEEE bit pattern: device f64
            # STORAGE is true 64-bit but no device op can extract its bits
            # (f64->u64 bitcast does not lower; arithmetic is ~49-bit), so
            # the shuffle kernel's byte packing needs the host-made sibling.
            # with_bits=False skips it for consumers that never reach that
            # kernel (mesh-sharded scans: exchange is an all_to_all)
            bits = (d.view(np.uint64)
                    if f.dtype is DType.DOUBLE and with_bits else None)
            staged.append((d, v, l, bits))
            plain = _nb(d, v, l, bits)
            enc_bytes += plain
            dec_bytes += plain
        m = um.TRANSFER_METRICS
        m[um.TRANSFER_ENCODED_BYTES].add(enc_bytes)
        m[um.TRANSFER_DECODED_EQUIV_BYTES].add(dec_bytes)
        up = (jax.device_put(staged, device) if device is not None
              else jax.device_put(staged))
        # shared all-valid mask, on the same device as the data
        alive = jnp.arange(cap, dtype=jnp.int32) < n
        if device is not None:
            alive = jax.device_put(alive, device)
        pad = cap - n
        cols = []
        for i, (f, slot) in enumerate(zip(schema, up)):
            enc = None
            if encoded.get(i) == "ree":
                # HBM expansion of the RLE runs: searchsorted over the run
                # ends picks each row's run, one gather per buffer. The
                # decoded column exists ONLY on device.
                ends, rv, vd, vbits = slot
                d, ridx = ce.expand_ree_device(jnp, ends, vd, cap)
                bits = (jnp.take(vbits, ridx, axis=0)
                        if vbits is not None else None)
                l = None
                v = (jnp.logical_and(jnp.take(rv, ridx, axis=0), alive)
                     if rv is not None else None)
            elif i in encoded:
                # padded gather: index padding rows point at dict slot 0;
                # their garbage values land beyond the live prefix
                idx, v, dd, extra = slot
                idx32 = idx.astype(jnp.int32)
                if pad:
                    idx32 = jnp.concatenate(
                        [idx32, jnp.zeros(pad, jnp.int32)], axis=0)
                d = jnp.take(dd, idx32, axis=0)
                if encoded[i] == "string":
                    l = jnp.take(extra, idx32, axis=0)
                    bits = None
                    enc_lengths = extra
                else:
                    bits = (jnp.take(extra, idx32, axis=0)
                            if extra is not None else None)
                    l = None
                    enc_lengths = None
                token, unique = enc_meta[i]
                if unique:
                    # the retained encoding pads its dictionary to a
                    # power-of-two bucket ON DEVICE (zero link bytes): the
                    # padded size is the jit-key shape, so per-row-group
                    # dictionary growth doesn't recompile encoded-domain
                    # programs
                    k_real = int(dd.shape[0])
                    dpad = ce.dict_bucket(k_real) - k_real
                    dd_enc, len_enc = dd, enc_lengths
                    if dpad:
                        dd_enc = jnp.concatenate(
                            [dd, jnp.zeros((dpad,) + dd.shape[1:],
                                           dd.dtype)], axis=0)
                        if enc_lengths is not None:
                            len_enc = jnp.concatenate(
                                [enc_lengths,
                                 jnp.zeros(dpad, enc_lengths.dtype)], axis=0)
                    enc = ce.DictEncoding(idx32, dd_enc, k_real, len_enc,
                                          token)
            else:
                d, v, l, bits = slot
                if pad:
                    d = jnp.concatenate(
                        [d, jnp.zeros((pad,) + d.shape[1:], d.dtype)],
                        axis=0)
                    if l is not None:
                        l = jnp.concatenate([l, jnp.zeros(pad, l.dtype)],
                                            axis=0)
                    if bits is not None:
                        bits = jnp.concatenate(
                            [bits, jnp.zeros(pad, bits.dtype)], axis=0)
            if v is not None:
                validity = (jnp.concatenate([v, jnp.zeros(pad, jnp.bool_)])
                            if pad and v.shape[0] != cap else v)
            else:
                validity = alive
            cols.append(DeviceColumn(f.dtype, d, validity, l, bits,
                                     encoding=enc))
        return DeviceBatch(schema, tuple(cols), n)

    def sliced_buffers(self) -> List[Tuple]:
        """Device-side (data, validity, lengths_or_None) slices of the live
        rows, ready to download: slicing happens ON DEVICE so only live rows
        cross the host link. The streaming-collect path uses this to start
        asynchronous per-batch downloads (columnar/transfer.py)."""
        n = self.num_rows
        sliced = []
        for col in self.columns:
            # DOUBLE columns with a bit sibling download the BITS: a device
            # u64->f64 bitcast rounds to the emulated ~49-bit arithmetic
            # precision, so the bits are the lossless representation
            data = col.bits if col.bits is not None else col.data
            sliced.append((data[:n], col.validity[:n],
                           col.lengths[:n] if col.lengths is not None else None))
        return sliced

    def to_arrow(self) -> pa.Table:
        """Download to a host arrow table (GpuColumnarToRow analog). All
        column buffers are sliced to the live rows on device and fetched in a
        single device_get so transfers overlap instead of paying one
        host-link round trip per buffer."""
        fetched = jax.device_get(self.sliced_buffers())
        return fetched_to_arrow(self.schema, fetched, self.num_rows)

    # ------------------------------------------------------------------ helpers
    @staticmethod
    def empty(schema: Schema, string_max_bytes: int = DEFAULT_STRING_MAX_BYTES,
              capacity: int = 0) -> "DeviceBatch":
        cap = max(capacity, 1)
        cols = tuple(null_column(f.dtype, cap, string_max_bytes) for f in schema)
        return DeviceBatch(schema, cols, 0)


def fetched_to_arrow(schema: Schema, fetched, num_rows: int) -> pa.Table:
    """Host buffers (one (data, validity, lengths) triple per column, as laid
    out by ``DeviceBatch.sliced_buffers``) -> arrow table."""
    arrays: List[pa.Array] = []
    for f, (data, validity, lengths) in zip(schema, fetched):
        data = np.asarray(data)
        if f.dtype is DType.DOUBLE and data.dtype == np.uint64:
            data = data.view(np.float64)
        arrays.append(_numpy_to_arrow(f.dtype, data,
                                      np.asarray(validity),
                                      None if lengths is None
                                      else np.asarray(lengths), num_rows))
    return pa.Table.from_arrays(arrays, schema=schema.to_pa())


def _arrow_to_staged(dtype: DType, arr: pa.Array, string_max_bytes: int):
    """Arrow column -> exact-size host (data, validity_or_None, lengths).
    validity is None when the column has no nulls (device builds the mask)."""
    validity = None if arr.null_count == 0 else _arrow_validity(arr)
    if dtype is DType.STRING:
        sarr = arr.cast(pa.string()) if not pa.types.is_string(arr.type) else arr
        mat, lengths = _strings_to_matrix(sarr, string_max_bytes)
        return mat, validity, lengths
    if dtype is DType.TIMESTAMP:
        np_data = np.asarray(arr.cast(pa.int64()).fill_null(0))
    elif dtype is DType.DATE:
        np_data = np.asarray(arr.cast(pa.int32()).fill_null(0))
    elif dtype is DType.BOOLEAN:
        np_data = np.asarray(arr.fill_null(False))
    else:
        np_data = np.asarray(arr.fill_null(0))
    return np_data.astype(dtype.np_dtype(), copy=False), validity, None


def _arrow_validity(arr: pa.Array) -> np.ndarray:
    if arr.null_count == 0:
        return np.ones(len(arr), dtype=np.bool_)
    import pyarrow.compute as pc
    return np.asarray(pc.is_valid(arr))


def string_width_bucket(max_len: int, cap: int) -> int:
    """Per-column device string width: the power-of-two bucket covering the
    longest value, clamped to the session cap. Narrow columns (flags, codes)
    then cost a fraction of the cap in staging, transfer, and device compute;
    binary kernels align mixed widths on the fly (ops/strings.align_widths)."""
    w = 8
    while w < max_len:
        w *= 2
    return min(w, cap)


def _strings_to_matrix(arr: pa.StringArray, max_bytes: int) -> Tuple[np.ndarray, np.ndarray]:
    """Arrow (offsets, bytes) -> fixed-width byte matrix + lengths, at the
    column's adaptive width bucket.

    Vectorized: the concatenated UTF-8 payload is row-major in arrow, so a boolean
    ragged mask scatters it into the matrix in one numpy op.
    """
    n = len(arr)
    if n == 0:
        return np.zeros((0, string_width_bucket(0, max_bytes)), np.uint8),             np.zeros(0, np.int32)
    arr = arr.fill_null("")
    offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                            count=n + 1, offset=arr.offset * 4)
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if lengths.max(initial=0) > max_bytes:
        raise ValueError(
            f"string of {lengths.max()} bytes exceeds device string width {max_bytes} "
            f"(spark.rapids.tpu.sql.string.maxBytes)")
    width = string_width_bucket(int(lengths.max(initial=0)), max_bytes)
    data_buf = arr.buffers()[2]
    payload = (np.frombuffer(data_buf, dtype=np.uint8,
                             count=int(offsets[-1]) - int(offsets[0]),
                             offset=int(offsets[0]))
               if data_buf is not None else np.zeros(0, np.uint8))
    mat = np.zeros((n, width), dtype=np.uint8)
    mask = np.arange(width, dtype=np.int32)[None, :] < lengths[:, None]
    mat[mask] = payload
    return mat, lengths


def _device_to_arrow(dtype: DType, col: DeviceColumn, num_rows: int) -> pa.Array:
    data, validity, lengths = col.to_numpy(num_rows)
    return _numpy_to_arrow(dtype, data, validity, lengths, num_rows)


def _numpy_to_arrow(dtype: DType, data: np.ndarray, validity: np.ndarray,
                    lengths: Optional[np.ndarray], num_rows: int) -> pa.Array:
    mask = ~validity  # arrow mask semantics: True = null
    if dtype is DType.STRING:
        sel = np.arange(int(lengths.max()) if num_rows else 0)[None, :] < lengths[:, None]
        payload = data[:, :sel.shape[1]][sel] if num_rows else np.zeros(0, np.uint8)
        offsets = np.zeros(num_rows + 1, dtype=np.int32)
        np.cumsum(lengths, out=offsets[1:])
        return pa.StringArray.from_buffers(
            num_rows,
            pa.py_buffer(offsets.tobytes()),
            pa.py_buffer(payload.tobytes()),
            pa.py_buffer(np.packbits(validity, bitorder="little").tobytes()),
            int(mask.sum()))
    null_count = int(mask.sum())
    validity_buf = (None if null_count == 0
                    else pa.py_buffer(np.packbits(validity, bitorder="little").tobytes()))
    if dtype is DType.BOOLEAN:
        data_buf = pa.py_buffer(np.packbits(data, bitorder="little").tobytes())
    else:
        data_buf = pa.py_buffer(np.ascontiguousarray(data).tobytes())
    storage_type = {DType.TIMESTAMP: pa.int64(), DType.DATE: pa.int32()}.get(
        dtype, dtype.pa_type())
    out = pa.Array.from_buffers(storage_type, num_rows, [validity_buf, data_buf],
                                null_count)
    return out.cast(dtype.pa_type()) if storage_type != dtype.pa_type() else out
