"""SQL type system and its mapping onto device (jax) and host (pyarrow) types.

Covers the v0-supported type set of the reference (GpuOverrides.isSupportedType,
GpuOverrides.scala:389 — boolean, byte, short, int, long, float, double, string, date,
timestamp; no decimal/array/map/struct/calendar in v0). Dates are int32 days since
epoch, timestamps int64 microseconds since epoch UTC, matching Spark's Catalyst
physical representation.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa


class DType(enum.Enum):
    BOOLEAN = "boolean"
    BYTE = "byte"
    SHORT = "short"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"
    DATE = "date"
    TIMESTAMP = "timestamp"
    NULL = "null"

    # ---- classification ---------------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_integral(self) -> bool:
        return self in _INTEGRAL

    @property
    def is_floating(self) -> bool:
        return self in (DType.FLOAT, DType.DOUBLE)

    @property
    def is_string(self) -> bool:
        return self is DType.STRING

    @property
    def is_datetime(self) -> bool:
        return self in (DType.DATE, DType.TIMESTAMP)

    # ---- device representation ---------------------------------------------------
    def np_dtype(self) -> np.dtype:
        """Numpy/jax element dtype of the device data buffer."""
        return _NP[self]

    def element_size(self) -> int:
        if self is DType.STRING:
            raise ValueError("string has no fixed element size; see DeviceColumn")
        return np.dtype(_NP[self]).itemsize

    # ---- host (arrow) representation ---------------------------------------------
    def pa_type(self) -> pa.DataType:
        return _PA[self]

    @staticmethod
    def from_pa(t: pa.DataType) -> "DType":
        for dt, pat in _PA.items():
            if pat.equals(t):
                return dt
        if pa.types.is_large_string(t):
            return DType.STRING
        if pa.types.is_timestamp(t):
            return DType.TIMESTAMP
        if pa.types.is_dictionary(t):
            # dictionary-encoded column: the logical type is the value type
            # (the encoding is an upload/transport detail, decoded on device)
            return DType.from_pa(t.value_type)
        if pa.types.is_run_end_encoded(t):
            # run-end-encoded column (RLE-dominant parquet chunks): ships as
            # (run_ends, values) and expands in HBM (columnar/encoding.py)
            return DType.from_pa(t.value_type)
        raise TypeError(f"unsupported arrow type {t} (reference also gates types at "
                        f"GpuOverrides.isSupportedType)")

    @staticmethod
    def common_numeric(a: "DType", b: "DType") -> "DType":
        """Numeric widening like Catalyst's binary-op type coercion."""
        order = [DType.BYTE, DType.SHORT, DType.INT, DType.LONG, DType.FLOAT, DType.DOUBLE]
        if a not in order or b not in order:
            raise TypeError(f"no common numeric type for {a} and {b}")
        return order[max(order.index(a), order.index(b))]

    @staticmethod
    def common_type(a: "DType", b: "DType") -> "DType":
        """Catalyst-style least common type for multi-branch expressions
        (coalesce/if/case-when/least/greatest): NULL yields the other side,
        equal types pass through, numerics widen; anything else is an error."""
        if a == b:
            return a
        if a is DType.NULL:
            return b
        if b is DType.NULL:
            return a
        return DType.common_numeric(a, b)

    @staticmethod
    def common_type_all(dtypes: Sequence["DType"]) -> "DType":
        out = dtypes[0]
        for dt in dtypes[1:]:
            out = DType.common_type(out, dt)
        return out


_NUMERIC = {DType.BYTE, DType.SHORT, DType.INT, DType.LONG, DType.FLOAT, DType.DOUBLE}
_INTEGRAL = {DType.BYTE, DType.SHORT, DType.INT, DType.LONG}

_NP = {
    DType.BOOLEAN: np.dtype(np.bool_),
    DType.BYTE: np.dtype(np.int8),
    DType.SHORT: np.dtype(np.int16),
    DType.INT: np.dtype(np.int32),
    DType.LONG: np.dtype(np.int64),
    DType.FLOAT: np.dtype(np.float32),
    DType.DOUBLE: np.dtype(np.float64),
    DType.STRING: np.dtype(np.uint8),   # byte-matrix payload
    DType.DATE: np.dtype(np.int32),     # days since epoch
    DType.TIMESTAMP: np.dtype(np.int64),  # microseconds since epoch UTC
    DType.NULL: np.dtype(np.int8),
}

_PA = {
    DType.BOOLEAN: pa.bool_(),
    DType.BYTE: pa.int8(),
    DType.SHORT: pa.int16(),
    DType.INT: pa.int32(),
    DType.LONG: pa.int64(),
    DType.FLOAT: pa.float32(),
    DType.DOUBLE: pa.float64(),
    DType.STRING: pa.string(),
    DType.DATE: pa.date32(),
    DType.TIMESTAMP: pa.timestamp("us", tz="UTC"),
    DType.NULL: pa.null(),
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True

    def __repr__(self) -> str:
        return f"{self.name}:{self.dtype.value}{'' if self.nullable else '!'}"


class Schema:
    """Ordered, name-addressable field list (StructType analog)."""

    def __init__(self, fields: Sequence[Field]):
        self.fields: Tuple[Field, ...] = tuple(fields)
        self._index = {f.name: i for i, f in enumerate(self.fields)}
        if len(self._index) != len(self.fields):
            raise ValueError(f"duplicate field names in {self.fields}")

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(self.fields)

    def index_of(self, name: str) -> int:
        if name not in self._index:
            raise KeyError(f"no field {name!r} in {self}")
        return self._index[name]

    def field(self, name: str) -> Field:
        return self.fields[self.index_of(name)]

    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def to_pa(self) -> pa.Schema:
        return pa.schema([pa.field(f.name, f.dtype.pa_type(), f.nullable)
                          for f in self.fields])

    @staticmethod
    def from_pa(s: pa.Schema) -> "Schema":
        return Schema([Field(f.name, DType.from_pa(f.type), f.nullable) for f in s])

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(repr(f) for f in self.fields) + ")"


def bucket_capacity(num_rows: int, bucketed: bool = True, minimum: int = 128) -> int:
    """Row capacity for a device batch.

    Power-of-two bucketing keeps the set of distinct array shapes small so XLA
    compilation caches hit across batches — the TPU replacement for cuDF's
    exact-sized device buffers (recompiling per batch size would dominate runtime).
    """
    if not bucketed:
        return max(num_rows, 1)
    cap = minimum
    while cap < num_rows:
        cap <<= 1
    return cap


#: nominal bytes per row per dtype, for size-estimate scaling (the Spark
#: sizeInBytes convention; STRING uses a flat 20 B — the estimate feeds
#: broadcast selection and out-of-core footprints, not allocation)
_DTYPE_WIDTH = {DType.BOOLEAN: 1, DType.BYTE: 1, DType.SHORT: 2,
                DType.INT: 4, DType.FLOAT: 4, DType.DATE: 4, DType.LONG: 8,
                DType.DOUBLE: 8, DType.TIMESTAMP: 8, DType.STRING: 20,
                DType.NULL: 1}


def row_width(schema: "Schema") -> int:
    """Nominal bytes per row for size-estimate scaling."""
    return sum(_DTYPE_WIDTH.get(f.dtype, 8) for f in schema)


def width_scaled_estimate(child, out_schema: "Schema"):
    """Child exec's size estimate scaled by the output/input row-width
    ratio (width-changing operators: projections, windows,
    aggregates-as-upper-bound); None propagates."""
    child_sz = child.size_estimate()
    if child_sz is None:
        return None
    in_w = row_width(child.output)
    return int(child_sz * row_width(out_schema) / max(in_w, 1))


def limit_size_estimate(child, out_schema: "Schema", n: int):
    """min(n rows at nominal width, child upper bound); None-tolerant."""
    cap = n * row_width(out_schema)
    child_sz = child.size_estimate()
    return cap if child_sz is None else min(cap, child_sz)


def union_size_estimate(children):
    """Sum of the children's estimates; None if any child is unknown."""
    sizes = [c.size_estimate() for c in children]
    return None if any(s is None for s in sizes) else sum(sizes)


def expand_size_estimate(child, num_projections: int):
    """Every input row emits one row per projection list; None propagates."""
    child_sz = child.size_estimate()
    return None if child_sz is None else child_sz * num_projections
