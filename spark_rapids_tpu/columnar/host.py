"""Host-side batch in the same columnar layout as DeviceBatch, backed by numpy.

Used by the CPU engine (fallback execution + compare-testing oracle) and as the
staging representation for spill/shuffle serialization — the analog of
RapidsHostColumnVector in the reference.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.columnar.batch import (_arrow_validity, _device_to_arrow,
                                             _strings_to_matrix)
from spark_rapids_tpu.columnar.dtypes import DType, Schema


@dataclass(frozen=True)
class HostColumn:
    dtype: DType
    data: np.ndarray
    validity: np.ndarray
    lengths: Optional[np.ndarray] = None

    def to_numpy(self, num_rows: int):
        return (self.data[:num_rows], self.validity[:num_rows],
                self.lengths[:num_rows] if self.lengths is not None else None)

    @property
    def nbytes(self) -> int:
        total = self.data.nbytes + self.validity.nbytes
        if self.lengths is not None:
            total += self.lengths.nbytes
        return total


@dataclass(frozen=True)
class HostBatch:
    schema: Schema
    columns: Tuple[HostColumn, ...]
    num_rows: int

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.columns)

    @staticmethod
    def from_arrow(table: pa.Table, string_max_bytes: int = 256) -> "HostBatch":
        table = table.combine_chunks()
        schema = Schema.from_pa(table.schema)
        cols: List[HostColumn] = []
        for i, f in enumerate(schema):
            arr = table.column(i)
            if isinstance(arr, pa.ChunkedArray):
                arr = (arr.chunk(0) if arr.num_chunks == 1
                       else pa.concat_arrays(arr.chunks))
            if isinstance(arr, pa.DictionaryArray):
                # host layout has no dictionary form; device-side dict
                # decode is DeviceBatch.from_arrow's job
                arr = arr.cast(arr.type.value_type)
            if pa.types.is_run_end_encoded(arr.type):
                # host layout has no run-length form either; device-side
                # expansion is DeviceBatch.from_arrow's job
                from spark_rapids_tpu.columnar.encoding import ree_to_plain
                arr = ree_to_plain(arr)
            validity = _arrow_validity(arr)
            if f.dtype is DType.STRING:
                mat, lengths = _strings_to_matrix(arr, string_max_bytes)
                cols.append(HostColumn(f.dtype, mat, validity, lengths))
                continue
            if f.dtype is DType.TIMESTAMP:
                data = np.asarray(arr.cast(pa.int64()).fill_null(0))
            elif f.dtype is DType.DATE:
                data = np.asarray(arr.cast(pa.int32()).fill_null(0))
            elif f.dtype is DType.BOOLEAN:
                data = np.asarray(arr.fill_null(False))
            else:
                data = np.asarray(arr.fill_null(0))
            cols.append(HostColumn(f.dtype, data.astype(f.dtype.np_dtype(),
                                                        copy=False), validity))
        return HostBatch(schema, tuple(cols), table.num_rows)

    def to_arrow(self) -> pa.Table:
        arrays = [_device_to_arrow(f.dtype, c, self.num_rows)
                  for f, c in zip(self.schema, self.columns)]
        return pa.Table.from_arrays(arrays, schema=self.schema.to_pa())
