"""Device column: the TPU-side equivalent of GpuColumnVector.

Reference analog: GpuColumnVector.java:40 wraps an ``ai.rapids.cudf.ColumnVector``
(device buffer + Arrow-style validity bitmask + string offsets). The TPU layout is
re-designed for XLA:

- every buffer is a jax.Array with a *static, bucketed* shape (see
  dtypes.bucket_capacity) so compiled programs are reused across batches;
- validity is a ``bool[capacity]`` vector, not a bitmask — the VPU is fine with
  byte masks and XLA fuses mask math into consumers;
- strings are a ``uint8[capacity, max_bytes]`` matrix plus an ``int32[capacity]``
  length vector (fixed-width layout): substring/upper/concat/compare become plain
  vectorized array ops on the MXU/VPU instead of offset-chasing kernels;
- rows at index >= num_rows (padding) always have validity False, length 0 and
  zeroed data, so reductions can run over the full capacity unconditionally.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.dtypes import DType


@dataclass(frozen=True)
class DeviceColumn:
    """One column of a device batch. Immutable (functional updates only)."""

    dtype: DType
    data: jax.Array                  # [capacity] or [capacity, max_bytes] for strings
    validity: jax.Array              # bool[capacity]
    lengths: Optional[jax.Array] = None  # int32[capacity], strings only
    #: DOUBLE columns only: the IEEE-754 bit pattern as uint64, kept from
    #: upload time. The X64-rewritten backend cannot bitcast f64->u64 (only
    #: u64->f64), so the accelerated shuffle's byte packing needs the bits
    #: carried alongside; device-computed doubles instead ride an exact
    #: three-float32 expansion (shuffle/partition_kernel.py).
    bits: Optional[jax.Array] = None
    #: columns that arrived dictionary-encoded keep their narrow index
    #: vector + small dictionary on device (columnar/encoding.DictEncoding)
    #: so filters/group-by/join keys can run on the index domain instead of
    #: the decoded values (exprs/encoded.py); invariant:
    #: data == take(encoding.values, encoding.indices) row-wise. Kernels
    #: that rebuild columns drop it (their output is no longer the gather).
    encoding: Optional["DictEncoding"] = None  # noqa: F821

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def max_bytes(self) -> int:
        if self.dtype is not DType.STRING:
            raise ValueError("max_bytes only defined for string columns")
        return int(self.data.shape[1])

    @property
    def device_size_bytes(self) -> int:
        total = self.data.size * self.data.dtype.itemsize
        total += self.validity.size
        if self.lengths is not None:
            total += self.lengths.size * 4
        if self.bits is not None:
            total += self.bits.size * 8
        return total

    def __post_init__(self):
        if self.dtype is DType.STRING and self.lengths is None:
            raise ValueError("string column requires lengths vector")

    # ---------------------------------------------------------------------------
    @staticmethod
    def from_numpy(dtype: DType, data: np.ndarray, validity: Optional[np.ndarray],
                   capacity: int, max_bytes: int = 0,
                   lengths: Optional[np.ndarray] = None,
                   device: Any = None) -> "DeviceColumn":
        """Pad host buffers to ``capacity`` and upload. Padding rows are invalid/zero."""
        staged = DeviceColumn.stage_numpy(dtype, data, validity, capacity,
                                          max_bytes, lengths)
        put = (lambda x: jax.device_put(x, device)) if device is not None \
            else jax.device_put
        return DeviceColumn(dtype, *[put(a) if a is not None else None
                                     for a in staged])

    @staticmethod
    def stage_numpy(dtype: DType, data: np.ndarray,
                    validity: Optional[np.ndarray], capacity: int,
                    max_bytes: int = 0, lengths: Optional[np.ndarray] = None):
        """Capacity-padded host buffers ready for upload — split out so batch
        builders can stage every column first and ship ONE device_put tree
        (per-array transfers pay a fixed host-link round trip each)."""
        n = data.shape[0]
        if n > capacity:
            raise ValueError(f"{n} rows > capacity {capacity}")
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
        vals = np.zeros(capacity, dtype=np.bool_)
        vals[:n] = validity
        if dtype is DType.STRING:
            assert lengths is not None
            mat = np.zeros((capacity, max_bytes), dtype=np.uint8)
            mat[:n, :data.shape[1]] = data
            lens = np.zeros(capacity, dtype=np.int32)
            lens[:n] = lengths
            return (mat, vals, lens)
        buf = np.zeros(capacity, dtype=dtype.np_dtype())
        buf[:n] = data
        return (buf, vals, None)

    def to_numpy(self, num_rows: int):
        """Download the first ``num_rows`` rows. The slice happens ON DEVICE so
        only the live rows cross the host link — downloading a capacity-sized
        buffer to read 4 result rows is pure waste (and host links can be
        orders of magnitude slower than HBM)."""
        data = np.asarray(self.data[:num_rows])
        validity = np.asarray(self.validity[:num_rows])
        lengths = (np.asarray(self.lengths[:num_rows])
                   if self.lengths is not None else None)
        return data, validity, lengths


def null_column(dtype: DType, capacity: int, max_bytes: int = 0) -> DeviceColumn:
    """All-null column of the given capacity."""
    validity = jnp.zeros(capacity, dtype=jnp.bool_)
    if dtype is DType.STRING:
        data = jnp.zeros((capacity, max_bytes), dtype=jnp.uint8)
        lengths = jnp.zeros(capacity, dtype=jnp.int32)
        return DeviceColumn(dtype, data, validity, lengths)
    data = jnp.zeros(capacity, dtype=dtype.np_dtype())
    return DeviceColumn(dtype, data, validity)
