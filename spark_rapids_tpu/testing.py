"""Result-comparison harness.

Analog of the reference's integration-test comparison machinery:
- ``integration_tests/src/main/python/asserts.py`` ``_assert_equal`` (deep CPU-vs-GPU
  result compare with NaN-equality and approximate floats);
- ``tests/.../SparkQueryCompareTestSuite.scala:655`` ``compareResults`` (sort-before-
  compare, float tolerance knobs).

Used both by unit tests and by the CPU-vs-TPU compare fixtures.
"""
from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence

import numpy as np
import pyarrow as pa


def _normalize(table: pa.Table) -> pa.Table:
    return table.combine_chunks()


def _sort_table(table: pa.Table) -> pa.Table:
    keys = [(name, "ascending") for name in table.column_names]
    return table.sort_by(keys)


def _values_equal(a: Any, b: Any, approx: Optional[float]) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) or math.isnan(b):
            return math.isnan(a) and math.isnan(b)
        if approx is not None:
            if a == b:
                return True
            denom = max(abs(a), abs(b))
            return denom != 0 and abs(a - b) / denom <= approx
        return a == b
    return a == b


def run_with_cpu_and_tpu(build_df, conf: Optional[dict] = None):
    """Run the same DataFrame-producing function against a TPU-enabled session
    and a CPU-only session, returning (cpu_table, tpu_table, tpu_session).

    Analog of SparkQueryCompareTestSuite.runOnCpuAndGpu
    (SparkQueryCompareTestSuite.scala:153,161): the CPU run flips
    spark.rapids.tpu.sql.enabled=false so everything executes on the fallback
    engine; the TPU run must actually place supported execs on the device.
    """
    from spark_rapids_tpu.api.dataframe import TpuSession
    base = dict(conf or {})
    cpu_sess = TpuSession({**base, "spark.rapids.tpu.sql.enabled": "false"})
    tpu_sess = TpuSession({**base, "spark.rapids.tpu.sql.enabled": "true"})
    cpu = build_df(cpu_sess).collect()
    tpu = build_df(tpu_sess).collect()
    return cpu, tpu, tpu_sess


def assert_tpu_and_cpu_equal(build_df, conf: Optional[dict] = None,
                             ignore_order: bool = False,
                             approx_float: Optional[float] = None,
                             expect_tpu_execs: Optional[Sequence[str]] = None):
    """testSparkResultsAreEqual analog: identical results CPU vs TPU, plus an
    optional assertion that named execs really ran on the device (the
    ExecutionPlanCaptureCallback role, Plugin.scala:180-270)."""
    cpu, tpu, sess = run_with_cpu_and_tpu(build_df, conf)
    assert_tables_equal(cpu, tpu, ignore_order=ignore_order,
                        approx_float=approx_float)
    if expect_tpu_execs:
        plan_str = sess.last_plan.tree_string() if sess.last_plan else ""
        for name in expect_tpu_execs:
            assert name in plan_str, (
                f"expected {name} on the TPU plan, got:\n{plan_str}\n"
                f"explain:\n{sess.last_explain}")
    return cpu


def assert_tables_equal(expected: pa.Table, actual: pa.Table,
                        ignore_order: bool = False,
                        approx_float: Optional[float] = None) -> None:
    """Deep-compare two arrow tables, NaN == NaN, optional unordered/approx modes."""
    expected = _normalize(expected)
    actual = _normalize(actual)
    assert expected.schema.equals(actual.schema), (
        f"schema mismatch:\nexpected {expected.schema}\nactual   {actual.schema}")
    assert expected.num_rows == actual.num_rows, (
        f"row count mismatch: expected {expected.num_rows}, actual {actual.num_rows}")
    if ignore_order and expected.num_rows > 1:
        # NaN-safe unordered compare: sorting with NaN/null works in arrow
        # (nulls last, NaN after numbers), so sorted tables line up row-wise.
        expected = _sort_table(expected)
        actual = _sort_table(actual)
    for name in expected.column_names:
        ecol = expected.column(name).to_pylist()
        acol = actual.column(name).to_pylist()
        for i, (e, a) in enumerate(zip(ecol, acol)):
            assert _values_equal(e, a, approx_float), (
                f"column {name!r} row {i}: expected {e!r}, actual {a!r}")
