"""Recursive-descent SQL parser with Pratt expression parsing.

Covers the surface the reference exercises through Catalyst for its benchmark
SQL (TpcdsLikeSpark.scala query texts): SELECT lists with aliases and
aggregates, FROM with comma joins / JOIN..ON / derived tables, WHERE with
AND/OR/NOT/BETWEEN/IN/LIKE/IS NULL, EXISTS / IN / scalar subqueries,
GROUP BY / HAVING / ORDER BY / LIMIT, CASE WHEN, EXTRACT, CAST, date and
interval literals with constant folding at plan time.
"""
from __future__ import annotations

import datetime
from typing import List, Optional, Tuple

from spark_rapids_tpu.sql import ast as A
from spark_rapids_tpu.sql.lexer import SqlError, Token, tokenize

# binding powers (higher binds tighter)
_BP = {"or": 10, "and": 20,
       "=": 40, "<>": 40, "!=": 40, "<": 40, "<=": 40, ">": 40, ">=": 40,
       "||": 45,
       "+": 50, "-": 50,
       "*": 60, "/": 60, "%": 60}

_AGG_FUNCS = {"sum", "avg", "count", "min", "max", "stddev", "stddev_pop",
              "variance", "var_pop", "first", "last"}


class Parser:
    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.i = 0

    # ---- token plumbing ----------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "KEYWORD" and t.value in words

    def eat_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            raise SqlError(f"expected {word.upper()}, got "
                           f"{self.peek().value!r} at {self.peek().pos}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise SqlError(f"expected {op!r}, got {self.peek().value!r} "
                           f"at {self.peek().pos}")

    # ---- statement ---------------------------------------------------------
    def parse_query(self) -> A.Node:
        """SELECT ((UNION [ALL] | INTERSECT | EXCEPT) SELECT)* — the
        set-op chain derived tables and CTE bodies accept
        (TpcdsLikeSpark's multi-channel unions; q14/q38/q87-style
        INTERSECT/EXCEPT). Chains fold LEFT uniformly (standard SQL gives
        INTERSECT higher precedence than UNION/EXCEPT — parenthesize
        mixed chains that rely on it)."""
        q: A.Node = self.parse_select()
        ops_seen = set()
        while self.at_kw("union", "intersect", "except"):
            kw = self.next().value.lower()
            if kw == "union":
                all_ = self.eat_kw("all")
                op = "union_all" if all_ else "union"
            else:
                op = kw
            ops_seen.add("intersect" if op == "intersect" else "other")
            if len(ops_seen) > 1:
                # left-folding would silently violate INTERSECT's higher
                # standard-SQL precedence: refuse rather than misparse
                raise SqlError(
                    "mixing INTERSECT with UNION/EXCEPT in one chain is "
                    "ambiguous here (INTERSECT binds tighter in SQL); "
                    "parenthesize via derived tables")
            r = self.parse_select()
            q = A.SetOp(op, q, r)
        return q

    def parse_select(self) -> A.Select:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        self.eat_kw("all")
        items: List[A.SelectItem] = []
        select_star = False
        if self.at_op("*"):
            self.next()
            select_star = True
        else:
            while True:
                e = self.expr()
                alias = None
                if self.eat_kw("as"):
                    alias = self._ident()
                elif self.peek().kind == "IDENT":
                    alias = self._ident()
                items.append(A.SelectItem(e, alias))
                if not self.eat_op(","):
                    break
        relations: List[A.Node] = []
        if self.eat_kw("from"):
            relations.append(self._relation())
            while True:
                if self.eat_op(","):
                    relations.append(self._relation())
                    continue
                how = self._join_kind()
                if how is None:
                    break
                rel = self._relation()
                cond = self.expr() if self.eat_kw("on") else None
                relations.append(A.JoinItem(how, rel, cond))
        where = self.expr() if self.eat_kw("where") else None
        group_by: List[A.Node] = []
        group_mode = "groupby"
        if self.eat_kw("group"):
            self.expect_kw("by")
            if self.at_kw("rollup", "cube"):
                group_mode = self.next().value
                self.expect_op("(")
                group_by.extend(self._expr_list())
                self.expect_op(")")
            else:
                group_by.extend(self._expr_list())
        having = self.expr() if self.eat_kw("having") else None
        order_by: List[A.OrderItem] = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by = self._order_items()
        limit = None
        if self.eat_kw("limit"):
            t = self.next()
            if t.kind != "NUMBER":
                raise SqlError(f"expected LIMIT count at {t.pos}")
            limit = int(t.value)
        return A.Select(tuple(items), tuple(relations), where,
                        tuple(group_by), having, tuple(order_by), limit,
                        distinct, select_star, group_mode)

    def _window_spec(self) -> A.WindowSpecNode:
        """OVER ( [PARTITION BY e,...] [ORDER BY e [ASC|DESC],...]
        [ROWS|RANGE [BETWEEN bound AND bound | bound]] )"""
        self.expect_kw("over")
        self.expect_op("(")
        part: List[A.Node] = []
        if self.eat_kw("partition"):
            self.expect_kw("by")
            part = self._expr_list()
        orders: List[A.OrderItem] = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            orders = self._order_items()
        ftype = None
        lower = upper = None
        if self.at_kw("rows", "range"):
            ftype = self.next().value
            pos = self.peek().pos
            if self.eat_kw("between"):
                lo = self._frame_bound()
                self.expect_kw("and")
                hi = self._frame_bound()
            else:
                lo = self._frame_bound()
                hi = 0          # single-bound form: .. AND CURRENT ROW
                if lo == "ub_fol" or (isinstance(lo, (int, float))
                                      and lo > 0):
                    raise SqlError(
                        f"a single frame bound must be PRECEDING or "
                        f"CURRENT ROW (at {pos})")
            if lo == "ub_fol" or hi == "ub_pre":
                raise SqlError(f"inverted frame direction at {pos}")
            lower = None if lo == "ub_pre" else lo
            upper = None if hi == "ub_fol" else hi
            if isinstance(lower, (int, float)) and \
                    isinstance(upper, (int, float)) and lower > upper:
                raise SqlError(f"frame lower bound exceeds upper at {pos}")
        self.expect_op(")")
        return A.WindowSpecNode(tuple(part), tuple(orders), ftype, lower,
                                upper)

    def _frame_bound(self):
        """'ub_pre'/'ub_fol' for unbounded; 0 = current row; negative =
        preceding, positive = following (floats allowed for RANGE)."""
        if self.eat_kw("unbounded"):
            if self.eat_kw("preceding"):
                return "ub_pre"
            self.expect_kw("following")
            return "ub_fol"
        if self.eat_kw("current"):
            self.expect_kw("row")
            return 0
        t = self.next()
        if t.kind != "NUMBER":
            raise SqlError(f"expected frame bound at {t.pos}")
        n = float(t.value) if "." in t.value else int(t.value)
        if self.eat_kw("preceding"):
            return -n
        self.expect_kw("following")
        return n

    def _join_kind(self) -> Optional[str]:
        if self.at_kw("join"):
            self.next()
            return "inner"
        for lead, how in (("inner", "inner"), ("cross", "cross"),
                          ("left", "left"), ("right", "right"),
                          ("full", "full")):
            if self.at_kw(lead):
                save = self.i
                self.next()
                if lead == "left" and self.at_kw("semi"):
                    self.next()
                    how = "left_semi"
                elif lead == "left" and self.at_kw("anti"):
                    self.next()
                    how = "left_anti"
                else:
                    self.eat_kw("outer")
                if self.eat_kw("join"):
                    return how
                self.i = save
                return None
        return None

    def _relation(self) -> A.Node:
        if self.at_op("("):
            self.next()
            q = self.parse_query()
            self.expect_op(")")
            alias = None
            if self.eat_kw("as"):
                alias = self._ident()
            elif self.peek().kind == "IDENT" and \
                    self.peek().value.lower() != "pivot":
                # 'pivot' is a soft keyword: FROM (subquery) PIVOT (...)
                # carries no derived-table alias (Spark accepts this form)
                alias = self._ident()
            if alias is None:
                alias = "__auto_generated_subquery_name"
            return self._maybe_pivot(A.SubqueryRef(q, alias))
        name = self._ident()
        alias = None
        if self.eat_kw("as"):
            alias = self._ident()
        elif self.peek().kind == "IDENT" and \
                self.peek().value.lower() != "pivot":
            alias = self._ident()
        ref: A.Node = A.TableRef(name, alias)
        return self._maybe_pivot(ref)

    def _maybe_pivot(self, ref: A.Node) -> A.Node:
        """rel PIVOT (agg [AS a][, ...] FOR col IN (lit [AS a], ...))
        [[AS] alias] — 'pivot' stays a soft keyword (usable as an
        identifier everywhere else)."""
        t = self.peek()
        if not (t.kind == "IDENT" and t.value.lower() == "pivot"):
            return ref
        save = self.i
        self.next()
        if not self.at_op("("):
            self.i = save
            return ref
        self.next()
        aggs = []
        while True:
            e = self.expr()
            al = self._ident() if self.eat_kw("as") else None
            aggs.append((e, al))
            if not self.eat_op(","):
                break
        self.expect_kw("for")
        pcol = A.ColRef(self._ident())
        self.expect_kw("in")
        self.expect_op("(")
        values = []
        while True:
            v = self.expr()
            if isinstance(v, A.UnaryOp) and v.op == "neg" \
                    and isinstance(v.child, A.Lit):
                v = A.Lit(-v.child.value)
            if not isinstance(v, A.Lit):
                raise SqlError("PIVOT IN values must be literals")
            val_alias = self._ident() if self.eat_kw("as") else None
            values.append((v.value, val_alias))
            if not self.eat_op(","):
                break
        self.expect_op(")")
        self.expect_op(")")
        alias = None
        if self.eat_kw("as"):
            alias = self._ident()
        elif self.peek().kind == "IDENT":
            alias = self._ident()
        return A.PivotRef(ref, tuple(aggs), pcol, tuple(values), alias)

    def _order_items(self) -> List["A.OrderItem"]:
        """expr [ASC|DESC] [NULLS FIRST|LAST] {, ...} — shared by the
        statement-level ORDER BY and window specs."""
        out: List[A.OrderItem] = []
        while True:
            e = self.expr()
            asc = True
            if self.eat_kw("desc"):
                asc = False
            else:
                self.eat_kw("asc")
            nulls_first = None
            t = self.peek()
            if t.kind == "IDENT" and t.value.lower() == "nulls":
                self.next()
                w = self._ident().lower()
                if w not in ("first", "last"):
                    raise SqlError(
                        f"expected FIRST or LAST after NULLS, got {w!r}")
                nulls_first = (w == "first")
            out.append(A.OrderItem(e, asc, nulls_first))
            if not self.eat_op(","):
                break
        return out

    def _expr_list(self) -> list:
        out = [self.expr()]
        while self.eat_op(","):
            out.append(self.expr())
        return out

    def _ident(self) -> str:
        from spark_rapids_tpu.sql.lexer import SOFT_KEYWORDS
        t = self.next()
        if t.kind == "KEYWORD" and t.value in SOFT_KEYWORDS:
            return t.value  # non-reserved word used as an identifier
        if t.kind != "IDENT":
            raise SqlError(f"expected identifier, got {t.value!r} at {t.pos}")
        return t.value

    # ---- expressions (Pratt) ----------------------------------------------
    def expr(self, min_bp: int = 0) -> A.Node:
        left = self._prefix()
        while True:
            left2 = self._postfix(left, min_bp)
            if left2 is not left:
                left = left2
                continue
            t = self.peek()
            op = None
            if t.kind == "OP" and t.value in _BP:
                op = t.value
            elif t.kind == "KEYWORD" and t.value in ("and", "or"):
                op = t.value
            if op is None or _BP[op] < min_bp:
                return left
            self.next()
            right = self.expr(_BP[op] + 1)
            if op == "!=":
                op = "<>"
            left = A.BinOp(op, left, right)

    #: binding power of the predicate postfixes (BETWEEN/IN/LIKE/IS NULL):
    #: looser than arithmetic/comparison, tighter than NOT/AND
    _POSTFIX_BP = 30

    def _postfix(self, left: A.Node, min_bp: int) -> A.Node:
        """BETWEEN / IN / LIKE / IS [NOT] NULL — bind looser than arithmetic
        (a + 1 BETWEEN x AND y predicates over a + 1), tighter than AND."""
        if min_bp <= self._POSTFIX_BP:
            negated = False
            save = self.i
            if self.at_kw("not"):
                if self.peek(1).kind == "KEYWORD" and \
                        self.peek(1).value in ("between", "in", "like"):
                    self.next()
                    negated = True
                else:
                    return left
            if self.eat_kw("between"):
                low = self.expr(_BP["and"] + 1)
                self.expect_kw("and")
                high = self.expr(_BP["and"] + 1)
                return A.Between(left, low, high, negated)
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self.parse_select()
                    self.expect_op(")")
                    return A.InSubquery(left, q, negated)
                opts = [self.expr()]
                while self.eat_op(","):
                    opts.append(self.expr())
                self.expect_op(")")
                return A.InList(left, tuple(opts), negated)
            if self.eat_kw("like"):
                t = self.next()
                if t.kind != "STRING":
                    raise SqlError(f"LIKE needs a string pattern at {t.pos}")
                return A.LikeOp(left, t.value, negated)
            if self.eat_kw("is"):
                neg = self.eat_kw("not")
                self.expect_kw("null")
                return A.IsNull(left, neg)
            self.i = save
        return left

    def _prefix(self) -> A.Node:
        t = self.peek()
        if t.kind == "OP" and t.value == "(":
            self.next()
            if self.at_kw("select"):
                q = self.parse_select()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "OP" and t.value == "-":
            self.next()
            return A.UnaryOp("neg", self.expr(70))
        if t.kind == "OP" and t.value == "+":
            self.next()
            return self.expr(70)
        if t.kind == "KEYWORD":
            if t.value == "not":
                self.next()
                return A.UnaryOp("not", self.expr(25))
            if t.value == "exists":
                self.next()
                self.expect_op("(")
                q = self.parse_select()
                self.expect_op(")")
                return A.ExistsSubquery(q)
            if t.value == "case":
                return self._case()
            if t.value == "date":
                self.next()
                s = self.next()
                if s.kind != "STRING":
                    raise SqlError(f"DATE needs a string at {s.pos}")
                return A.Lit(datetime.date.fromisoformat(s.value))
            if t.value == "interval":
                self.next()
                s = self.next()
                if s.kind == "STRING":
                    n = int(s.value)
                elif s.kind == "NUMBER":
                    n = int(s.value)
                else:
                    raise SqlError(f"INTERVAL needs a count at {s.pos}")
                unit = self._ident().lower().rstrip("s")
                if unit not in ("day", "month", "year"):
                    raise SqlError(f"unsupported interval unit {unit!r}")
                return A.Interval(n, unit)
            if t.value == "extract":
                self.next()
                self.expect_op("(")
                part = self._ident().lower()
                # FROM here is a keyword separator, not a clause
                self.expect_kw("from")
                v = self.expr()
                self.expect_op(")")
                return A.ExtractExpr(part, v)
            if t.value == "cast":
                self.next()
                self.expect_op("(")
                v = self.expr()
                self.expect_kw("as")
                to = self._type_name()
                self.expect_op(")")
                return A.CastExpr(v, to)
            if t.value == "substring":
                self.next()
                self.expect_op("(")
                v = self.expr()
                if self.eat_kw("from"):
                    start = self.expr()
                    self.expect_kw("for")
                    length = self.expr()
                else:
                    self.expect_op(",")
                    start = self.expr()
                    self.expect_op(",")
                    length = self.expr()
                self.expect_op(")")
                return A.FuncCall("substring", (v, start, length))
            if t.value == "case":
                return self._case()
            if t.value == "null":
                self.next()
                return A.Lit(None)
            if t.value == "true":
                self.next()
                return A.Lit(True)
            if t.value == "false":
                self.next()
                return A.Lit(False)
        if t.kind == "NUMBER":
            self.next()
            return A.Lit(float(t.value) if "." in t.value else int(t.value))
        if t.kind == "STRING":
            self.next()
            return A.Lit(t.value)
        if t.kind == "IDENT":
            self.next()
            name = t.value
            # function call
            if self.at_op("("):
                self.next()
                distinct = self.eat_kw("distinct")
                if self.at_op("*"):
                    self.next()
                    self.expect_op(")")
                    call = A.FuncCall(name.lower(), (), distinct, star=True)
                elif self.at_op(")"):
                    self.next()
                    call = A.FuncCall(name.lower(), (), distinct)
                else:
                    args = [self.expr()]
                    while self.eat_op(","):
                        args.append(self.expr())
                    self.expect_op(")")
                    call = A.FuncCall(name.lower(), tuple(args), distinct)
                if self.at_kw("over"):
                    return A.WindowFuncCall(call, self._window_spec())
                return call
            # qualified column a.b
            if self.at_op(".") and self.peek(1).kind == "IDENT":
                self.next()
                col = self._ident()
                return A.ColRef(col, qualifier=name)
            return A.ColRef(name)
        from spark_rapids_tpu.sql.lexer import SOFT_KEYWORDS
        if t.kind == "KEYWORD" and t.value in SOFT_KEYWORDS:
            self.next()
            return A.ColRef(t.value)
        raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

    def _case(self) -> A.Node:
        self.expect_kw("case")
        branches: List[Tuple[A.Node, A.Node]] = []
        while self.eat_kw("when"):
            cond = self.expr()
            self.expect_kw("then")
            val = self.expr()
            branches.append((cond, val))
        otherwise = self.expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return A.CaseWhen(tuple(branches), otherwise)

    def _type_name(self) -> str:
        t = self.next()
        if t.kind not in ("IDENT", "KEYWORD"):
            raise SqlError(f"expected type name at {t.pos}")
        name = t.value.lower()
        if self.at_op("("):  # e.g. decimal(12, 2) — precision ignored
            self.next()
            while not self.at_op(")"):
                self.next()
            self.next()
        return name


def parse_sql(text: str) -> A.Node:
    import dataclasses
    p = Parser(text)
    ctes = []
    if p.eat_kw("with"):
        while True:
            name = p._ident().lower()
            p.eat_kw("as")
            p.expect_op("(")
            q = p.parse_query()
            p.expect_op(")")
            ctes.append((name, q))
            if not p.eat_op(","):
                break
    stmt = p.parse_query()
    if p.peek().kind != "EOF":
        t = p.peek()
        raise SqlError(f"trailing input at {t.pos}: {t.value!r}")
    if ctes:
        stmt = dataclasses.replace(stmt, ctes=tuple(ctes))
    return stmt
