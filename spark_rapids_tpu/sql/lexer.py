"""SQL tokenizer.

Reference role: the front end of the path the reference gets for free from
Spark's Catalyst parser (its benchmark suites feed raw SQL,
integration_tests/.../tpcds/TpcdsLikeSpark.scala:30). Hand-written: the
environment ships no SQL parser dependency.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class Token:
    kind: str          # KEYWORD | IDENT | NUMBER | STRING | OP | EOF
    value: str
    pos: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "exists", "between", "like", "is",
    "null", "case", "when", "then", "else", "end", "join", "inner", "left",
    "right", "full", "outer", "cross", "semi", "anti", "on", "distinct",
    "asc", "desc", "union", "all", "date", "interval", "extract", "cast",
    "substring", "true", "false", "for", "over", "partition", "rows",
    "unbounded", "preceding", "following", "current", "row", "rollup",
    "cube", "range", "with", "intersect", "except",
}

#: window/grouping words are NON-reserved (Spark keeps them usable as
#: identifiers): the parser falls back to identifier where one is expected
SOFT_KEYWORDS = {"over", "partition", "rows", "unbounded", "preceding",
                 "following", "current", "row", "rollup", "cube", "range"}

_OPS = ["<>", "!=", ">=", "<=", "||", "=", "<", ">", "(", ")", ",", "+",
        "-", "*", "/", ".", "%"]


class SqlError(ValueError):
    pass


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and text[i + 1] == "-":  # line comment
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if text[j] == "'":
                    if j + 1 < n and text[j + 1] == "'":  # escaped quote
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            if j >= n:
                raise SqlError(f"unterminated string literal at {i}")
            out.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (text[j].isdigit() or (text[j] == "." and not seen_dot)):
                seen_dot |= text[j] == "."
                j += 1
            # only treat '.' as part of the number when followed by a digit
            # (9. is valid SQL but 9.x is a qualified ref — not for numbers)
            out.append(Token("NUMBER", text[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            low = word.lower()
            out.append(Token("KEYWORD" if low in KEYWORDS else "IDENT",
                             low if low in KEYWORDS else word, i))
            i = j
            continue
        for op in _OPS:
            if text.startswith(op, i):
                out.append(Token("OP", op, i))
                i += len(op)
                break
        else:
            raise SqlError(f"unexpected character {c!r} at {i}")
    out.append(Token("EOF", "", n))
    return out
